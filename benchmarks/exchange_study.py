"""Multi-device exchange study — the first *measured* schedule evidence.

The reference characterized its data plane executor-to-executor on a
15-node cluster (README.md:7-19); real multi-chip hardware is not
available on this rig, so this study measures the exchange plane's
*scaling shape* two ways the rig does support:

1. **Single-process virtual-device meshes** (``--xla_force_host_
   platform_device_count=E``): step time + transfer counters for the
   all_to_all vs ring schedules at E in {2,4,8} and several bucket
   sizes, plus flat-vs-hierarchical ``(dcn, exec)`` sharding at E=8.
2. **Two-process ``jax.distributed``** (gloo over loopback TCP): the
   SAME ExchangeProgram on a global 8-device mesh spanning 2 processes
   x 4 devices — the multi-host code path (process-local shard
   construction, non-addressable accounting) executed for real.

Every record is labeled CPU-only: this box has ONE core, so absolute
GB/s says nothing about TPU ICI — what transfers across is the
schedule *shape* (a2a's single fused collective vs ring's E-1
dependent hops) and that the multi-host path runs at all. Correctness
is asserted per configuration (payload round-trip), so every number is
backed by a verified exchange, mirroring how the reference's 1.41x
came from a verified TeraSort run.

Usage:
    python benchmarks/exchange_study.py                 # full study -> EXCHANGE_r05.json
    python benchmarks/exchange_study.py --quick         # CI-sized subset, no file
    python benchmarks/exchange_study.py --stage-ab      # stage-level schedule A/B
                                                        #   -> BENCH_r08.json

The ``--stage-ab`` mode (DESIGN.md §22) measures one whole reduce
stage four ways on an in-process cluster — per-block device pull
(collective compiler off), compiled collective waves (pipeline depth
1), double-buffered pipelined waves (depth 2, wave_overlap_ms > 0
asserted), and fused fetch+merge — asserts all four land
byte-identical partitions, and
reports each against the exchange-loopback roofline measured on the
SAME mesh in the same process (``*_roofline_fraction`` fields)."""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
COORD = os.environ.get("SRT_EXCHANGE_COORD", "127.0.0.1:29791")


def _payload(src: int, dst: int, block: int) -> bytes:
    """Deterministic per-(src,dst) block, distinct lengths under the bucket."""
    n = max(1, (block // 2) + ((37 * src + 11 * dst) % (block // 2)))
    return bytes([(src * 16 + dst) % 251]) * n


def _build_send(e: int, block: int):
    import numpy as np

    from sparkrdma_tpu.ops.exchange import pack_blocks

    rows, counts = [], []
    for src in range(e):
        slab, cnt = pack_blocks(
            [_payload(src, dst, block) for dst in range(e)], block
        )
        rows.append(slab)
        counts.append(cnt)
    return np.concatenate(rows, axis=0), np.concatenate(counts, axis=0)


# ----------------------------------------------------------------------
# child: one (E, topology) mesh, all schedules x blocks, one JSON line
# ----------------------------------------------------------------------
def run_child(e: int, num_slices: int, blocks, reps: int) -> None:
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    from sparkrdma_tpu.ops.exchange import ExchangeProgram, unpack_blocks
    from sparkrdma_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= e, "device farm came up short"
    mesh = make_mesh(jax.devices()[:e], num_slices=num_slices)
    topology = "hier" if num_slices > 1 else "flat"
    prog = ExchangeProgram(mesh)
    schedules = ["a2a"] if topology == "hier" else ["a2a", "ring"]
    records = []
    for block in blocks:
        send, counts = _build_send(e, block)
        for sched in schedules:
            fn = prog.exchange if sched == "a2a" else prog.ring_exchange
            recv, rcounts = fn(send, counts)  # warmup (compile) + verify
            r = np.asarray(recv).reshape(e, e, block)
            rc = np.asarray(rcounts).reshape(e, e)
            for dst in range(e):
                got = unpack_blocks(r[dst], rc[dst])
                want = [_payload(src, dst, block) for src in range(e)]
                assert got == want, f"corrupt exchange e={e} {sched} {block}"
            # counters are program-lifetime cumulative: snapshot after
            # the warmup/verify call so the record's deltas cover
            # exactly the `reps` timed steps of THIS config
            base = dict(prog.stats[sched])
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fn(send, counts)  # entry point blocks on completion
                times.append(time.perf_counter() - t0)
            s = prog.stats[sched]
            assert s["exchanges"] == base["exchanges"] + reps
            total = e * e * block
            med = statistics.median(times)
            records.append(
                {
                    "e": e,
                    "topology": topology,
                    "mesh_shape": dict(mesh.shape),
                    "schedule": sched,
                    "block_bytes": block,
                    "total_bytes_per_step": total,
                    "reps": reps,
                    "step_s_median": round(med, 6),
                    "step_s_min": round(min(times), 6),
                    "gbps_cpu_only": round(total / med / 1e9, 4),
                    "bytes_sent": s["bytes_sent"] - base["bytes_sent"],
                    "bytes_received": s["bytes_received"] - base["bytes_received"],
                    "bytes_received_valid": (
                        s["bytes_received_valid"] - base["bytes_received_valid"]
                    ),
                    "verified": True,
                }
            )
    print("RESULT " + json.dumps(records), flush=True)


# ----------------------------------------------------------------------
# child: stage-level schedule A/B (per-block vs collective vs fused)
# ----------------------------------------------------------------------
def run_stage_ab_child(nblocks: int, block_bytes: int, reps: int) -> None:
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    from sparkrdma_tpu.ops.exchange import ExchangeProgram, round_bucket
    from sparkrdma_tpu.parallel.mesh import make_mesh
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    num_parts = 4
    shards = max(1, nblocks // num_parts)
    total = shards * num_parts * block_bytes

    conf = TpuShuffleConf({"tpu.shuffle.transport": "python"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex_map = TpuShuffleManager(conf, is_driver=False, executor_id="ab-map")
    ex_red = TpuShuffleManager(conf, is_driver=False, executor_id="ab-red")
    driver.register_shuffle(
        BaseShuffleHandle(
            shuffle_id=61, num_maps=1, partitioner=HashPartitioner(num_parts)
        )
    )
    io_map, io_red = DeviceShuffleIO(ex_map), DeviceShuffleIO(ex_red)
    try:
        rng = np.random.default_rng(7)
        windows, want = [], {p: [] for p in range(num_parts)}
        for _ in range(shards):
            data = {
                p: rng.integers(0, 256, block_bytes, np.uint8)
                for p in range(num_parts)
            }
            windows.append(io_map.stage_device_blocks(61, data))
            for p, arr in data.items():
                want[p].append(arr)
        io_map.publish_staged_batch(61, windows, num_map_outputs_each=1)
        want_sets = {
            p: sorted(a.tobytes() for a in want[p]) for p in range(num_parts)
        }

        def fetch(mode):
            got = io_red.fetch_device_blocks(
                61, 0, num_parts, timeout_s=120, fused=(mode == "fused")
            )
            for bufs in got.values():
                for b in bufs:
                    arr = getattr(b, "array", None)
                    if arr is not None:
                        jax.block_until_ready(arr)
            return got

        def free(got):
            for bufs in got.values():
                for b in bufs:
                    b.free()

        def verify(mode, got):
            for p in range(num_parts):
                if mode == "fused":
                    # one merged slab per pid: pin content by length +
                    # per-block membership (order is the merge order)
                    assert len(got[p]) == 1, f"{mode}: pid {p} not fused"
                    blob = bytes(got[p][0].read(0, got[p][0].length))
                    assert len(blob) == shards * block_bytes
                    for a in want[p]:
                        assert a.tobytes() in blob, f"{mode}: pid {p} corrupt"
                else:
                    have = sorted(
                        bytes(b.read(0, b.length)) for b in got[p]
                    )
                    assert have == want_sets[p], f"{mode}: pid {p} corrupt"

        # mode matrix is the A/B: the tuner would re-cut budgets
        # between reps and blur it, so it sits this bench out
        conf.set("tpu.shuffle.collective.autoTune", "false")
        from sparkrdma_tpu.obs import get_registry

        overlap_c = get_registry().counter(
            "collective.wave_overlap_ms", role="ab-red"
        )
        # a cut that forms several waves per stage — what the pipelined
        # mode needs in flight; the single-wave modes keep the default
        pipelined_cut = max(64 * 1024, round_bucket(total // 8))

        def run_mode(mode):
            conf.set(
                "tpu.shuffle.collective.enabled",
                "false" if mode == "per_block" else "true",
            )
            conf.set(
                "tpu.shuffle.collective.pipelineDepth",
                "2" if mode == "pipelined" else "1",
            )
            conf.set(
                "tpu.shuffle.collective.waveBytes",
                str(pipelined_cut) if mode == "pipelined" else "64m",
            )
            warm = fetch(mode)  # warmup: compile + correctness gate
            verify(mode, warm)
            free(warm)
            o0 = overlap_c.value
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                got = fetch(mode)
                times.append(time.perf_counter() - t0)
                free(got)
            med = statistics.median(times)
            return {
                "step_s_median": round(med, 6),
                "step_s_min": round(min(times), 6),
                "gbps_cpu_only": round(total / med / 1e9, 4),
                "overlap_ms": round(overlap_c.value - o0, 3),
                "verified": True,
            }

        modes = {
            m: run_mode(m)
            for m in ("per_block", "collective", "pipelined", "fused")
        }
        conf.set("tpu.shuffle.collective.enabled", "true")
        # the pipelining A/B proof: depth 1 cannot overlap by
        # construction, depth 2 must (issue while consume runs)
        assert modes["collective"]["overlap_ms"] == 0.0, (
            "depth-1 collective recorded overlap"
        )
        assert modes["pipelined"]["overlap_ms"] > 0.0, (
            "depth-2 pipelined mode recorded no overlap"
        )

        # exchange-loopback roofline on the SAME mesh, same process:
        # the compiled collective's ceiling is what one fused exchange
        # step moves per second at this bucket size
        mesh = make_mesh(jax.devices()[:8])
        prog = ExchangeProgram(mesh)
        e = prog.num_shards
        bucket = round_bucket(block_bytes)
        send = np.zeros((e * e, bucket), np.uint8)
        counts = np.full((e * e,), bucket, np.int32)
        prog.exchange(send, counts)  # compile
        rtimes = []
        for _ in range(reps):
            t0 = time.perf_counter()
            prog.exchange(send, counts)
            rtimes.append(time.perf_counter() - t0)
        rmed = statistics.median(rtimes)
        roof_gbps = e * e * bucket / rmed / 1e9

        per_block = modes["per_block"]["gbps_cpu_only"]
        record = {
            "metric": "stage_schedule_ab",
            "unit": "GB/s (CPU-only; shapes transfer, absolutes do not)",
            "num_blocks": shards * num_parts,
            "block_bytes": block_bytes,
            "num_partitions": num_parts,
            "total_bytes_per_stage": total,
            "reps": reps,
            "per_block_pull": modes["per_block"],
            "compiled_collective": modes["collective"],
            "pipelined_collective": modes["pipelined"],
            "fused_fetch_merge": modes["fused"],
            "pipeline_depth": 2,
            "pipelined_wave_bytes": pipelined_cut,
            "pipelined_overlap_ms": modes["pipelined"]["overlap_ms"],
            "exchange_loopback_gbps": round(roof_gbps, 4),
            "collective_roofline_fraction": round(
                modes["collective"]["gbps_cpu_only"] / roof_gbps, 4
            ),
            "pipelined_roofline_fraction": round(
                modes["pipelined"]["gbps_cpu_only"] / roof_gbps, 4
            ),
            "fused_roofline_fraction": round(
                modes["fused"]["gbps_cpu_only"] / roof_gbps, 4
            ),
            "collective_speedup_vs_per_block": round(
                modes["collective"]["gbps_cpu_only"] / max(per_block, 1e-9), 3
            ),
            "pipelined_speedup_vs_per_block": round(
                modes["pipelined"]["gbps_cpu_only"] / max(per_block, 1e-9), 3
            ),
            "fused_speedup_vs_per_block": round(
                modes["fused"]["gbps_cpu_only"] / max(per_block, 1e-9), 3
            ),
            "byte_identical_across_paths": True,
            "note": (
                "CPU loopback: per-block pull pays no per-block "
                "issue/DMA latency here, so the amortization the "
                "collective exists for (BENCH_r05's ~20x exchange-vs-"
                "host gap) cannot show in the speedup column on this "
                "rig. What transfers: byte identity across all four "
                "paths, the depth-2 overlap counter going positive "
                "while depth 1 stays zero, the roofline fractions vs "
                "the same-mesh exchange, and the compile-once "
                "wave/program shapes."
            ),
        }
        print("RESULT " + json.dumps(record), flush=True)
    finally:
        io_red.stop()
        io_map.stop()
        ex_red.stop()
        ex_map.stop()
        driver.stop()


# ----------------------------------------------------------------------
# child: one rank of the 2-process jax.distributed run
# ----------------------------------------------------------------------
def run_dist_child(pid: int, nprocs: int, block: int, reps: int) -> None:
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(COORD, num_processes=nprocs, process_id=pid)
    from jax.sharding import NamedSharding

    from sparkrdma_tpu.ops.exchange import ExchangeProgram, unpack_blocks
    from sparkrdma_tpu.parallel.mesh import make_mesh, shard_spec

    e = len(jax.devices())  # global device count across processes
    local = len(jax.local_devices())
    mesh = make_mesh(jax.devices())
    prog = ExchangeProgram(mesh)
    sharding = NamedSharding(mesh, shard_spec(mesh))

    send_np, counts_np = _build_send(e, block)
    # multi-host construction: each process contributes ONLY the rows
    # its local devices hold (global row-shard d lives on device d)
    lo, hi = pid * local * e, (pid + 1) * local * e
    send = jax.make_array_from_process_local_data(
        sharding, send_np[lo:hi], send_np.shape
    )
    counts = jax.make_array_from_process_local_data(
        sharding, counts_np[lo:hi], counts_np.shape
    )

    recv, rcounts = prog.exchange(send, counts)  # warmup + verify below
    assert not recv.is_fully_addressable  # the real multi-host path
    for shard, cshard in zip(recv.addressable_shards, rcounts.addressable_shards):
        dst = shard.index[0].start // e
        got = unpack_blocks(
            np.asarray(shard.data), np.asarray(cshard.data)
        )
        want = [_payload(src, dst, block) for src in range(e)]
        assert got == want, f"rank {pid}: corrupt rows for dst {dst}"

    base = dict(prog.stats["a2a"])  # exclude warmup/verify traffic
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        prog.exchange(send, counts)
        times.append(time.perf_counter() - t0)
    s = prog.stats["a2a"]
    if pid == 0:
        total = e * e * block
        med = statistics.median(times)
        print(
            "RESULT "
            + json.dumps(
                {
                    "processes": nprocs,
                    "local_devices_per_process": local,
                    "e": e,
                    "schedule": "a2a",
                    "block_bytes": block,
                    "total_bytes_per_step": total,
                    "reps": reps,
                    "step_s_median": round(med, 6),
                    "gbps_cpu_only": round(total / med / 1e9, 4),
                    # receive accounting from LOCAL shards only (the
                    # non-addressable branch of ExchangeProgram._account),
                    # as a delta over exactly the `reps` timed steps
                    "bytes_received_valid_local": (
                        s["bytes_received_valid"] - base["bytes_received_valid"]
                    ),
                    "verified": True,
                }
            ),
            flush=True,
        )
    jax.distributed.shutdown()


# ----------------------------------------------------------------------
# parent: orchestrate subprocesses, aggregate, write the artifact
# ----------------------------------------------------------------------
def _spawn_child(args, devcount: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # keep inherited XLA flags but OWN the device count: a stale
    # --xla_force_host_platform_device_count (e.g. pytest's conftest
    # farm of 8) must not fight the one this child needs
    kept = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={devcount}"]
    )
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env,
        cwd=ROOT,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,  # surfaced in errors when a child dies
        text=True,
    )


def _result_line(out: str):
    for line in out.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"child produced no RESULT line:\n{out[-2000:]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="CI subset, no artifact")
    ap.add_argument(
        "--reps", type=int, default=21,
        help="timed steps per config (median reported). 21 is the "
             "canonical artifact setting: 7-rep runs on this shared "
             "rig were noisy enough to fake a schedule crossover",
    )
    ap.add_argument("--out", default=os.path.join(ROOT, "EXCHANGE_r05.json"))
    ap.add_argument(
        "--stage-ab", action="store_true",
        help="stage-level schedule A/B (per-block vs collective vs "
             "pipelined vs fused, DESIGN.md §22) -> BENCH_r08.json",
    )
    ap.add_argument(
        "--stage-out", default=os.path.join(ROOT, "BENCH_r08.json"))
    ap.add_argument("--child", nargs=4, metavar=("E", "SLICES", "BLOCKS", "REPS"))
    ap.add_argument("--dist-child", nargs=4, metavar=("PID", "NPROCS", "BLOCK", "REPS"))
    ap.add_argument(
        "--stage-child", nargs=3, metavar=("NBLOCKS", "BLOCK", "REPS"))
    args = ap.parse_args()

    if args.stage_child:
        nblocks, block, reps = (int(x) for x in args.stage_child)
        run_stage_ab_child(nblocks, block, reps)
        return
    if args.stage_ab:
        nblocks, block = (8, 65536) if args.quick else (32, 262144)
        reps = 3 if args.quick else max(7, args.reps // 3)
        p = _spawn_child(
            ["--stage-child", str(nblocks), str(block), str(reps)], 8
        )
        out, err = p.communicate(timeout=1200)
        if p.returncode != 0:
            raise RuntimeError(f"stage-ab child rc={p.returncode}:\n{err[-2000:]}")
        record = _result_line(out)
        artifact = {
            "label": (
                "Stage-level schedule A/B on the 8-virtual-device CPU "
                "mesh: per-block device pull vs compiled collective vs "
                "double-buffered pipelined waves vs fused fetch+merge, "
                "byte-identity asserted per mode, depth-2 overlap "
                "counter asserted positive, roofline = exchange "
                "loopback on the same mesh."
            ),
            "host": {"nproc": os.cpu_count(), "platform": sys.platform},
            "parsed": record,
        }
        print(json.dumps(artifact, indent=1))
        if not args.quick:
            with open(args.stage_out, "w") as f:
                json.dump(artifact, f, indent=1)
            print(f"wrote {args.stage_out}", file=sys.stderr)
        return

    if args.child:
        e, slices, blocks, reps = args.child
        run_child(int(e), int(slices), [int(b) for b in blocks.split(",")], int(reps))
        return
    if args.dist_child:
        pid, nprocs, block, reps = (int(x) for x in args.dist_child)
        run_dist_child(pid, nprocs, block, reps)
        return

    blocks = "16384,262144" if args.quick else "4096,65536,524288"
    reps = 3 if args.quick else args.reps
    meshes = (
        [(4, 1), (8, 1), (8, 2)]
        if args.quick
        else [(2, 1), (4, 1), (8, 1), (8, 2), (8, 4)]
    )
    single = []
    for e, slices in meshes:
        p = _spawn_child(["--child", str(e), str(slices), blocks, str(reps)], e)
        out, err = p.communicate(timeout=1200)
        if p.returncode != 0:
            raise RuntimeError(
                f"child (e={e}, slices={slices}) rc={p.returncode}:\n{err[-2000:]}"
            )
        single.extend(_result_line(out))
        print(f"mesh e={e} slices={slices}: done", file=sys.stderr)

    dist_block = 16384 if args.quick else 65536
    dist_reps = 3 if args.quick else args.reps
    procs = [
        _spawn_child(["--dist-child", str(pid), "2", str(dist_block), str(dist_reps)], 4)
        for pid in range(2)
    ]
    # drain both children CONCURRENTLY: they form one jax.distributed
    # pair, so blocking on child 0 while child 1 fills its piped stderr
    # (gloo chatter can exceed the pipe buffer) would deadlock the run
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(len(procs)) as tp:
        results = list(tp.map(lambda p: p.communicate(timeout=1200), procs))
    for pid, p in enumerate(procs):
        if p.returncode != 0:
            raise RuntimeError(
                f"dist child {pid} rc={p.returncode}:\n{results[pid][1][-2000:]}"
            )
    dist = _result_line(results[0][0])
    print("distributed 2-process run: done", file=sys.stderr)

    # schedule comparison at a glance: ring/a2a step-time ratio per config
    compare = []
    flat = [r for r in single if r["topology"] == "flat"]
    for e in sorted({r["e"] for r in flat}):
        for b in sorted({r["block_bytes"] for r in flat}):
            a2a = next(
                (r for r in flat if r["e"] == e and r["block_bytes"] == b
                 and r["schedule"] == "a2a"), None)
            ring = next(
                (r for r in flat if r["e"] == e and r["block_bytes"] == b
                 and r["schedule"] == "ring"), None)
            if a2a and ring:
                compare.append(
                    {
                        "e": e,
                        "block_bytes": b,
                        "ring_over_a2a_step_ratio": round(
                            ring["step_s_median"] / a2a["step_s_median"], 3
                        ),
                    }
                )

    artifact = {
        "label": (
            "CPU-only: virtual-device meshes on a 1-core host. Schedule "
            "SHAPES and the multi-host code path transfer to TPU; "
            "absolute GB/s does not (no ICI here). Every record is "
            "correctness-verified payload round-trip."
        ),
        "host": {"nproc": os.cpu_count(), "platform": sys.platform},
        "single_process": single,
        "schedule_comparison": compare,
        "two_process_distributed": dist,
    }
    print(json.dumps(artifact, indent=1))
    if not args.quick:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
