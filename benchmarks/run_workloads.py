"""Workload benchmark suite — the HiBench role (SURVEY.md §6).

Runs the BASELINE.md workload set against this framework and prints one
JSON line per workload (and, with --out, writes them all to a committed
artifact — WORKLOADS_r{N}.json — so regressions are visible
round-over-round):

  1. TeraSort via the HOST engine (full shuffle path: writers,
     registered memory, one-sided READs, fetcher) — BASELINE config #1
     shape, scaled by --scale.
  2. TeraSort via the DEVICE plane (partition -> all_to_all -> merge).
  3. PageRank (multi-round all-to-all).
  4. ALS (iterative wide shuffle).
  5. Hash join (shuffle-heavy join).
  6. Transformer training throughput (ulysses attention through the
     Pallas flash kernel fwd+bwd; K steps in one executable).
  7. With --e2e-gb G: END-TO-END TeraSort of G GiB through the WHOLE
     stack — host map sorts -> range split -> publish into registered
     memory -> driver location protocol -> one-sided native READs ->
     HBM staging -> device merge — verified on-device (sortedness +
     order-invariant checksums vs the host input) and phase-timed
     against the stock single-host ``np.sort`` baseline (the
     reference's 1.41x comparison shape, README.md:7-19).

Usage: python benchmarks/run_workloads.py [--scale 0.05]
         [--transport native] [--e2e-gb 1.0] [--out WORKLOADS_r04.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RECORDS = []


def report(workload, seconds, **extra):
    rec = {"workload": workload, "seconds": round(seconds, 4), **extra}
    RECORDS.append(rec)
    print(json.dumps(rec), flush=True)


def bench_engine_terasort(scale: float, transport: str):
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n = int(1_000_000 * scale)  # records of ~100B => scale * 100MB
    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint64)

    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        data = [(int(k), b"x" * 90) for k in keys]
        t0 = time.perf_counter()
        rdd = ctx.parallelize(data, 8).sort_by_key(num_partitions=8)
        out = ctx.run_job(rdd)
        dt = time.perf_counter() - t0
        bd = ctx.last_breakdown  # critical-path verdict (obs/critpath.py)
    assert len(out) == n
    assert all(out[i][0] <= out[i + 1][0] for i in range(min(1000, n - 1)))
    report(
        "terasort_engine", dt,
        records=n, transport=transport,
        mb=round(n * 100 / 1e6, 1),
        records_per_s=int(n / dt),
        breakdown=bd.to_dict() if bd is not None else None,
    )


def bench_device_terasort(scale: float):
    import jax

    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int((1 << 24) * scale * 20)  # default scale 0.05 -> 16M keys
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    sorter = TeraSorter(make_mesh())
    sorter.sort(keys)  # warm: compile at the real shape
    t0 = time.perf_counter()
    out = sorter.sort(keys)
    dt = time.perf_counter() - t0
    assert len(out) == n
    report(
        "terasort_device", dt,
        keys=n, devices=len(jax.devices()),
        e2e_gbps_incl_transfers=round(n * 4 / dt / 1e9, 3),
        note=(
            "wall time includes host->device and device->host of every "
            "byte; on this rig those ride the axon tunnel (~15 MB/s "
            "readback) and dominate — bench.py's device_sort_gbps is "
            "the on-chip rate of the same step"
        ),
    )


def bench_e2e_terasort(gb: float, transport: str, reducers: int = 8,
                       executors: int = 2, device_fetch: bool = True):
    """One measured TeraSort with the WHOLE framework in the loop.

    Map side plays Spark's part (host sorts, as the reference leaves to
    Spark's sort writers); everything after — registered-memory
    publish, driver location RPC, one-sided READs, HBM staging, device
    merge — is this framework. Output is verified WITHOUT bulk
    device->host readback (order-invariant xor/sum checksums + an
    on-device sortedness reduction), because bulk readback on this rig
    measures the axon tunnel, not the framework (see bench.py)."""
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.ops.sort import device_sort
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n = int(gb * (1 << 30)) // 4
    n -= n % executors
    rng = np.random.default_rng(12)
    shards = [
        rng.integers(0, 1 << 32, n // executors, dtype=np.uint32)
        for _ in range(executors)
    ]

    # stock role: one host np.sort over everything (what the reference's
    # baseline ran as Spark's sort shuffle on one node)
    t0 = time.perf_counter()
    host_sorted = np.sort(np.concatenate(shards))
    t_host = time.perf_counter() - t0
    del host_sorted  # multiset checks below; bytes never compared bulk

    # expected per-reducer order-invariant checksums from the INPUT
    edges = np.asarray(
        [(r * (1 << 32)) // reducers for r in range(1, reducers)], np.uint32
    )
    exp_sum = np.zeros(reducers, np.uint32)
    exp_xor = np.zeros(reducers, np.uint32)
    exp_cnt = np.zeros(reducers, np.int64)
    for sh in shards:
        dest = np.searchsorted(edges, sh, side="right")
        for r in range(reducers):
            sel = sh[dest == r]
            exp_cnt[r] += len(sel)
            with np.errstate(over="ignore"):
                exp_sum[r] += sel.sum(dtype=np.uint32)
            exp_xor[r] ^= np.bitwise_xor.reduce(sel) if len(sel) else np.uint32(0)

    # device_fetch=False pins the HOST transport plane under test: in
    # this single-process harness every executor's arena is
    # mesh-visible, so the device plane would otherwise pull every
    # remote block HBM->HBM and the host plane would idle (DESIGN.md
    # §17 — exactly what it should do in production, but not what a
    # transport benchmark wants)
    conf = TpuShuffleConf({
        "tpu.shuffle.transport": transport,
        "tpu.shuffle.deviceFetch.enabled": str(device_fetch).lower(),
    })
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [
        TpuShuffleManager(conf, is_driver=False, executor_id=f"e2e-{i}")
        for i in range(executors)
    ]
    handle = BaseShuffleHandle(
        shuffle_id=99, num_maps=executors, partitioner=HashPartitioner(reducers)
    )
    driver.register_shuffle(handle)
    ios = [DeviceShuffleIO(ex) for ex in execs]
    phases = {}
    try:
        # --- map side: the PIPELINED DEVICE-ACCELERATED map plane ------
        # WORKLOADS_r05 pinned the e2e loss here: sequential host
        # np.sort + publish walled 22.95 s. Two structural fixes ride
        # together (DESIGN.md "Pipelined map plane"):
        #   1. the O(N log N) sort runs ON DEVICE (MapShardSorter: one
        #      device_sort + device-side searchsorted per shard; the
        #      host never sorts),
        #   2. sort -> stage -> publish run as a bounded three-stage
        #      pipeline (MapTaskPipeline), so shard k+1 sorts while
        #      shard k stages into registered memory and shard k-1's
        #      locations upload.
        # Busy times per stage come from the pipeline report; the wall
        # is what counts. conf map.deviceSort=false falls back to the
        # host sort inside the same pipeline (stage/publish overlap
        # still applies).
        from sparkrdma_tpu.models import MapShardSorter
        from sparkrdma_tpu.shuffle.writer.pipeline import MapTaskPipeline

        keep0 = {}  # executor 0's sorted output, reused by the solo probe
        use_device_sort = bool(conf.map_device_sort)
        shard_sorter = MapShardSorter() if use_device_sort else None
        t0 = time.perf_counter()
        if shard_sorter is not None:
            shard_sorter.warm(n // executors, len(edges))
        map_compile_s = time.perf_counter() - t0

        def sort_shard(i):
            if shard_sorter is not None:
                local, bounds = shard_sorter.sort_partition(shards[i], edges)
            else:
                local = np.sort(shards[i])
                bounds = np.concatenate(
                    [[0], np.searchsorted(local, edges), [len(local)]]
                )
            if i == 0:
                keep0["local"], keep0["bounds"] = local, bounds
            return local, bounds

        def stage_shard(i, sorted_out):
            local, bounds = sorted_out
            return ios[i].stage_device_blocks(
                99,
                {r: local[bounds[r]: bounds[r + 1]] for r in range(reducers)},
            )

        def publish_shard(i, locs):
            ios[i].publish_staged(99, locs, num_map_outputs=1)

        pipe = MapTaskPipeline(
            sort_shard, stage_shard, publish_shard,
            parallelism=conf.map_parallelism,
            depth=conf.map_pipeline_depth,
            role="e2e-map",
        )
        pipe_report = pipe.run(range(executors))
        phases["map_publish_wall_s"] = pipe_report.wall_s

        # publish cost measured UNCONTENDED (solo re-publish of
        # executor 0's retained sorted output to a throwaway shuffle
        # id): busy timers under the pipelined phase inflate with CPU
        # contention against the sorts (1-core rig) and wall-minus-busy
        # arithmetic breaks on multi-core — a direct solo measurement
        # is right on both topologies
        local0, bounds0 = keep0["local"], keep0["bounds"]
        ts = time.perf_counter()
        ios[0].publish_device_blocks(
            98, {r: local0[bounds0[r]: bounds0[r + 1]] for r in range(reducers)}
        )
        publish_solo = time.perf_counter() - ts
        ios[0].unpublish(98)
        keep0.clear()
        del local0

        # --- reduce side: READ -> stage -> device merge ----------------
        # Blocks arrive STAGED AS uint32 (fetch dtype) — a uint8 slab
        # would force on-device byte->word assembly, whose [..., 4]-minor
        # reshape the TPU tiled layout pads 4->128 (measured: a 32 GiB
        # HBM allocation for a 1 GiB input). jit's own dispatch cache
        # handles per-shape retracing.
        @jax.jit
        def merge(arrs, word_counts):
            stacked_u32 = jnp.stack(arrs)
            _, words = stacked_u32.shape
            iota = jnp.arange(words, dtype=jnp.int32)[None, :]
            masked = jnp.where(
                iota < word_counts[:, None], stacked_u32,
                jnp.uint32(0xFFFFFFFF),
            )
            merged = device_sort(masked.reshape(-1))
            t = word_counts.sum().astype(jnp.uint32)
            vi = jnp.arange(merged.shape[0], dtype=jnp.int32)
            mm = jnp.where(vi < t, merged, jnp.uint32(0))
            csum = mm.sum(dtype=jnp.uint32)
            cxor = jax.lax.reduce(
                mm, jnp.uint32(0), jax.lax.bitwise_xor, (0,)
            )
            ok = jnp.all(merged[1:] >= merged[:-1]).astype(jnp.uint32)
            # ONE packed scalar vector -> one host readback per
            # reducer (each sync pays full tunnel latency)
            return merged, jnp.stack([t, csum, cxor, ok])

        # warm the merge executable at the expected slab shape (compile
        # is the JVM-startup analogue the reference's numbers exclude)
        from sparkrdma_tpu.ops.hbm_arena import MIN_BLOCK_SIZE, _size_class

        # Warm every executable the timed loop can hit (compile is the
        # JVM-startup analogue the reference's numbers exclude). The
        # mean block size can sit ON a size-class boundary, so blocks
        # land in TWO adjacent classes: warm the merge at both
        # homogeneous shapes AND the small->large pad used when one
        # reducer's blocks mix classes.
        mean_block = int(n / executors / reducers * 4)
        cls_hi = _size_class(int(mean_block * 1.05)) // 4
        cls_lo = max(_size_class(MIN_BLOCK_SIZE) // 4, cls_hi // 2)
        t0 = time.perf_counter()
        for cw in {cls_hi, cls_lo}:
            jax.block_until_ready(
                merge(
                    tuple(jnp.zeros((cw,), jnp.uint32)
                          for _ in range(executors)),
                    jnp.full((executors,), cw, jnp.int32),
                )[0]
            )
        if cls_lo != cls_hi:
            jax.block_until_ready(
                jnp.zeros((cls_hi,), jnp.uint32)
                .at[:cls_lo]
                .set(jnp.zeros((cls_lo,), jnp.uint32))
            )
        phases_compile = time.perf_counter() - t0

        # impute the merge's ON-CHIP time: K chained device_sorts at
        # the merge shape inside ONE executable, differenced against a
        # 1-step chain — the only timing that survives the tunnel
        # (bench.py methodology). The merge is the sort plus cheap
        # elementwise masking, so this bounds its real compute from
        # below; device_merge_busy_s minus this is tunnel dispatch +
        # readback latency, MEASURED rather than asserted.
        from functools import partial

        from sparkrdma_tpu.ops.sort import device_sort as _dsort

        @partial(jax.jit, static_argnums=(1,))
        def sort_chain(v, k):
            def body(i, v):
                return _dsort(v ^ i.astype(jnp.uint32))

            return jax.lax.fori_loop(0, k, body, v)

        probe = jnp.zeros((executors * cls_hi,), jnp.uint32)
        jax.block_until_ready(sort_chain(probe, 1))
        jax.block_until_ready(sort_chain(probe, 9))

        def _timed_chain(k, reps=3):
            best = float("inf")
            for _ in range(reps):
                ts = time.perf_counter()
                jax.block_until_ready(sort_chain(probe, k))
                best = min(best, time.perf_counter() - ts)
            return best

        # bench.py _chained_ms discipline: differencing cancels
        # dispatch, but rig jitter can eat the difference — retry, then
        # fall back to the dispatch-INCLUSIVE per-step time (an
        # over-estimate of compute, hence conservative for the
        # ex-tunnel claim) rather than silently imputing zero compute
        for _ in range(2):
            t_hi = _timed_chain(9)
            delta = t_hi - _timed_chain(1)
            if delta > 0:
                per_merge_on_chip = delta / 8
                break
        else:
            per_merge_on_chip = t_hi / 9
        merge_on_chip_total = per_merge_on_chip * reducers

        # fetch/compute overlap (SURVEY §2.3, DESIGN.md §16): the
        # reduce side runs on the ReduceTaskPipeline — group READs for
        # reducer k+2 in flight while k+1's checksum verify runs on the
        # decode pool, k's host->HBM staging rides under k-1's device
        # merge (double-buffered staging). r05's 1-deep prefetch loop
        # fused transport+verify+stage into one blocking call; the
        # split-phase DeviceShuffleIO API lets each plane's busy clock
        # tick on its own pipeline stage.
        from sparkrdma_tpu.shuffle.reader.pipeline import ReduceTaskPipeline

        reducer_io = ios[0]

        def fetch_blocks(r):
            got = reducer_io.fetch_host_blocks(
                99, r, r + 1, timeout_s=120, dtype=np.uint32
            )
            return got.get(r, [])

        def verify_blocks(_r, blocks):
            return [reducer_io.verify_host_block(hb) for hb in blocks]

        def stage_blocks(_r, blocks):
            return [
                reducer_io.stage_host_block(hb, dtype=np.uint32)
                for hb in blocks
            ]

        def merge_group(_r, bufs):
            # pin the set device-resident across the direct .array
            # access (no-op unless HBM pressure spilled some; members
            # are never victims while pinned)
            with reducer_io.device_buffers.pinned_on_device(bufs):
                cap = max(b.array.shape[0] for b in bufs)
                arrs = tuple(
                    b.array
                    if b.array.shape[0] == cap
                    else jnp.zeros((cap,), jnp.uint32)
                    .at[: b.array.shape[0]]
                    .set(b.array)
                    for b in bufs
                )
                counts = jnp.asarray(
                    [b.length // 4 for b in bufs], jnp.int32
                )
                merged, packed = merge(arrs, counts)
            jax.block_until_ready(merged)
            for b in bufs:
                b.free()
            return packed  # tiny, stays on device

        def discard_group(stage, _item, value):
            # abort drain: host blocks release, device slabs free;
            # merge outputs (packed scalar rows) hold no resources
            if not value:
                return
            if stage in ("fetch", "decode"):
                for hb in value:
                    hb.release()
            elif stage == "stage":
                for b in value:
                    b.free()

        pipe = ReduceTaskPipeline(
            fetch_blocks, verify_blocks, stage_blocks, merge_group,
            parallelism=conf.reduce_parallelism,
            depth=conf.reduce_pipeline_depth,
            double_buffer=conf.reduce_double_buffer_staging,
            role="e2e-reduce",
            discard_fn=discard_group,
        )
        t_wall0 = time.perf_counter()
        # Verification scalars stay ON DEVICE until every merge is done,
        # then come back in ONE batched readback. Measured on this rig
        # (DESIGN.md §13): reading back ANY output of a large program
        # flips the axon runtime into a mode where the NEXT host->HBM
        # transfer stalls 13-25 s — interleaved per-reducer readbacks
        # were 7x-ing the whole fetch/stage plane (150-200 s of stalls
        # at 1 GiB). Deferring the readbacks pays that cost once.
        reduce_report = pipe.run(range(reducers))
        packed_rows = reduce_report.results
        # ONE readback for all reducers: [count, sum, xor, sorted] rows
        t0 = time.perf_counter()
        stats = np.asarray(jax.device_get(jnp.stack(packed_rows)))
        t_readback = time.perf_counter() - t0
        for r in range(reducers):
            t, csum, cxor, ok = (int(x) for x in stats[r])
            if t != exp_cnt[r]:
                raise SystemExit(
                    f"E2E FAILED: reducer {r} count {t} != {exp_cnt[r]}"
                )
            if csum != int(exp_sum[r]) or cxor != int(exp_xor[r]):
                raise SystemExit(f"E2E FAILED: reducer {r} checksum mismatch")
            if not ok:
                raise SystemExit(f"E2E FAILED: reducer {r} output not sorted")
        reduce_wall = time.perf_counter() - t_wall0
        # only wall time counts toward the total; per-plane busy times
        # are informational (they overlap)
        phases["reduce_wall_s"] = reduce_wall
        rbusy = reduce_report.stage_busy_s
        t_fetch = rbusy["fetch"] + rbusy["stage"]
        t_merge = rbusy["merge"]
        extra_busy = {
            "fetch_stage_busy_s": round(t_fetch, 3),
            "framework_decode_busy_s": round(rbusy["decode"], 3),
            "device_merge_busy_s": round(t_merge, 3),
            "verify_readback_s": round(t_readback, 3),
            "overlap_saved_s": round(reduce_report.overlap_s, 3),
            "reduce_pipeline_overlap_saved_s": round(
                reduce_report.overlap_s, 3
            ),
        }
        t_merge_final = t_merge
        # live observability counters (pool allocs, read-path split,
        # fetch histograms, HBM budget/spills) into the artifact
        metrics = reducer_io.metrics_snapshot()
    finally:
        for io in ios:
            io.stop()
        for ex in execs:
            ex.stop()
        driver.stop()

    total = sum(phases.values())
    # tunnel-vs-framework attribution, measured not asserted:
    #   framework = publish + fetch transport (bytes arriving in host
    #     memory: RPC, one-sided READ, mmap/pread) — what this
    #     framework ADDS over a plain sort pipeline;
    #   compute   = host map sorts + the merge's imputed ON-CHIP time
    #     (the work the baseline's np.sort also had to do);
    #   tunnel    = host->HBM staging + merge dispatch/readback beyond
    #     on-chip time — the rig's accelerator link, not framework.
    ft = float(metrics.get("fetch_transport_s", 0.0))
    fs = float(metrics.get("fetch_stage_s", 0.0))
    tunnel_merge = max(t_merge_final - merge_on_chip_total, 0.0)
    # publish cost: the solo uncontended measurement scaled to all
    # executors (see above). Busy timers from the pipelined phase stay
    # in the table, labeled contended, for transparency.
    publish_uncontended = publish_solo * executors
    # reduce-side residual: wall not accounted to either plane's busy
    # clock or the batched verify readback (scheduling gaps, Python
    # orchestration)
    reduce_residual = max(
        phases["reduce_wall_s"]
        - extra_busy["fetch_stage_busy_s"]
        - extra_busy["framework_decode_busy_s"]
        - t_merge_final
        - extra_busy["verify_readback_s"],
        0.0,
    )
    attribution = {
        "compute_map_sort_busy_s": round(
            pipe_report.stage_busy_s["sort"], 3
        ),
        "compute_merge_on_chip_s_imputed": round(merge_on_chip_total, 3),
        "framework_map_stage_busy_s": round(
            pipe_report.stage_busy_s["stage"], 3
        ),
        "framework_publish_uncontended_s": round(publish_uncontended, 3),
        "framework_publish_busy_s_contended": round(
            pipe_report.stage_busy_s["publish"], 3
        ),
        "map_pipeline_overlap_saved_s": round(pipe_report.overlap_s, 3),
        "framework_fetch_transport_s": round(ft, 3),
        "framework_reduce_residual_s": round(reduce_residual, 3),
        "tunnel_fetch_stage_s": round(fs, 3),
        "tunnel_merge_dispatch_readback_s": round(tunnel_merge, 3),
        "tunnel_verify_readback_s": extra_busy["verify_readback_s"],
    }
    # the framework's OWN code (registration+publish+location RPC+READ
    # transport+orchestration residual): what the reference's plugin
    # adds over Spark's sort machinery — compare against
    # host_sort_baseline_s
    framework_attributable = publish_uncontended + ft + reduce_residual
    # ex-tunnel comparison: RECONSTRUCTED bottom-up from measured
    # non-tunnel components (subtracting overlapped busy clocks from a
    # wall would double-count their overlap — fetch staging and merge
    # dispatch run concurrently by design)
    ex_tunnel_total = (
        phases["map_publish_wall_s"]
        + ft
        + merge_on_chip_total
        + reduce_residual
    )
    report(
        "terasort_e2e", total,
        gb=round(n * 4 / (1 << 30), 3), transport=transport,
        reducers=reducers, executors=executors,
        host_sort_baseline_s=round(t_host, 3),
        vs_host_sort=round(t_host / total, 3),
        vs_host_sort_ex_tunnel=round(t_host / ex_tunnel_total, 3),
        framework_attributable_s=round(framework_attributable, 3),
        attribution=attribution,
        map_sorter=("device" if use_device_sort else "host"),
        map_parallelism=conf.map_parallelism,
        reduce_parallelism=conf.reduce_parallelism,
        reduce_pipeline_depth=conf.reduce_pipeline_depth,
        reduce_double_buffer=conf.reduce_double_buffer_staging,
        compile_warm_s=round(phases_compile + map_compile_s, 3),
        verified="count+sum+xor+sorted (on-device)",
        metrics=metrics,
        **extra_busy,
        note=(
            "attribution: framework_attributable_s is the framework's "
            "OWN code (uncontended publish + fetch transport + reduce "
            "orchestration residual — the role the reference's plugin "
            "plays over Spark's sort machinery); compute rows are work "
            "the baseline also does; tunnel rows are MEASURED host<->"
            "HBM staging and merge dispatch/readback beyond imputed "
            "on-chip time. vs_host_sort_ex_tunnel compares against an "
            "ex-tunnel wall RECONSTRUCTED from measured non-tunnel "
            "components (map+publish wall, fetch transport, on-chip "
            "merge, reduce residual) — subtracting overlapped busy "
            "clocks from the wall would double-count their overlap"
        ),
        **{k: round(v, 3) for k, v in phases.items()},
    )


def bench_device_terasort_skew(scale: float):
    """The adversarial TeraSort round (SURVEY §7.3(2)): zipf-skewed
    keys concentrate mass in a few range partitions, so the static
    bucket capacity overflows and the sorter retries with doubled
    capacity (terasort.py capacity doubling). This workload makes that
    strategy's cost a NUMBER next to the uniform round: extra
    executions + a recompile per new capacity (cached within the
    process and across runs via the persistent cache).

    Overflow requires E > 1 (at E=1 every key lands in the one bucket,
    which is sized to hold them all), so on a single-chip rig this
    self-provisions an 8-virtual-device CPU mesh in a child process —
    the dryrun_multichip strategy; the record is labeled CPU-only."""
    import subprocess

    import jax

    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) == 1 and not os.environ.get("_SRT_SKEW_CHILD"):
        env = dict(os.environ)
        env["_SRT_SKEW_CHILD"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        kept = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            kept + ["--xla_force_host_platform_device_count=8"]
        )
        # a failed/stuck child must not discard every other workload's
        # record: report the failure into the artifact and move on
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--only", "skew", "--scale", str(scale)],
                env=env, capture_output=True, text=True,
                timeout=max(900.0, 18000.0 * scale),
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"skew child rc={proc.returncode}:\n{proc.stderr[-2000:]}"
                )
            lines = [
                l for l in proc.stdout.splitlines()
                if '"terasort_device_skew"' in l
            ]
            if not lines:
                raise RuntimeError(
                    "skew child exited 0 without a record line; stderr:\n"
                    + proc.stderr[-2000:]
                )
        except (subprocess.TimeoutExpired, RuntimeError) as e:
            report("terasort_device_skew", -1, error=str(e)[:2000])
            return
        rec = json.loads(lines[-1])
        rec["platform"] = "cpu-8dev (overflow needs E>1; CPU-only timing)"
        RECORDS.append(rec)
        print(json.dumps(rec), flush=True)
        return

    # overflow needs several shards: at E=1 the one bucket is sized to
    # hold everything and the record would silently show no skew cost
    assert len(jax.devices()) > 1, (
        "skew bench requires a multi-device mesh; the CPU-farm child "
        "failed to materialize its 8 virtual devices"
    )

    n = int((1 << 24) * scale * 20)
    rng = np.random.default_rng(0)
    # zipf ranks mapped into the uint32 key space: heavy mass lands in
    # the lowest-range partitions (~a>1.5 concentrates >70% of keys in
    # the first percent of the key space)
    ranks = rng.zipf(1.5, size=n)
    keys = ((ranks % (1 << 16)) * 65536 + rng.integers(0, 65536, n)).astype(
        np.uint32
    )
    sorter = TeraSorter(make_mesh())

    out = sorter.sort(keys)  # warm: compiles base capacity AND retries
    assert len(out) == n
    doublings_warm = max(
        0, int(np.log2(max(k[1] for k in sorter._step_cache)
                       / min(k[1] for k in sorter._step_cache)))
    ) if len(sorter._step_cache) > 1 else 0
    t0 = time.perf_counter()
    out = sorter.sort(keys)
    dt_static = time.perf_counter() - t0
    assert all(out[i] <= out[i + 1] for i in range(0, min(2000, n - 1)))

    # adaptive control: sampled quantile edges + sampled capacity
    # (shuffle/planner.py plan_edges) replace the overflow-retry ladder
    out_ad = sorter.sort(keys, adaptive=True)  # warm adaptive executable
    assert len(out_ad) == n
    t0 = time.perf_counter()
    out_ad = sorter.sort(keys, adaptive=True)
    dt = time.perf_counter() - t0
    assert all(out_ad[i] <= out_ad[i + 1] for i in range(0, min(2000, n - 1)))

    # uniform control at the same n, same process (executables warm)
    uni = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    sorter.sort(uni)  # warm any uniform-shape executable
    t0 = time.perf_counter()
    sorter.sort(uni)
    dt_uni = time.perf_counter() - t0
    report(
        "terasort_device_skew", dt,
        keys=n, zipf_a=1.5,
        capacity_doublings=doublings_warm,
        uniform_control_s=round(dt_uni, 4),
        static_plan_s=round(dt_static, 4),
        skew_overhead_x=round(dt / dt_uni, 3) if dt_uni > 0 else None,
        skew_overhead_x_static=(
            round(dt_static / dt_uni, 3) if dt_uni > 0 else None
        ),
        devices=len(jax.devices()),
        note=(
            "primary timing = adaptive plan (sampled quantile edges, "
            "shuffle/planner.py) — one right-sized execution; "
            "skew_overhead_x_static = the pre-planner overflow-retry "
            "ladder at doubled bucket capacities (SURVEY §7.3(2))"
        ),
    )


def bench_transformer_train(scale: float):
    """Sharded transformer training throughput on one chip: K SGD
    steps (ulysses attention -> the Pallas flash kernel fwd + custom-
    VJP bwd) inside ONE executable, so the measurement is steady-state
    compute, not per-step dispatch through the tunnel."""
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.models.transformer_step import (
        TransformerStep,
        init_params,
        make_training_mesh,
    )

    mesh = make_training_mesh(jax.devices()[:1])
    heads, dhead = 8, 64
    d_model, d_hidden = heads * dhead, 4 * heads * dhead
    b = 4
    s = max(128, int(2048 * scale * 20))  # default scale 0.05 -> 2048
    params = init_params(d_model, n_heads=heads, d_hidden=d_hidden, tp=1)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))
    step = TransformerStep(mesh, n_heads=heads, lr=0.01, attn="ulysses")
    pl, xl, yl = step.place(params, x, y)

    def run(n):
        loss, _ = step.run_steps(pl, xl, yl, n)
        return float(loss)

    l1 = run(1)  # warm: compiles step + loop
    run(9)
    t0 = time.perf_counter()
    run(1)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    lk = run(9)
    tk = time.perf_counter() - t0
    if tk > t1:
        per_step = (tk - t1) / 8  # dispatch cancelled by differencing
    else:
        # timing noise ate the difference: fall back to the dispatch-
        # inclusive per-step time (conservative underestimate of
        # throughput) rather than reporting nonsense
        per_step = tk / 9
    assert np.isfinite(lk) and lk <= l1 * 1.01, "training diverged"
    # attention (fwd 1x + bwd 2.5x) + mlp/proj matmul flops per step
    att = 4 * b * heads * s * s * dhead * 3.5
    mlp = 2 * b * s * (4 * d_model * d_model + 2 * d_model * d_hidden) * 3
    report(
        "transformer_train", tk,
        steps_per_s=round(1.0 / per_step, 2),
        step_ms=round(per_step * 1e3, 2),
        tflops_effective=round((att + mlp) / per_step / 1e12, 2),
        b=b, s=s, d_model=d_model, heads=heads, attn="ulysses+flash_vjp",
        final_loss=round(lk, 5),
    )


def bench_pagerank(scale: float):
    from sparkrdma_tpu.models import PageRank
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int(20000 * scale * 20)
    m = n * 8
    rng = np.random.default_rng(0)
    edges = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    pr = PageRank(make_mesh())
    pr.run(edges, n, iters=10)  # warm compile
    t0 = time.perf_counter()
    ranks = pr.run(edges, n, iters=10)
    dt = time.perf_counter() - t0
    assert abs(ranks.sum() - 1.0) < 1e-2
    report("pagerank", dt, vertices=n, edges=m, iters=10)


def bench_als(scale: float):
    from sparkrdma_tpu.models import ALS
    from sparkrdma_tpu.models.als import rmse
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n_u = int(2000 * scale * 20)
    n_i = n_u // 2
    m = n_u * 10
    rng = np.random.default_rng(0)
    tu = rng.normal(size=(n_u, 4))
    tv = rng.normal(size=(n_i, 4))
    users = rng.integers(0, n_u, m)
    items = rng.integers(0, n_i, m)
    vals = (tu[users] * tv[items]).sum(1)
    ratings = np.stack([users, items, vals], 1)
    als = ALS(make_mesh(), rank=8)
    als.fit(ratings, n_u, n_i, iters=5)  # warm compile
    t0 = time.perf_counter()
    u, v = als.fit(ratings, n_u, n_i, iters=5)
    dt = time.perf_counter() - t0
    report("als", dt, users=n_u, items=n_i, ratings=m, rmse=round(rmse(u, v, ratings), 4))


def bench_hashjoin(scale: float):
    from sparkrdma_tpu.models import HashJoin
    from sparkrdma_tpu.parallel.mesh import make_mesh

    nb = int(10000 * scale * 20)
    npr = nb * 8
    rng = np.random.default_rng(0)
    bk = rng.choice(1 << 24, nb, replace=False).astype(np.uint32)
    bv = rng.integers(0, 1 << 20, nb).astype(np.int32)
    pk = rng.choice(bk, npr).astype(np.uint32)
    pv = np.arange(npr, dtype=np.int32)
    hj = HashJoin(make_mesh())
    hj.join(bk, bv, pk, pv)  # warm compile
    t0 = time.perf_counter()
    out = hj.join(bk, bv, pk, pv)
    dt = time.perf_counter() - t0
    assert len(out) == npr
    report("hashjoin", dt, build=nb, probe=npr, rows_per_s=int(npr / dt))


def bench_analytic_scan(scale: float):
    """Analytic column scan over shuffled blocks (DESIGN.md §25): the
    same typed record set staged through both block encodings, then one
    full-column aggregate (sum of the value column) consumed straight
    off the framed partition stream. The columnar side decodes via
    zero-copy ``np.frombuffer`` views and reduces vectorized; the
    pickle side must materialize every row tuple first — the decode
    delta IS the workload, so both scans run on one core and the row
    reports both times plus the speedup. Results are asserted equal."""
    import io

    from sparkrdma_tpu.engine.serializer import (
        CompressionCodec,
        PickleSerializer,
        frame_compressed,
        iter_compressed_blocks,
    )
    from sparkrdma_tpu.shuffle import columnar
    from sparkrdma_tpu.shuffle.writer.columnar import ColumnarPartitionWriter

    n = int(4_000_000 * scale * 20)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    vals = rng.integers(0, 1 << 30, n, dtype=np.int64)
    records = [(k, v) for k, v in zip(keys, vals)]
    logical_bytes = keys.nbytes + vals.nbytes
    codec = CompressionCodec(enabled=True)

    chunks = []
    cw = ColumnarPartitionWriter(codec, chunks.append, batch_rows=4096)
    for rec in records:
        cw.write_record(rec)
    cw.flush_batch()
    col_stream = b"".join(chunks)

    import pickle
    import struct

    pack = struct.Struct(">I").pack
    pkl_stream = bytearray()
    buf = bytearray()
    for rec in records:
        data = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        buf += pack(len(data))
        buf += data
        if len(buf) >= (256 << 10):
            pkl_stream += frame_compressed(codec, bytes(buf))
            buf.clear()
    if buf:
        pkl_stream += frame_compressed(codec, bytes(buf))

    t0 = time.perf_counter()
    col_sum = 0
    for block in iter_compressed_blocks(io.BytesIO(col_stream), codec):
        col_sum += int(columnar.decode_columns(block)[1].sum(dtype=np.int64))
    dt_col = time.perf_counter() - t0

    ser = PickleSerializer()
    t0 = time.perf_counter()
    pkl_sum = 0
    for block in iter_compressed_blocks(io.BytesIO(bytes(pkl_stream)), codec):
        pkl_sum += sum(int(r[1]) for r in ser.load_buffer(block))
    dt_pkl = time.perf_counter() - t0

    assert col_sum == pkl_sum == int(vals.sum(dtype=np.int64))
    report(
        "analytic_scan", dt_col,
        rows=n,
        logical_mb=round(logical_bytes / 1e6, 1),
        columnar_scan_gbps=round(logical_bytes / dt_col / 1e9, 4),
        pickle_scan_gbps=round(logical_bytes / dt_pkl / 1e9, 4),
        pickle_seconds=round(dt_pkl, 4),
        scan_speedup=round(dt_pkl / dt_col, 2) if dt_col else None,
    )


def enable_compile_cache() -> None:
    """Persistent XLA compilation cache (the SVC amortization the
    reference gets from stateful verb calls, RdmaChannel.java:185-192:
    setup cost paid once per JOB, not per run). First run compiles and
    persists; every later run of the same shapes loads in ~ms, so
    compile_warm_s stops dominating small e2e runs."""
    import jax

    cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # older jax: cache flags absent — run uncached
        pass


if __name__ == "__main__":
    if os.environ.get("_SRT_SKEW_CHILD"):
        # the axon platform plugin force-overrides JAX_PLATFORMS at
        # import; pin the CPU device farm via config (conftest.py
        # strategy) before any jax use
        import jax

        jax.config.update("jax_platforms", "cpu")
    enable_compile_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--transport", default="python", choices=["python", "native"])
    ap.add_argument(
        "--only", default=None,
        choices=[None, "engine", "terasort", "skew", "e2e", "train",
                 "pagerank", "als", "join", "scan"],
    )
    ap.add_argument(
        "--e2e-gb", type=float, default=0.0,
        help="run the full-stack end-to-end TeraSort at this many GiB",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write every record to this JSON artifact file",
    )
    ap.add_argument(
        "--trace-out", default=None,
        help="export the shuffle span trace (Chrome trace-event JSON, "
        "Perfetto-loadable) to this path; defaults to <out>.trace.json "
        "when --out is given",
    )
    args = ap.parse_args()
    runs = {
        "engine": lambda: bench_engine_terasort(args.scale, args.transport),
        "terasort": lambda: bench_device_terasort(args.scale),
        "skew": lambda: bench_device_terasort_skew(args.scale),
        "train": lambda: bench_transformer_train(args.scale),
        "pagerank": lambda: bench_pagerank(args.scale),
        "als": lambda: bench_als(args.scale),
        "join": lambda: bench_hashjoin(args.scale),
        "scan": lambda: bench_analytic_scan(args.scale),
    }
    if args.only == "e2e" and args.e2e_gb <= 0:
        ap.error("--only e2e requires --e2e-gb > 0")
    if args.e2e_gb > 0:
        runs["e2e"] = lambda: bench_e2e_terasort(args.e2e_gb, args.transport)

    from sparkrdma_tpu.obs import export_chrome_trace, get_registry
    from sparkrdma_tpu.obs.telemetry import Heartbeater, TelemetryHub

    # time-resolved telemetry across the whole run: the artifact gets a
    # timeline + straggler report, not just the end-state registry
    hub = TelemetryHub(role="workloads", interval_ms=500)
    heartbeater = Heartbeater(
        get_registry(), "workloads-proc", interval_ms=500, send=hub.ingest
    ).start()

    for name, fn in runs.items():
        if args.only in (None, name):
            fn()
    heartbeater.stop(flush=True)

    trace_out = args.trace_out or (f"{args.out}.trace.json" if args.out else None)
    if trace_out:
        trace = export_chrome_trace(trace_out)
        print(
            f"wrote {trace_out} ({len(trace['traceEvents'])} trace events)",
            flush=True,
        )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(
                {
                    "generated_unix": int(time.time()),
                    "scale": args.scale,
                    "transport": args.transport,
                    "e2e_gb": args.e2e_gb,
                    "workloads": RECORDS,
                    "obs_registry": get_registry().snapshot(),
                    # last per-job critical-path verdict, if a workload
                    # produced one (obs --critical-path reads this)
                    "breakdown": next(
                        (r.get("breakdown") for r in reversed(RECORDS)
                         if r.get("breakdown")),
                        None,
                    ),
                    "trace_file": trace_out,
                    "telemetry_timeline": hub.timeline(),
                    "stragglers": hub.straggler_report(),
                },
                f, indent=1,
            )
            f.write("\n")
        print(f"wrote {args.out} ({len(RECORDS)} workloads)", flush=True)
    hub.stop()
