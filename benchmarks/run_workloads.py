"""Workload benchmark suite — the HiBench role (SURVEY.md §6).

Runs the BASELINE.md workload set against this framework and prints one
JSON line per workload (and, with --out, writes them all to a committed
artifact — WORKLOADS_r{N}.json — so regressions are visible
round-over-round):

  1. TeraSort via the HOST engine (full shuffle path: writers,
     registered memory, one-sided READs, fetcher) — BASELINE config #1
     shape, scaled by --scale.
  2. TeraSort via the DEVICE plane (partition -> all_to_all -> merge).
  3. PageRank (multi-round all-to-all).
  4. ALS (iterative wide shuffle).
  5. Hash join (shuffle-heavy join).
  6. Transformer training throughput (ulysses attention through the
     Pallas flash kernel fwd+bwd; K steps in one executable).
  7. With --e2e-gb G: END-TO-END TeraSort of G GiB through the WHOLE
     stack — host map sorts -> range split -> publish into registered
     memory -> driver location protocol -> one-sided native READs ->
     HBM staging -> device merge — verified on-device (sortedness +
     order-invariant checksums vs the host input) and phase-timed
     against the stock single-host ``np.sort`` baseline (the
     reference's 1.41x comparison shape, README.md:7-19).

Usage: python benchmarks/run_workloads.py [--scale 0.05]
         [--transport native] [--e2e-gb 1.0] [--out WORKLOADS_r04.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RECORDS = []


def report(workload, seconds, **extra):
    rec = {"workload": workload, "seconds": round(seconds, 4), **extra}
    RECORDS.append(rec)
    print(json.dumps(rec), flush=True)


def bench_engine_terasort(scale: float, transport: str):
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n = int(1_000_000 * scale)  # records of ~100B => scale * 100MB
    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint64)

    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        data = [(int(k), b"x" * 90) for k in keys]
        t0 = time.perf_counter()
        rdd = ctx.parallelize(data, 8).sort_by_key(num_partitions=8)
        out = ctx.run_job(rdd)
        dt = time.perf_counter() - t0
    assert len(out) == n
    assert all(out[i][0] <= out[i + 1][0] for i in range(min(1000, n - 1)))
    report(
        "terasort_engine", dt,
        records=n, transport=transport,
        mb=round(n * 100 / 1e6, 1),
        records_per_s=int(n / dt),
    )


def bench_device_terasort(scale: float):
    import jax

    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int((1 << 24) * scale * 20)  # default scale 0.05 -> 16M keys
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    sorter = TeraSorter(make_mesh())
    sorter.sort(keys)  # warm: compile at the real shape
    t0 = time.perf_counter()
    out = sorter.sort(keys)
    dt = time.perf_counter() - t0
    assert len(out) == n
    report(
        "terasort_device", dt,
        keys=n, devices=len(jax.devices()),
        e2e_gbps_incl_transfers=round(n * 4 / dt / 1e9, 3),
        note=(
            "wall time includes host->device and device->host of every "
            "byte; on this rig those ride the axon tunnel (~15 MB/s "
            "readback) and dominate — bench.py's device_sort_gbps is "
            "the on-chip rate of the same step"
        ),
    )


def bench_e2e_terasort(gb: float, transport: str, reducers: int = 8,
                       executors: int = 2):
    """One measured TeraSort with the WHOLE framework in the loop.

    Map side plays Spark's part (host sorts, as the reference leaves to
    Spark's sort writers); everything after — registered-memory
    publish, driver location RPC, one-sided READs, HBM staging, device
    merge — is this framework. Output is verified WITHOUT bulk
    device->host readback (order-invariant xor/sum checksums + an
    on-device sortedness reduction), because bulk readback on this rig
    measures the axon tunnel, not the framework (see bench.py)."""
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.ops.sort import device_sort
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n = int(gb * (1 << 30)) // 4
    n -= n % executors
    rng = np.random.default_rng(12)
    shards = [
        rng.integers(0, 1 << 32, n // executors, dtype=np.uint32)
        for _ in range(executors)
    ]

    # stock role: one host np.sort over everything (what the reference's
    # baseline ran as Spark's sort shuffle on one node)
    t0 = time.perf_counter()
    host_sorted = np.sort(np.concatenate(shards))
    t_host = time.perf_counter() - t0
    del host_sorted  # multiset checks below; bytes never compared bulk

    # expected per-reducer order-invariant checksums from the INPUT
    edges = np.asarray(
        [(r * (1 << 32)) // reducers for r in range(1, reducers)], np.uint32
    )
    exp_sum = np.zeros(reducers, np.uint32)
    exp_xor = np.zeros(reducers, np.uint32)
    exp_cnt = np.zeros(reducers, np.int64)
    for sh in shards:
        dest = np.searchsorted(edges, sh, side="right")
        for r in range(reducers):
            sel = sh[dest == r]
            exp_cnt[r] += len(sel)
            with np.errstate(over="ignore"):
                exp_sum[r] += sel.sum(dtype=np.uint32)
            exp_xor[r] ^= np.bitwise_xor.reduce(sel) if len(sel) else np.uint32(0)

    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [
        TpuShuffleManager(conf, is_driver=False, executor_id=f"e2e-{i}")
        for i in range(executors)
    ]
    handle = BaseShuffleHandle(
        shuffle_id=99, num_maps=executors, partitioner=HashPartitioner(reducers)
    )
    driver.register_shuffle(handle)
    ios = [DeviceShuffleIO(ex) for ex in execs]
    phases = {}
    try:
        # --- map side: host sort + range split (Spark's role) ----------
        t0 = time.perf_counter()
        splits = []
        for sh in shards:
            local = np.sort(sh)
            bounds = np.concatenate(
                [[0], np.searchsorted(local, edges), [len(local)]]
            )
            splits.append((local, bounds))
        phases["map_sort_s"] = time.perf_counter() - t0

        # --- publish into registered memory + driver locations ---------
        t0 = time.perf_counter()
        for io, (local, bounds) in zip(ios, splits):
            io.publish_device_blocks(
                99,
                {r: local[bounds[r]: bounds[r + 1]] for r in range(reducers)},
            )
        phases["publish_s"] = time.perf_counter() - t0

        # --- reduce side: READ -> stage -> device merge ----------------
        # Blocks arrive STAGED AS uint32 (fetch dtype) — a uint8 slab
        # would force on-device byte->word assembly, whose [..., 4]-minor
        # reshape the TPU tiled layout pads 4->128 (measured: a 32 GiB
        # HBM allocation for a 1 GiB input). jit's own dispatch cache
        # handles per-shape retracing.
        @jax.jit
        def merge(arrs, word_counts):
            stacked_u32 = jnp.stack(arrs)
            _, words = stacked_u32.shape
            iota = jnp.arange(words, dtype=jnp.int32)[None, :]
            masked = jnp.where(
                iota < word_counts[:, None], stacked_u32,
                jnp.uint32(0xFFFFFFFF),
            )
            merged = device_sort(masked.reshape(-1))
            t = word_counts.sum().astype(jnp.uint32)
            vi = jnp.arange(merged.shape[0], dtype=jnp.int32)
            mm = jnp.where(vi < t, merged, jnp.uint32(0))
            csum = mm.sum(dtype=jnp.uint32)
            cxor = jax.lax.reduce(
                mm, jnp.uint32(0), jax.lax.bitwise_xor, (0,)
            )
            ok = jnp.all(merged[1:] >= merged[:-1]).astype(jnp.uint32)
            # ONE packed scalar vector -> one host readback per
            # reducer (each sync pays full tunnel latency)
            return merged, jnp.stack([t, csum, cxor, ok])

        # warm the merge executable at the expected slab shape (compile
        # is the JVM-startup analogue the reference's numbers exclude)
        from sparkrdma_tpu.ops.hbm_arena import MIN_BLOCK_SIZE, _size_class

        # Warm every executable the timed loop can hit (compile is the
        # JVM-startup analogue the reference's numbers exclude). The
        # mean block size can sit ON a size-class boundary, so blocks
        # land in TWO adjacent classes: warm the merge at both
        # homogeneous shapes AND the small->large pad used when one
        # reducer's blocks mix classes.
        mean_block = int(n / executors / reducers * 4)
        cls_hi = _size_class(int(mean_block * 1.05)) // 4
        cls_lo = max(_size_class(MIN_BLOCK_SIZE) // 4, cls_hi // 2)
        t0 = time.perf_counter()
        for cw in {cls_hi, cls_lo}:
            jax.block_until_ready(
                merge(
                    tuple(jnp.zeros((cw,), jnp.uint32)
                          for _ in range(executors)),
                    jnp.full((executors,), cw, jnp.int32),
                )[0]
            )
        if cls_lo != cls_hi:
            jax.block_until_ready(
                jnp.zeros((cls_hi,), jnp.uint32)
                .at[:cls_lo]
                .set(jnp.zeros((cls_lo,), jnp.uint32))
            )
        phases_compile = time.perf_counter() - t0

        # fetch/compute overlap (SURVEY §2.3): the next reducer's
        # READ + HBM staging runs on a worker thread while the device
        # merges the current one — the e2e exercises the same overlap
        # the fetcher gives record-plane readers. Phase timers count
        # BUSY time per plane; with overlap their sum exceeds wall.
        from concurrent.futures import ThreadPoolExecutor

        t_fetch = t_merge = 0.0

        def fetch_one(r):
            nonlocal t_fetch
            t0 = time.perf_counter()
            got = reducer_io.fetch_device_blocks(
                99, r, r + 1, dtype=np.uint32, timeout_s=120
            )
            t_fetch += time.perf_counter() - t0
            return got[r]

        reducer_io = ios[0]
        t_wall0 = time.perf_counter()
        pool = ThreadPoolExecutor(1, thread_name_prefix="e2e-fetch")
        try:
            fut = pool.submit(fetch_one, 0)
            for r in range(reducers):
                bufs = fut.result()
                if r + 1 < reducers:
                    fut = pool.submit(fetch_one, r + 1)
                t0 = time.perf_counter()
                # pin the set device-resident across the direct .array
                # access (no-op unless HBM pressure spilled some;
                # members are never victims while pinned)
                with reducer_io.device_buffers.pinned_on_device(bufs):
                    cap = max(b.array.shape[0] for b in bufs)
                    arrs = tuple(
                        b.array
                        if b.array.shape[0] == cap
                        else jnp.zeros((cap,), jnp.uint32)
                        .at[: b.array.shape[0]]
                        .set(b.array)
                        for b in bufs
                    )
                    counts = jnp.asarray(
                        [b.length // 4 for b in bufs], jnp.int32
                    )
                    merged, packed = merge(arrs, counts)
                # ONE readback: [count, sum, xor, sorted]
                t, csum, cxor, ok = (int(x) for x in np.asarray(packed))
                if t != exp_cnt[r]:
                    raise SystemExit(
                        f"E2E FAILED: reducer {r} count {t} != {exp_cnt[r]}"
                    )
                if csum != int(exp_sum[r]) or cxor != int(exp_xor[r]):
                    raise SystemExit(
                        f"E2E FAILED: reducer {r} checksum mismatch"
                    )
                if not ok:
                    raise SystemExit(
                        f"E2E FAILED: reducer {r} output not sorted"
                    )
                for b in bufs:
                    b.free()
                del merged
                t_merge += time.perf_counter() - t0
        finally:
            # a verification failure or fetch fault must not tear down
            # executors underneath the in-flight prefetch, nor hang
            # interpreter exit joining a 120 s fetch
            pool.shutdown(wait=False, cancel_futures=True)
        reduce_wall = time.perf_counter() - t_wall0
        # only wall time counts toward the total; per-plane busy times
        # are informational (they overlap)
        phases["reduce_wall_s"] = reduce_wall
        extra_busy = {
            "fetch_stage_busy_s": round(t_fetch, 3),
            "device_merge_busy_s": round(t_merge, 3),
            "overlap_saved_s": round(
                max(0.0, t_fetch + t_merge - reduce_wall), 3
            ),
        }
        # live observability counters (pool allocs, read-path split,
        # fetch histograms, HBM budget/spills) into the artifact
        metrics = reducer_io.metrics_snapshot()
    finally:
        for io in ios:
            io.stop()
        for ex in execs:
            ex.stop()
        driver.stop()

    total = sum(phases.values())
    report(
        "terasort_e2e", total,
        gb=round(n * 4 / (1 << 30), 3), transport=transport,
        reducers=reducers, executors=executors,
        host_sort_baseline_s=round(t_host, 3),
        vs_host_sort=round(t_host / total, 3),
        compile_warm_s=round(phases_compile, 3),
        verified="count+sum+xor+sorted (on-device)",
        metrics=metrics,
        **extra_busy,
        note=(
            "single-host rig: reduce_wall_s (and the overlapped "
            "fetch_stage_busy_s / device_merge_busy_s it is built "
            "from) is dominated by axon-tunnel dispatch+transfer "
            "latency, not framework code (bench.py measures the "
            "planes in isolation); the reference's 1.41x was "
            "multi-node where shuffle crosses a real network"
        ),
        **{k: round(v, 3) for k, v in phases.items()},
    )


def bench_transformer_train(scale: float):
    """Sharded transformer training throughput on one chip: K SGD
    steps (ulysses attention -> the Pallas flash kernel fwd + custom-
    VJP bwd) inside ONE executable, so the measurement is steady-state
    compute, not per-step dispatch through the tunnel."""
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.models.transformer_step import (
        TransformerStep,
        init_params,
        make_training_mesh,
    )

    mesh = make_training_mesh(jax.devices()[:1])
    heads, dhead = 8, 64
    d_model, d_hidden = heads * dhead, 4 * heads * dhead
    b = 4
    s = max(128, int(2048 * scale * 20))  # default scale 0.05 -> 2048
    params = init_params(d_model, n_heads=heads, d_hidden=d_hidden, tp=1)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))
    step = TransformerStep(mesh, n_heads=heads, lr=0.01, attn="ulysses")
    pl, xl, yl = step.place(params, x, y)

    def run(n):
        loss, _ = step.run_steps(pl, xl, yl, n)
        return float(loss)

    l1 = run(1)  # warm: compiles step + loop
    run(9)
    t0 = time.perf_counter()
    run(1)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    lk = run(9)
    tk = time.perf_counter() - t0
    if tk > t1:
        per_step = (tk - t1) / 8  # dispatch cancelled by differencing
    else:
        # timing noise ate the difference: fall back to the dispatch-
        # inclusive per-step time (conservative underestimate of
        # throughput) rather than reporting nonsense
        per_step = tk / 9
    assert np.isfinite(lk) and lk <= l1 * 1.01, "training diverged"
    # attention (fwd 1x + bwd 2.5x) + mlp/proj matmul flops per step
    att = 4 * b * heads * s * s * dhead * 3.5
    mlp = 2 * b * s * (4 * d_model * d_model + 2 * d_model * d_hidden) * 3
    report(
        "transformer_train", tk,
        steps_per_s=round(1.0 / per_step, 2),
        step_ms=round(per_step * 1e3, 2),
        tflops_effective=round((att + mlp) / per_step / 1e12, 2),
        b=b, s=s, d_model=d_model, heads=heads, attn="ulysses+flash_vjp",
        final_loss=round(lk, 5),
    )


def bench_pagerank(scale: float):
    from sparkrdma_tpu.models import PageRank
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int(20000 * scale * 20)
    m = n * 8
    rng = np.random.default_rng(0)
    edges = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    pr = PageRank(make_mesh())
    pr.run(edges, n, iters=10)  # warm compile
    t0 = time.perf_counter()
    ranks = pr.run(edges, n, iters=10)
    dt = time.perf_counter() - t0
    assert abs(ranks.sum() - 1.0) < 1e-2
    report("pagerank", dt, vertices=n, edges=m, iters=10)


def bench_als(scale: float):
    from sparkrdma_tpu.models import ALS
    from sparkrdma_tpu.models.als import rmse
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n_u = int(2000 * scale * 20)
    n_i = n_u // 2
    m = n_u * 10
    rng = np.random.default_rng(0)
    tu = rng.normal(size=(n_u, 4))
    tv = rng.normal(size=(n_i, 4))
    users = rng.integers(0, n_u, m)
    items = rng.integers(0, n_i, m)
    vals = (tu[users] * tv[items]).sum(1)
    ratings = np.stack([users, items, vals], 1)
    als = ALS(make_mesh(), rank=8)
    als.fit(ratings, n_u, n_i, iters=5)  # warm compile
    t0 = time.perf_counter()
    u, v = als.fit(ratings, n_u, n_i, iters=5)
    dt = time.perf_counter() - t0
    report("als", dt, users=n_u, items=n_i, ratings=m, rmse=round(rmse(u, v, ratings), 4))


def bench_hashjoin(scale: float):
    from sparkrdma_tpu.models import HashJoin
    from sparkrdma_tpu.parallel.mesh import make_mesh

    nb = int(10000 * scale * 20)
    npr = nb * 8
    rng = np.random.default_rng(0)
    bk = rng.choice(1 << 24, nb, replace=False).astype(np.uint32)
    bv = rng.integers(0, 1 << 20, nb).astype(np.int32)
    pk = rng.choice(bk, npr).astype(np.uint32)
    pv = np.arange(npr, dtype=np.int32)
    hj = HashJoin(make_mesh())
    hj.join(bk, bv, pk, pv)  # warm compile
    t0 = time.perf_counter()
    out = hj.join(bk, bv, pk, pv)
    dt = time.perf_counter() - t0
    assert len(out) == npr
    report("hashjoin", dt, build=nb, probe=npr, rows_per_s=int(npr / dt))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--transport", default="python", choices=["python", "native"])
    ap.add_argument(
        "--only", default=None,
        choices=[None, "engine", "terasort", "e2e", "train",
                 "pagerank", "als", "join"],
    )
    ap.add_argument(
        "--e2e-gb", type=float, default=0.0,
        help="run the full-stack end-to-end TeraSort at this many GiB",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write every record to this JSON artifact file",
    )
    args = ap.parse_args()
    runs = {
        "engine": lambda: bench_engine_terasort(args.scale, args.transport),
        "terasort": lambda: bench_device_terasort(args.scale),
        "train": lambda: bench_transformer_train(args.scale),
        "pagerank": lambda: bench_pagerank(args.scale),
        "als": lambda: bench_als(args.scale),
        "join": lambda: bench_hashjoin(args.scale),
    }
    if args.only == "e2e" and args.e2e_gb <= 0:
        ap.error("--only e2e requires --e2e-gb > 0")
    if args.e2e_gb > 0:
        runs["e2e"] = lambda: bench_e2e_terasort(args.e2e_gb, args.transport)
    for name, fn in runs.items():
        if args.only in (None, name):
            fn()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(
                {
                    "generated_unix": int(time.time()),
                    "scale": args.scale,
                    "transport": args.transport,
                    "e2e_gb": args.e2e_gb,
                    "workloads": RECORDS,
                },
                f, indent=1,
            )
            f.write("\n")
        print(f"wrote {args.out} ({len(RECORDS)} workloads)", flush=True)
