"""Workload benchmark suite — the HiBench role (SURVEY.md §6).

Runs the BASELINE.md workload set against this framework and prints one
JSON line per workload:

  1. TeraSort via the HOST engine (full shuffle path: writers,
     registered memory, one-sided READs, fetcher) — BASELINE config #1
     shape, scaled by --scale.
  2. TeraSort via the DEVICE plane (partition -> all_to_all -> merge).
  3. PageRank (multi-round all-to-all).
  4. ALS (iterative wide shuffle).
  5. Hash join (shuffle-heavy join).

Usage: python benchmarks/run_workloads.py [--scale 0.05] [--transport native]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def report(workload, seconds, **extra):
    print(
        json.dumps(
            {"workload": workload, "seconds": round(seconds, 4), **extra}
        ),
        flush=True,
    )


def bench_engine_terasort(scale: float, transport: str):
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n = int(1_000_000 * scale)  # records of ~100B => scale * 100MB
    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint64)

    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        data = [(int(k), b"x" * 90) for k in keys]
        t0 = time.perf_counter()
        rdd = ctx.parallelize(data, 8).sort_by_key(num_partitions=8)
        out = ctx.run_job(rdd)
        dt = time.perf_counter() - t0
    assert len(out) == n
    assert all(out[i][0] <= out[i + 1][0] for i in range(min(1000, n - 1)))
    report(
        "terasort_engine", dt,
        records=n, transport=transport,
        mb=round(n * 100 / 1e6, 1),
        records_per_s=int(n / dt),
    )


def bench_device_terasort(scale: float):
    import jax

    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int((1 << 24) * scale * 20)  # default scale 0.05 -> 16M keys
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    sorter = TeraSorter(make_mesh())
    sorter.sort(keys)  # warm: compile at the real shape
    t0 = time.perf_counter()
    out = sorter.sort(keys)
    dt = time.perf_counter() - t0
    assert len(out) == n
    report(
        "terasort_device", dt,
        keys=n, devices=len(jax.devices()),
        gbps=round(n * 4 / dt / 1e9, 3),
    )


def bench_pagerank(scale: float):
    from sparkrdma_tpu.models import PageRank
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n = int(20000 * scale * 20)
    m = n * 8
    rng = np.random.default_rng(0)
    edges = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    pr = PageRank(make_mesh())
    pr.run(edges, n, iters=10)  # warm compile
    t0 = time.perf_counter()
    ranks = pr.run(edges, n, iters=10)
    dt = time.perf_counter() - t0
    assert abs(ranks.sum() - 1.0) < 1e-2
    report("pagerank", dt, vertices=n, edges=m, iters=10)


def bench_als(scale: float):
    from sparkrdma_tpu.models import ALS
    from sparkrdma_tpu.models.als import rmse
    from sparkrdma_tpu.parallel.mesh import make_mesh

    n_u = int(2000 * scale * 20)
    n_i = n_u // 2
    m = n_u * 10
    rng = np.random.default_rng(0)
    tu = rng.normal(size=(n_u, 4))
    tv = rng.normal(size=(n_i, 4))
    users = rng.integers(0, n_u, m)
    items = rng.integers(0, n_i, m)
    vals = (tu[users] * tv[items]).sum(1)
    ratings = np.stack([users, items, vals], 1)
    als = ALS(make_mesh(), rank=8)
    als.fit(ratings, n_u, n_i, iters=5)  # warm compile
    t0 = time.perf_counter()
    u, v = als.fit(ratings, n_u, n_i, iters=5)
    dt = time.perf_counter() - t0
    report("als", dt, users=n_u, items=n_i, ratings=m, rmse=round(rmse(u, v, ratings), 4))


def bench_hashjoin(scale: float):
    from sparkrdma_tpu.models import HashJoin
    from sparkrdma_tpu.parallel.mesh import make_mesh

    nb = int(10000 * scale * 20)
    npr = nb * 8
    rng = np.random.default_rng(0)
    bk = rng.choice(1 << 24, nb, replace=False).astype(np.uint32)
    bv = rng.integers(0, 1 << 20, nb).astype(np.int32)
    pk = rng.choice(bk, npr).astype(np.uint32)
    pv = np.arange(npr, dtype=np.int32)
    hj = HashJoin(make_mesh())
    hj.join(bk, bv, pk, pv)  # warm compile
    t0 = time.perf_counter()
    out = hj.join(bk, bv, pk, pv)
    dt = time.perf_counter() - t0
    assert len(out) == npr
    report("hashjoin", dt, build=nb, probe=npr, rows_per_s=int(npr / dt))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--transport", default="python", choices=["python", "native"])
    ap.add_argument(
        "--only", default=None,
        choices=[None, "engine", "terasort", "pagerank", "als", "join"],
    )
    args = ap.parse_args()
    runs = {
        "engine": lambda: bench_engine_terasort(args.scale, args.transport),
        "terasort": lambda: bench_device_terasort(args.scale),
        "pagerank": lambda: bench_pagerank(args.scale),
        "als": lambda: bench_als(args.scale),
        "join": lambda: bench_hashjoin(args.scale),
    }
    for name, fn in runs.items():
        if args.only in (None, name):
            fn()
