"""Measured study: what is the fastest exact device sort on one TPU chip?

This is the evidence behind ``ops/sort.device_sort`` and
docs/DESIGN.md §6. It exists because rounds 1-3 kept *assuming* a
faster-than-XLA sort decomposition existed (row-wise shapes, Pallas
bitonic networks) without ever timing one on the hardware. Run it on a
real chip; it prints one JSON object with every measurement.

Methodology (the only one that works through the axon tunnel, see
bench.py): K data-dependent steps chained inside ONE jitted program,
differenced against a 1-step run, scalar readback; median of
``--reps`` runs. ``block_until_ready`` returns early on this platform,
so naive per-dispatch timing reports fantasy numbers (we measured
"5.8 TB/s" for a flat sort that way).

Findings (v5e, 2026-07, jax 0.9):

- flat ``lax.sort`` of 32M u32: ~82 ms (1.6 GB/s). This is the VPU
  comparator roofline, not an XLA weakness: a bitonic network is
  ~log2(n)^2/2 ≈ 310 compare-exchange stages at n=2^25, and XLA
  executes them at ~0.25 ms/stage — ~10x better fused than anything
  composable from jnp ops (a single reshape+min/max merge stage costs
  ~2.5 ms at the jnp level, measured below).
- row-wise sort IS much faster per pass (short rows vectorize across
  sublanes), but a full sort needs log2(R) merge levels on top, and
  every expressible merge (jnp strided min/max chains, Pallas
  compare-exchange kernels) pays the same comparator bound with worse
  fusion than XLA's own sort. Every decomposition we measured or
  bounded lands at or above flat-sort time.
- scatter/gather-based radix passes are 3-6x slower than sorting
  itself (random scatter ~0.55 GB/s, gather ~0.28 GB/s) — counting
  sort is a dead end on this hardware.

Conclusion: ``lax.sort`` is the optimal exact-sort primitive on this
chip; the framework's own perf leverage is the byte plane around it.
That mirrors the reference exactly: SparkRDMA never replaced Spark's
sort — it replaced the transport under it
(/root/reference/README.md:7-19; RdmaWrapperShuffleWriter delegates to
Spark's own sort writers, RdmaWrapperShuffleWriter.scala:85-101).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = 1 << 25  # 32M u32 keys = 128 MiB


def _bench(x, step, chain, reps):
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(1,))
    def chained(v, k):
        def body(i, v):
            # re-disorder between rounds; xor keeps any sort honest
            v = jnp.flip(v) ^ (i.astype(jnp.uint32) * jnp.uint32(2654435761))
            return step(v)

        return jax.lax.fori_loop(0, k, body, v).sum()

    float(chained(x, 1))
    float(chained(x, chain))  # compile both
    dts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(chained(x, 1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(chained(x, chain))
        tk = time.perf_counter() - t0
        dts.append(max((tk - t1) / (chain - 1), 1e-9))
    dt = float(np.median(dts))
    return {"ms": round(dt * 1e3, 2), "gbps": round(N * 4 / dt / 1e9, 2)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chain", type=int, default=16,
                    help="chained steps per jit (>= 2: differencing needs it)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--quick", action="store_true", help="flat + 3 row shapes only")
    args = ap.parse_args()
    if args.chain < 2:
        ap.error("--chain must be >= 2 (K-vs-1 differencing)")

    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.ops.sort import pack_by_partition, radix_partition

    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.integers(0, 1 << 32, size=N, dtype=np.uint32), jax.devices()[0]
    )
    out = {"n": N, "device": str(jax.devices()[0])}

    out["flat_sort"] = _bench(x, jnp.sort, args.chain, args.reps)
    row_cs = [9, 11, 13] if args.quick else [7, 8, 9, 10, 11, 13, 15, 17, 19, 21]
    for logc in row_cs:
        c = 1 << logc
        out[f"rowsort_2^{logc}"] = _bench(
            x, lambda v, c=c: jnp.sort(v.reshape(-1, c), axis=-1).reshape(-1),
            args.chain, args.reps,
        )
    if not args.quick:
        # one bitonic merge stage at the jnp level (reshape + min/max):
        # the building block every hand-rolled merge tree pays per stage
        for logd in [13, 21]:
            d = 1 << logd

            def stage(v, d=d):
                w = v.reshape(-1, 2, d)
                lo = jnp.minimum(w[:, 0, :], w[:, 1, :])
                hi = jnp.maximum(w[:, 0, :], w[:, 1, :])
                return jnp.stack([lo, hi], axis=1).reshape(-1)

            out[f"minmax_stage_2^{logd}"] = _bench(x, stage, args.chain, args.reps)
        # the shuffle partition/pack pass (argsort-based stable bucketing):
        # what the e>1 write path costs per step on one chip
        def pack(v):
            dest = radix_partition(v, 8, 32)
            slab, _, _ = pack_by_partition(v, dest, 8, (N // 8) * 2, fill=0)
            return slab.reshape(-1)[:N]

        out["radix_pack_e8"] = _bench(x, pack, max(2, args.chain // 4), args.reps)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
