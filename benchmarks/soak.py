"""Multi-tenant concurrent-shuffle soak — the tenancy plane's ledger.

One TpuContext serves closed-loop job streams from N tenants with
unequal weights and unequal job sizes for ``--seconds`` wall-clock:
hundreds of small mixed jobs (terasort-, hashjoin-, and
pagerank-shaped RDD pipelines, every result verified) dispatched
through the admission controller, the fair-share map/reduce pools, and
the shuffle planes (DESIGN.md §19). The harness then interrogates the
obs registry for the serving invariants:

- **HWM flatness** — process-wide ``mempool.in_use_bytes`` /
  ``hbm.in_use_bytes`` high-water marks must stop growing after the
  first half (steady-state serving leaks nothing per job);
- **no starvation** — every tenant completes jobs in the second half;
- **p99 task latency** — per tenant, from the ``tenant.task_ms``
  histogram bucket deltas between the halftime and final snapshots;
- **fairness** (``--strict``) — each tenant's measured task-seconds
  share within 25 %% (relative) of its weight share while all streams
  stay backlogged;
- **quota backpressure probe** — a dedicated segment installs a tiny
  mempool quota for one tenant and proves it blocks (counters) while a
  concurrent in-quota tenant's job latency stays near its solo
  baseline (asserted under ``--strict``, recorded always);
- **push-vs-rpc probe** — a short cluster-mode (subprocess workers)
  segment under concurrent two-tenant load, verifying push volume
  moves on the data plane and NEVER shows up as an ``rpc.handle_ms``
  message type (recorded either way);
- **event journal + capacity** (PR 20) — the merged HLC-ordered
  cluster event journal rides the ledger as ``ledger["journal"]``
  (render with ``python -m sparkrdma_tpu.obs --timeline LEDGER``) and
  the USE-method capacity report as ``ledger["capacity"]``. A quiet
  soak gates on a quiet journal (no pages, no takeovers); the quota
  probe gates on the capacity plane naming mempool as the binding
  resource; ``driver:kill`` chaos gates on the journal reproducing
  the kill -> takeover -> adoption causal chain in merged HLC order.

Since PR 16 the verdicts are built on the SLO engine's shared
:func:`~sparkrdma_tpu.obs.slo.judge` primitive (soak and production
share one evaluator), the driver hub's live SLO/burn-rate state rides
the ledger as ``ledger["slo"]`` (breach + diagnosis records included,
rendered by ``python -m sparkrdma_tpu.obs --diagnose LEDGER``), and a
chaos mode exists: ``--fault-plan`` installs a seeded
``testing/faults.py`` plan for the soak segment and ``--expect-breach``
flips the gate — the run fails unless an SLO breach fired AND the
automated diagnosis names the injected seam. Without a fault plan the
gate is the opposite: zero breaches, zero diagnoses (no false pages).

Emits one JSON ledger (``--out``, default SOAK_r01.json) and exits
nonzero when a required check fails. CI smoke:
``python benchmarks/soak.py --seconds 20 --tenants 3`` — fails on HWM
growth, a starved tenant, or any job failure; the fairness/quota bars
are enforced by the acceptance run's ``--strict``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sparkrdma_tpu.engine.context import TpuContext
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.slo import judge
from sparkrdma_tpu.tenancy import quota as _quota
from sparkrdma_tpu.utils.config import TpuShuffleConf

WEIGHTS = [4, 2, 1, 1]          # unequal by construction
JOB_ROWS = [3000, 2000, 1200, 1200]  # unequal job sizes, same order
N_PARTS = 8                     # > task_threads: queues stay backlogged
JOBS_IN_FLIGHT = 2              # per-tenant closed-loop concurrency

# rpc.handle_ms message types that ARE control plane — anything else
# appearing under concurrent push load is data volume leaking into the
# metadata path
CONTROL_RPC_TYPES = {
    "MANAGER_HELLO",
    "FETCH_PARTITION_LOCATIONS",
    "PUBLISH_PARTITION_LOCATIONS",
    "ANNOUNCE_MANAGERS",
}


# ---------------------------------------------------------------------------
# job shapes — small, verified, all three planes of the mixed workload
# ---------------------------------------------------------------------------
def _terasort_job(ctx, rng, rows, tenant):
    data = rng.integers(0, 1 << 30, rows).tolist()
    rdd = (
        ctx.parallelize(data, N_PARTS)
        .map(lambda x: (int(x), None))
        .sort_by_key(num_partitions=N_PARTS)
    )
    out = [k for k, _ in ctx.run_job(rdd, tenant=tenant)]
    assert out == sorted(data), "terasort-shaped job produced unsorted output"


def _hashjoin_job(ctx, rng, rows, tenant):
    keys = rng.integers(0, rows, rows).tolist()
    build = ctx.parallelize(
        [(k, i) for i, k in enumerate(keys[: rows // 2])], N_PARTS // 2
    )
    probe = ctx.parallelize(
        [(k, -i) for i, k in enumerate(keys)], N_PARTS // 2
    )
    rdd = build.join(probe, num_partitions=N_PARTS)
    n = len(ctx.run_job(rdd, tenant=tenant))
    assert n > 0, "hashjoin-shaped job joined nothing"


def _pagerank_job(ctx, rng, rows, tenant):
    n_vertices = max(50, rows // 20)
    edges = rng.integers(0, n_vertices, (rows, 2))
    deg = np.bincount(edges[:, 0], minlength=n_vertices)
    rdd = (
        ctx.parallelize(edges.tolist(), N_PARTS)
        .map(lambda e: (int(e[1]), 1.0 / max(1, deg[e[0]])))
        .reduce_by_key(lambda a, b: a + b, num_partitions=N_PARTS)
    )
    contribs = dict(ctx.run_job(rdd, tenant=tenant))
    assert len(contribs) > 0 and all(v > 0 for v in contribs.values())


SHAPES = [_terasort_job, _hashjoin_job, _pagerank_job]


# ---------------------------------------------------------------------------
# registry helpers
# ---------------------------------------------------------------------------
def _p99_from_bucket_delta(half: dict, end: dict) -> float | None:
    """p99 (ms) of the observations BETWEEN two full histogram
    snapshots, by linear interpolation over the bucket-count deltas."""
    items = []
    overflow = 0
    for key, c_end in end.get("buckets", {}).items():
        d = c_end - half.get("buckets", {}).get(key, 0)
        if key == "overflow":
            overflow = d
        else:
            items.append((float(key[3:]), d))
    items.sort()
    total = sum(d for _, d in items) + overflow
    if total <= 0:
        return None
    target = 0.99 * total
    cum = 0
    lo = 0.0
    for bound, d in items:
        if cum + d >= target:
            frac = (target - cum) / d if d else 1.0
            return round(lo + frac * (bound - lo), 3)
        cum += d
        lo = bound
    return round(end.get("max") or lo, 3)  # landed in overflow


def _tenant_task_stats(snap_half, snap_end, tenant):
    """(task_seconds, p99_ms) for one tenant across its pools, from the
    halftime-vs-end delta of every tenant.task_ms histogram."""
    secs = 0.0
    merged_half = {"buckets": {}}
    merged_end = {"buckets": {}, "max": 0.0}
    for key, h_end in snap_end["histograms"].items():
        if not key.startswith("tenant.task_ms") or f"tenant={tenant}" not in key:
            continue
        h_half = snap_half["histograms"].get(
            key, {"count": 0, "sum": 0.0, "buckets": {}}
        )
        secs += (h_end["sum"] - h_half.get("sum", 0.0)) / 1e3
        for b, c in h_end.get("buckets", {}).items():
            merged_end["buckets"][b] = merged_end["buckets"].get(b, 0) + c
        for b, c in h_half.get("buckets", {}).items():
            merged_half["buckets"][b] = merged_half["buckets"].get(b, 0) + c
        merged_end["max"] = max(merged_end["max"], h_end.get("max") or 0.0)
    return secs, _p99_from_bucket_delta(merged_half, merged_end)


def _hwm(snap, name) -> int:
    g = snap["gauges"].get(name)
    return int(g["hwm"]) if g else 0


# ---------------------------------------------------------------------------
# soak phases
# ---------------------------------------------------------------------------
def run_soak(args) -> dict:
    tenants = [f"tenant-{i}" for i in range(args.tenants)]
    weights = {t: WEIGHTS[i] for i, t in enumerate(tenants)}
    conf_map = {
        "tpu.shuffle.tenancy.weights": ",".join(
            f"{t}:{w}" for t, w in weights.items()
        ),
        # mapped (zero-copy page-cache) delivery bypasses the pooled
        # destination buffers entirely, which would make the mempool
        # HWM-flatness check vacuous — soak the pooled plane instead
        "tpu.shuffle.mappedFetch": "false",
    }
    if args.fault_plan:
        # chaos mode: seeded fault plan travels the normal conf path
        # (manager ensure_installed), exactly like production would
        conf_map["tpu.shuffle.faultPlan"] = args.fault_plan
        conf_map["tpu.shuffle.faultPlanSeed"] = str(args.fault_seed)
    if args.slo_task_p99_ms:
        conf_map["tpu.shuffle.obs.slo.taskP99Ms"] = str(args.slo_task_p99_ms)
        # tighten the telemetry/eval cadence so a short soak still
        # accumulates enough ring windows for the burn-rate horizons
        conf_map["tpu.shuffle.obs.telemetry.intervalMs"] = "250"
        conf_map["tpu.shuffle.obs.slo.evalIntervalMs"] = "500"
    conf = TpuShuffleConf(conf_map)
    reg = get_registry()
    stats = {
        t: {"jobs": 0, "jobs_2nd_half": 0, "failures": [], "by_shape": {}}
        for t in tenants
    }
    lock = threading.Lock()
    halftime = {"snap": None, "at": 0.0}
    deadline = time.monotonic() + args.seconds
    half_at = time.monotonic() + args.seconds / 2.0

    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        def stream(tenant, idx, slot):
            rng = np.random.default_rng(args.seed * 1000 + idx * 10 + slot)
            rows = int(JOB_ROWS[idx] * args.scale)
            k = slot
            while time.monotonic() < deadline:
                shape = SHAPES[k % len(SHAPES)]
                k += 1
                try:
                    shape(ctx, rng, rows, tenant)
                except Exception as e:  # noqa: BLE001 — ledgered
                    with lock:
                        stats[tenant]["failures"].append(
                            f"{shape.__name__}: {type(e).__name__}: {e}"
                        )
                    continue
                with lock:
                    stats[tenant]["jobs"] += 1
                    name = shape.__name__.strip("_")
                    stats[tenant]["by_shape"][name] = (
                        stats[tenant]["by_shape"].get(name, 0) + 1
                    )
                    if halftime["snap"] is not None:
                        stats[tenant]["jobs_2nd_half"] += 1

        threads = [
            threading.Thread(
                target=stream, args=(t, i, s), name=f"soak-{t}-{s}"
            )
            for i, t in enumerate(tenants)
            for s in range(JOBS_IN_FLIGHT)
        ]
        for t in threads:
            t.start()
        # halftime snapshot: the steady-state baseline every flatness
        # and latency delta is measured against
        while time.monotonic() < half_at:
            time.sleep(0.1)
        halftime["snap"] = reg.snapshot()
        halftime["at"] = time.monotonic()
        for t in threads:
            t.join(timeout=args.seconds + 120)
        snap_end = reg.snapshot()
        # drain the tail into the hub and force one final SLO pass, so
        # short runs can't end between evaluation cadences
        ctx.telemetry_flush()
        hub = ctx.driver.telemetry
        if hub is not None:
            hub.slo.evaluate()
            slo_summary = hub.slo.summary()
            # PR 20 artifacts: the merged HLC-ordered event journal (the
            # incident timeline, rendered by `python -m sparkrdma_tpu.obs
            # --timeline LEDGER`) and the USE-method capacity report
            journal_events = hub.journal.merged()
            capacity_report = hub.capacity.capacity_report(refresh=True)
        else:
            slo_summary = {}
            journal_events = []
            capacity_report = {}

    # ---- per-tenant ledger -------------------------------------------
    total_secs = 0.0
    per_tenant = {}
    for i, t in enumerate(tenants):
        secs, p99 = _tenant_task_stats(halftime["snap"], snap_end, t)
        total_secs += secs
        per_tenant[t] = {
            "weight": weights[t],
            "jobs": stats[t]["jobs"],
            "jobs_2nd_half": stats[t]["jobs_2nd_half"],
            "by_shape": stats[t]["by_shape"],
            "failures": stats[t]["failures"][:5],
            "task_seconds_2nd_half": round(secs, 3),
            "p99_task_ms_2nd_half": p99,
        }
    weight_total = sum(weights.values())
    max_rel_dev = 0.0
    for t in tenants:
        share = per_tenant[t]["task_seconds_2nd_half"] / total_secs if total_secs else 0.0
        wshare = weights[t] / weight_total
        rel = abs(share - wshare) / wshare
        per_tenant[t]["task_seconds_share"] = round(share, 4)
        per_tenant[t]["weight_share"] = round(wshare, 4)
        per_tenant[t]["share_rel_dev"] = round(rel, 4)
        max_rel_dev = max(max_rel_dev, rel)

    # ---- HWM flatness ------------------------------------------------
    hwms = {}
    for name in ("mempool.in_use_bytes", "hbm.in_use_bytes"):
        h0, h1 = _hwm(halftime["snap"], name), _hwm(snap_end, name)
        growth = (h1 - h0) / h0 if h0 else 0.0
        hwms[name] = {
            "halftime_hwm": h0,
            "final_hwm": h1,
            "growth_pct": round(growth * 100, 2),
        }

    return {
        "per_tenant": per_tenant,
        "fairness_max_rel_dev": round(max_rel_dev, 4),
        "hwm": hwms,
        "admission": {
            k: v
            for k, v in snap_end["counters"].items()
            if k.startswith("admission.")
        },
        "metastore": {
            k: v
            for k, v in snap_end["counters"].items()
            if k.startswith("metastore.")
        },
        "slo": slo_summary,
        "journal": journal_events,
        "capacity": capacity_report,
    }


def run_quota_probe(args) -> dict:
    """Quota backpressure proof: 'probe-hog' gets a tiny mempool quota
    and must block (counters) yet keep progressing (bounded overruns),
    while the unquota'd 'probe-quiet' tenant's job latency stays near
    its solo baseline."""
    reg = get_registry()

    def quiet_jobs(ctx, n, tenant="probe-quiet"):
        rng = np.random.default_rng(args.seed + 99)
        walls = []
        for _ in range(n):
            t0 = time.perf_counter()
            _pagerank_job(ctx, rng, int(1500 * args.scale), tenant)
            walls.append(time.perf_counter() - t0)
        return float(np.median(walls))

    # solo baseline: no quotas installed, quiet tenant alone. Both
    # contexts run with mapped delivery off so fetches land in pooled
    # registered buffers — the plane the mempool quota governs.
    base = {"tpu.shuffle.mappedFetch": "false"}
    with TpuContext(
        num_executors=2, conf=TpuShuffleConf(dict(base)), task_threads=4
    ) as ctx:
        quiet_jobs(ctx, 2)  # warm
        solo = quiet_jobs(ctx, 5)

    # contended run: hog capped at ~one pooled destination buffer with a
    # short overrun deadline — every concurrent in-flight fetch group
    # beyond the first must block, yet the hog keeps crawling forward
    _quota.reset()
    conf = TpuShuffleConf(
        dict(
            base,
            **{
                "tpu.shuffle.tenancy.quota.probe-hog.mempoolBytes": "8k",
                "tpu.shuffle.tenancy.quotaBlockMaxMs": "200",
            },
        )
    )
    before = reg.snapshot(prefix="tenant.quota")
    stop = threading.Event()
    hog_jobs = {"n": 0}

    def hog():
        rng = np.random.default_rng(args.seed + 7)
        while not stop.is_set():
            try:
                _terasort_job(ctx, rng, int(2000 * args.scale), "probe-hog")
                hog_jobs["n"] += 1
            except Exception:  # noqa: BLE001 — the probe only needs load
                pass

    try:
        with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
            hog_t = threading.Thread(target=hog, name="soak-quota-hog")
            hog_t.start()
            time.sleep(0.5)  # let the hog hit its quota first
            contended = quiet_jobs(ctx, 5)
            # USE-method capacity report captured WHILE the hog is still
            # pinned at its quota: the binding resource must be the
            # quota-governed mempool, with every other resource showing
            # more headroom (docs/OBSERVABILITY.md "Event journal &
            # capacity plane")
            hub = ctx.driver.telemetry
            capacity = (
                hub.capacity.capacity_report(refresh=True)
                if hub is not None else {}
            )
            stop.set()
            hog_t.join(timeout=120)
    finally:
        _quota.reset()
    delta = reg.delta(before, prefix="tenant.quota")["counters"]
    blocks = sum(
        v for k, v in delta.items()
        if k.startswith("tenant.quota_blocks") and "probe-hog" in k
    )
    overruns = sum(
        v for k, v in delta.items()
        if k.startswith("tenant.quota_overruns") and "probe-hog" in k
    )
    return {
        "quiet_solo_median_s": round(solo, 4),
        "quiet_contended_median_s": round(contended, 4),
        "quiet_slowdown": round(contended / solo, 3) if solo else None,
        "hog_quota_blocks": blocks,
        "hog_quota_overruns": overruns,
        "hog_jobs_completed": hog_jobs["n"],
        "capacity": capacity,
    }


def run_push_rpc_probe(args) -> dict:
    """Cluster-mode (subprocess workers) two-tenant concurrent load
    with the push/merge plane on: push volume must move on the data
    plane (task protocol) and never surface as an rpc.handle_ms
    message type on the metadata plane."""
    from sparkrdma_tpu.engine.cluster import ClusterContext

    reg = get_registry()
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            "tpu.shuffle.push.enabled": "true",
            "tpu.shuffle.obs.telemetry.intervalMs": "200",
        }
    )
    before = reg.snapshot(prefix="rpc.")
    rows = int(4000 * args.scale)
    with ClusterContext(num_executors=2, conf=conf) as cluster:
        def one_job(tenant, mod):
            map_fns = [
                (lambda lo=p * rows: iter(
                    (f"k-{(lo + i) % mod}", 1) for i in range(rows)
                ))
                for p in range(4)
            ]
            out = cluster.run_map_reduce(
                map_fns, num_partitions=4,
                reduce_fn=lambda it: [sum(1 for _ in it)],
                tenant=tenant,
            )
            total = sum(c for per_worker in out for c in per_worker)
            assert total == 4 * rows, f"{tenant}: {total} != {4 * rows}"

        threads = [
            threading.Thread(target=one_job, args=(f"push-t{j}", 211 + j))
            for j in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        # the final push counters ride the NEXT worker heartbeat and the
        # NEXT driver poll after job end — poll the timeline (bounded)
        # instead of racing a fixed sleep against two timers
        pushed = 0
        poll_deadline = time.monotonic() + 10.0
        while time.monotonic() < poll_deadline:
            pushed = 0
            for windows in cluster.driver.telemetry.timeline().values():
                for w in windows:
                    for k, v in (w.get("counters") or {}).items():
                        if k.startswith("push.pushed_bytes"):
                            pushed += v
            if pushed > 0:
                break
            time.sleep(0.3)
    delta = reg.delta(before, prefix="rpc.")
    rpc_types = set()
    for key in delta["histograms"]:
        if key.startswith("rpc.handle_ms"):
            for part in key[len("rpc.handle_ms{"):-1].split(","):
                k, _, v = part.partition("=")
                if k == "type":
                    rpc_types.add(v)
    return {
        "pushed_bytes": pushed,
        "rpc_handle_types_seen": sorted(rpc_types),
        "push_in_rpc_handle_ms": bool(rpc_types - CONTROL_RPC_TYPES),
    }


# ---------------------------------------------------------------------------
def main() -> int:
    ap = argparse.ArgumentParser(description="multi-tenant shuffle soak")
    ap.add_argument("--seconds", type=float, default=60.0)
    ap.add_argument("--tenants", type=int, default=4, choices=[3, 4])
    ap.add_argument("--out", default="SOAK_r01.json")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument(
        "--strict",
        action="store_true",
        help="additionally enforce the fairness (25%%) and quota-"
        "neighborhood (10%%) bars — the acceptance-run mode; without "
        "it they are recorded but only HWM flatness, zero job "
        "failures, and no starvation gate the exit code",
    )
    ap.add_argument(
        "--skip-cluster-probe",
        action="store_true",
        help="skip the subprocess push-vs-rpc segment",
    )
    ap.add_argument(
        "--fault-plan", default="",
        help="chaos mode: install this seeded fault plan "
        "(testing/faults.py grammar) for the soak segment; the quota "
        "and push probes are skipped so the injected faults cannot "
        "leak into their baselines",
    )
    ap.add_argument("--fault-seed", type=int, default=1)
    ap.add_argument(
        "--expect-breach", action="store_true",
        help="with --fault-plan: gate on an SLO breach firing AND the "
        "automated diagnosis naming the injected seam (instead of the "
        "default zero-breach gate)",
    )
    ap.add_argument(
        "--slo-task-p99-ms", type=int, default=0,
        help="install the p99 task-latency objective at this target "
        "(tpu.shuffle.obs.slo.taskP99Ms) for the soak segment",
    )
    args = ap.parse_args()

    ledger = {
        "args": {
            "seconds": args.seconds,
            "tenants": args.tenants,
            "scale": args.scale,
            "seed": args.seed,
            "strict": args.strict,
            "fault_plan": [args.fault_plan],
            "expect_breach": args.expect_breach,
            "slo_task_p99_ms": args.slo_task_p99_ms,
        },
    }
    ledger["soak"] = run_soak(args)
    ledger["slo"] = ledger["soak"].pop("slo", {})
    # top level so `python -m sparkrdma_tpu.obs --timeline LEDGER` finds
    # the merged event journal directly
    ledger["journal"] = ledger["soak"].pop("journal", [])
    ledger["capacity"] = ledger["soak"].pop("capacity", {})
    chaos_mode = bool(args.fault_plan)
    if not chaos_mode:
        ledger["quota_probe"] = run_quota_probe(args)
    if not args.skip_cluster_probe and not chaos_mode:
        try:
            ledger["push_rpc_probe"] = run_push_rpc_probe(args)
        except Exception as e:  # noqa: BLE001 — recorded, CI-gated below
            ledger["push_rpc_probe"] = {
                "error": f"{type(e).__name__}: {e}"
            }

    # ---- verdicts: every bar is one slo.judge() record ----------------
    verdicts = []
    checks = {}

    def check(key, verdict):
        verdicts.append(verdict)
        checks[key] = verdict["ok"]

    soak = ledger["soak"]
    check("zero_job_failures", judge(
        "zero-job-failures",
        sum(len(v["failures"]) for v in soak["per_tenant"].values()),
        0, "eq"))
    check("no_starved_tenant", judge(
        "no-starved-tenant",
        min(v["jobs_2nd_half"] for v in soak["per_tenant"].values()),
        1, "ge"))
    check("hwm_flat", judge(
        "hwm-flat",
        max(h["growth_pct"] for h in soak["hwm"].values()),
        10.0, "le", note="steady-state HWM growth pct, 2nd half"))
    # per-tenant p99 from the same exceedance identity the online
    # latency objective enforces — recorded always, never a gate here
    # (chaos mode exists to violate it; the gate is the breach check)
    if args.slo_task_p99_ms:
        for t, row in sorted(soak["per_tenant"].items()):
            verdicts.append(judge(
                f"task-p99-{t}", row["p99_task_ms_2nd_half"],
                args.slo_task_p99_ms,
                "le", note="recorded only; gated online via burn rate"))
    if "quota_probe" in ledger:
        check("quota_backpressure_engaged", judge(
            "quota-backpressure-engaged",
            min(ledger["quota_probe"]["hog_quota_blocks"],
                ledger["quota_probe"]["hog_jobs_completed"]),
            1, "ge",
            note="hog must both block on quota and keep progressing"))
        # USE-plane capacity gate: under quota backpressure the report
        # must name the quota-governed mempool as THE binding resource
        # (argmax utilization — every other resource shows more headroom)
        binding = (ledger["quota_probe"].get("capacity") or {}).get(
            "binding") or {}
        check("capacity_binding_is_mempool", judge(
            "capacity-binding-is-mempool",
            int(binding.get("resource") == "mempool"), 1, "eq",
            note=f"binding={binding.get('resource', 'none')} "
                 f"headroom={binding.get('headroom', 'n/a')}"))
    probe = ledger.get("push_rpc_probe", {})
    if "error" not in probe and probe:
        check("push_absent_from_rpc_handle_ms", judge(
            "push-absent-from-rpc-handle-ms",
            int(not probe["push_in_rpc_handle_ms"]
                and probe["pushed_bytes"] > 0),
            1, "eq"))
    # ---- SLO-engine gates: breaches answer to the fault plan ----------
    breach_count = int(ledger["slo"].get("breach_count", 0))
    diagnoses = ledger["slo"].get("diagnosis_records", [])
    if chaos_mode and args.expect_breach:
        check("slo_breach_observed", judge(
            "slo-breach-observed", breach_count, 1, "ge",
            note="seeded fault plan must trip the latency objective"))
        want_peer = ""
        for part in args.fault_plan.replace(":", ",").split(","):
            if part.startswith("peer="):
                want_peer = part[len("peer="):]
        named = 0
        for diag in diagnoses:
            top = diag.get("top_cause") or {}
            if (top.get("cause") == "injected-fault"
                    and (not want_peer or top.get("executor") == want_peer)
                    and top.get("category")):
                named = 1
        check("diagnosis_names_injected_seam", judge(
            "diagnosis-names-injected-seam", named, 1, "eq",
            note=f"top cause must be the injected fault on "
                 f"{want_peer or 'any executor'} with a stage category"))
    elif not chaos_mode:
        check("zero_slo_breaches", judge(
            "zero-slo-breaches", breach_count, 0, "eq",
            note="healthy soak must not page"))
        check("zero_diagnoses", judge(
            "zero-diagnoses", len(diagnoses), 0, "eq"))
        # quiet-journal gate: a healthy soak's merged event journal must
        # carry no pages and no lease takeovers
        noisy = sum(
            1 for e in ledger["journal"]
            if e.get("kind") in ("slo.page", "meta.takeover")
        )
        check("journal_quiet", judge(
            "journal-quiet", noisy, 0, "eq",
            note="no slo.page / meta.takeover events in a healthy soak"))
    # ---- control-plane HA gate: driver killed mid-job -----------------
    # (docs/RESILIENCE.md "Control-plane HA"): the metadata hub was
    # wiped while jobs were in flight, so on top of the zero-failure
    # bar above, executors must have re-ADOPTED committed map outputs
    # into the rebuilt hub — re-publish, never recompute
    if chaos_mode and "driver:kill" in args.fault_plan:
        adoptions = sum(
            v for k, v in soak.get("metastore", {}).items()
            if k.startswith("metastore.adoptions")
        )
        check("driver_kill_readopted", judge(
            "driver-kill-readopted", adoptions, 1, "ge",
            note="post-wipe publishes carrying the new generation must "
                 "land as adoptions, not recomputes"))
        # causal-order gate: the merged HLC order must reproduce the
        # incident chain kill -> takeover -> adoption (the journal is
        # already sorted by (hlc, origin, seq))
        kinds = [e.get("kind") for e in ledger["journal"]]
        order_ok = 0
        if "driver.kill" in kinds:
            ki = kinds.index("driver.kill")
            ti = next((i for i in range(ki + 1, len(kinds))
                       if kinds[i] == "meta.takeover"), -1)
            if ti > ki:
                ai = next((i for i in range(ti + 1, len(kinds))
                           if kinds[i] == "meta.adopt"), -1)
                order_ok = int(ai > ti)
        check("journal_kill_takeover_adopt_order", judge(
            "journal-kill-takeover-adopt-order", order_ok, 1, "eq",
            note="merged journal HLC order must show driver.kill before "
                 "meta.takeover before meta.adopt"))
    if args.strict:
        check("fairness_within_25pct", judge(
            "fairness-within-25pct", soak["fairness_max_rel_dev"],
            0.25, "le"))
        slowdown = ledger["quota_probe"]["quiet_slowdown"]
        cores = os.cpu_count() or 1
        if cores >= 4:
            check("quiet_within_10pct_of_solo", judge(
                "quiet-within-10pct-of-solo", slowdown, 1.10, "le"))
        else:
            # on a rig with fewer cores than the two concurrent
            # workloads need, the quiet tenant pays raw CPU contention
            # that no memory-quota backpressure can remove — record the
            # ratio, enforce the bar only where it is measurable
            ledger["quota_probe"]["quiet_isolation_note"] = (
                f"10% neighbor-isolation bar not enforced: {cores} core(s)"
                " < 4, quiet tenant's slowdown is CPU contention, not"
                " quota spillover"
            )
    ledger["slo"]["verdicts"] = verdicts
    ledger["checks"] = checks
    ledger["ok"] = all(checks.values())

    with open(args.out, "w") as f:
        json.dump(ledger, f, indent=2, sort_keys=True)
    print(json.dumps({"ok": ledger["ok"], "checks": checks, "out": args.out}))
    return 0 if ledger["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
