import time, numpy as np
import jax, jax.numpy as jnp
from sparkrdma_tpu.ops.pallas_sort import sort_flat

N = 1 << 25
rng = np.random.default_rng(0)
keys = rng.integers(0, 1 << 32, size=N, dtype=np.uint32)
dev = jax.devices()[0]
xk = jax.device_put(keys, dev)
print("device:", dev, flush=True)

f = jax.jit(lambda v: sort_flat(v).sum())
t0 = time.perf_counter()
r = float(f(xk))
print(f"compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
# correctness on chip
got = np.asarray(jax.jit(lambda v: sort_flat(v))(xk))
assert np.array_equal(got, np.sort(keys)), "WRONG"
print("correct on chip", flush=True)
for _ in range(3):
    t0 = time.perf_counter(); float(f(xk)); t = time.perf_counter()-t0
    print(f"per-dispatch: {t:.3f}s -> {N*4/t/1e9:.2f} GB/s", flush=True)
f_flat = jax.jit(lambda v: jnp.sort(v).sum())
float(f_flat(xk))
t0 = time.perf_counter(); float(f_flat(xk)); t = time.perf_counter()-t0
print(f"flat jnp.sort per-dispatch: {t:.3f}s -> {N*4/t/1e9:.2f} GB/s", flush=True)
