"""End-to-end demo: host shuffle engine + device exchange plane.

Run directly (any machine; device parts use whatever jax.devices()
provides — force an 8-device CPU farm with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu):

    python examples/demo_shuffle.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def demo_engine_wordcount():
    from sparkrdma_tpu.engine.context import TpuContext

    text = (
        "the quick brown fox jumps over the lazy dog "
        "the dog barks the fox runs"
    ).split()
    with TpuContext(num_executors=2) as ctx:
        counts = (
            ctx.parallelize(text * 500, 4)
            .map(lambda w: (w, 1))
            .reduce_by_key(lambda a, b: a + b)
            .collect()
        )
    top = sorted(counts, key=lambda kv: -kv[1])[:3]
    print("wordcount top-3:", top)
    assert dict(counts)["the"] == 2000


def demo_engine_join():
    from sparkrdma_tpu.engine.context import TpuContext

    with TpuContext(num_executors=2) as ctx:
        users = ctx.parallelize([(i, f"user{i}") for i in range(100)], 4)
        orders = ctx.parallelize([(i % 100, f"order{i}") for i in range(300)], 4)
        joined = users.join(orders, num_partitions=4).collect()
    print("join rows:", len(joined), "sample:", joined[0])
    assert len(joined) == 300


def demo_device_terasort():
    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    keys = np.random.default_rng(0).integers(0, 1 << 32, 1 << 16, dtype=np.uint32)
    out = TeraSorter(make_mesh()).sort(keys)
    assert (np.diff(out.astype(np.int64)) >= 0).all()
    print("device terasort: sorted", len(out), "keys over", end=" ")
    import jax

    print(len(jax.devices()), "device(s)")


def demo_device_shuffle_io():
    import jax.numpy as jnp

    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    try:
        driver.register_shuffle(
            BaseShuffleHandle(shuffle_id=1, num_maps=2, partitioner=HashPartitioner(2))
        )
        io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
        io0.publish_device_blocks(1, {0: jnp.arange(256, dtype=jnp.uint8)})
        io1.publish_device_blocks(1, {1: jnp.full((128,), 9, jnp.uint8)})
        got = io0.fetch_device_blocks(1, 0, 2)
        print(
            "device shuffle io: fetched partitions",
            sorted(got),
            "bytes",
            [b.length for bufs in got.values() for b in bufs],
        )
        for bufs in got.values():
            for b in bufs:
                b.free()
        io0.stop()
        io1.stop()
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


if __name__ == "__main__":
    demo_engine_wordcount()
    demo_engine_join()
    demo_device_terasort()
    demo_device_shuffle_io()
    print("demo OK")
