"""Long-context training demo: sp-sharded transformer steps with both
sequence-parallel schedules.

Runs on the 8-device virtual CPU mesh (no TPU slice needed) and shows
the two ways the framework trains across a sharded sequence axis:

- ``attn="ring"``: kv blocks hop neighbour-to-neighbour (ppermute),
  O(seq/sp) memory, autodiff through the online softmax;
- ``attn="ulysses"``: two all-to-alls re-shard seq<->heads and the
  full-sequence attention per head group runs through the Pallas flash
  kernel, whose custom VJP keeps the backward at flash memory cost.

Both schedules step the SAME initial parameters on the SAME batch and
must agree with each other step for step (they compute identical math
on different communication schedules).

Usage: python examples/train_long_context.py [--steps 5]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from sparkrdma_tpu.models.transformer_step import (
    TransformerStep,
    init_params,
    make_training_mesh,
)


def main(steps: int = 5) -> None:
    mesh = make_training_mesh()
    print(f"mesh: {dict(mesh.shape)}")
    d_model, heads = 32, 4
    params = init_params(d_model, n_heads=heads, d_hidden=64,
                         tp=mesh.shape["tp"], seed=0)
    rng = np.random.default_rng(0)
    b, s = 4, 64  # sequence sharded over sp: each shard holds s/sp
    x = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(b, s, d_model)).astype(np.float32))

    histories = {}
    for schedule in ("ring", "ulysses"):
        step = TransformerStep(mesh, n_heads=heads, lr=0.2, attn=schedule)
        pl, xl, yl = step.place(params, x, y)
        losses = []
        for _ in range(steps):
            loss, pl = step.step(pl, xl, yl)
            losses.append(float(loss))
        histories[schedule] = losses
        print(f"{schedule:8s} losses: " + " ".join(f"{v:.5f}" for v in losses))

    drift = max(
        abs(a - b) for a, b in zip(histories["ring"], histories["ulysses"])
    )
    assert drift < 1e-4, f"schedules diverged: {drift}"
    assert histories["ring"][-1] < histories["ring"][0], "loss did not drop"
    print(f"schedules agree (max drift {drift:.2e}); loss decreased. demo OK")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    main(ap.parse_args().steps)
