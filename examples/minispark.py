"""MiniSpark — a FOREIGN engine proving the drop-in shuffle SPI.

This file is deliberately a third-party codebase in miniature: a tiny
PySpark-shaped engine with its own conf, its own partitioner class, its
own builtin hash shuffle, and user-facing RDD operations. It imports
NOTHING from sparkrdma_tpu at module level. Exactly like Spark's

    spark.shuffle.manager = org.apache.spark.shuffle.rdma.RdmaShuffleManager

(reference README.md:52-58, RdmaShuffleManager.scala:40-41), setting ONE
config key

    engine.shuffle.manager = sparkrdma_tpu.shuffle.TpuShuffleManager

swaps the entire shuffle plane for the TPU-native framework, resolved
dynamically by class path. User job code is byte-identical under both
managers; the engine drives only the documented SPI surface:

    manager = Manager(conf_dict, is_driver=..., executor_id=...)
    handle  = Handle(shuffle_id, num_maps, partitioner)   # duck-typed
    manager.register_shuffle(handle)                       # driver
    writer  = manager.get_writer(handle, map_id); writer.write(it); writer.stop(True)
    manager.finalize_maps(shuffle_id)                      # per executor
    reader  = manager.get_reader(handle, lo, hi); reader.read()
    manager.unregister_shuffle(shuffle_id); manager.stop()

(the same verbs Spark's ShuffleManager trait exposes,
RdmaShuffleManager.scala:187-332).
"""

from __future__ import annotations

import importlib
from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional, Tuple


# ----------------------------------------------------------------------
# the foreign engine's own types (no framework imports)
# ----------------------------------------------------------------------
class MiniConf(dict):
    """PySpark-style string conf."""

    def set(self, key: str, value: str) -> "MiniConf":
        self[key] = value
        return self


class MiniHashPartitioner:
    """The engine's OWN partitioner — satisfies the SPI duck type
    (``num_partitions`` attribute + ``partition(key) -> int``)."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def partition(self, key) -> int:
        return hash(key) % self.num_partitions


class _MiniHandle:
    """The engine's own shuffle handle — carries what the SPI documents:
    shuffle_id, num_maps, partitioner (duck-typed, like Spark's
    ShuffleDependency attributes, RdmaShuffleManager.scala:223-227)."""

    def __init__(self, shuffle_id: int, num_maps: int, partitioner):
        self.shuffle_id = shuffle_id
        self.num_maps = num_maps
        self.partitioner = partitioner
        # SPI-optional attributes the framework reader understands
        self.serializer = None
        self.aggregator = None
        self.key_ordering = None
        self.map_side_combine = False


# ----------------------------------------------------------------------
# builtin shuffle (what the engine ships with; the thing being replaced)
# ----------------------------------------------------------------------
class _BuiltinWriter:
    def __init__(self, store, shuffle_id, map_id, partitioner):
        self._store = store
        self._sid = shuffle_id
        self._map = map_id
        self._part = partitioner

    def write(self, records: Iterable[Tuple]) -> None:
        buckets = defaultdict(list)
        for k, v in records:
            buckets[self._part.partition(k)].append((k, v))
        self._store[(self._sid, self._map)] = dict(buckets)

    def stop(self, success: bool) -> None:
        if not success:
            self._store.pop((self._sid, self._map), None)


class _BuiltinReader:
    def __init__(self, store, shuffle_id, num_maps, lo, hi):
        self._store = store
        self._sid = shuffle_id
        self._num_maps = num_maps
        self._lo, self._hi = lo, hi

    def read(self):
        for m in range(self._num_maps):
            buckets = self._store.get((self._sid, m), {})
            for p in range(self._lo, self._hi):
                yield from buckets.get(p, [])


class BuiltinShuffleManager:
    """The engine's stock single-process hash shuffle."""

    def __init__(self, conf, is_driver: bool, executor_id: str = "driver"):
        self._store: Dict = {}

    def register_shuffle(self, handle):
        return handle

    def get_writer(self, handle, map_id: int):
        return _BuiltinWriter(
            self._store, handle.shuffle_id, map_id, handle.partitioner
        )

    def finalize_maps(self, shuffle_id: int) -> None:
        pass

    def get_reader(self, handle, lo: int, hi: int):
        return _BuiltinReader(
            self._store, handle.shuffle_id, handle.num_maps, lo, hi
        )

    def unregister_shuffle(self, shuffle_id: int) -> None:
        self._store = {
            k: v for k, v in self._store.items() if k[0] != shuffle_id
        }

    def stop(self) -> None:
        self._store.clear()


def _resolve_manager_class(class_path: str):
    """``pkg.module.Class`` -> class, the spark.shuffle.manager lookup."""
    mod_name, _, cls_name = class_path.rpartition(".")
    return getattr(importlib.import_module(mod_name), cls_name)


# ----------------------------------------------------------------------
# the engine
# ----------------------------------------------------------------------
class MiniSparkContext:
    """2-executor local engine; the shuffle plane is whatever
    ``engine.shuffle.manager`` names."""

    NUM_EXECUTORS = 2

    def __init__(self, conf: Optional[MiniConf] = None):
        self.conf = conf or MiniConf()
        class_path = self.conf.get("engine.shuffle.manager", "builtin")
        self._next_shuffle = 0
        if class_path == "builtin":
            self.driver = BuiltinShuffleManager(self.conf, is_driver=True)
            # the builtin store is process-wide; executors share it
            self.executors = [self.driver] * self.NUM_EXECUTORS
        else:
            manager_cls = _resolve_manager_class(class_path)
            # the SPI constructor contract: (conf_mapping, is_driver,
            # executor_id). The engine passes its OWN conf mapping;
            # unknown engine.* keys are ignored by the manager, and the
            # driver writes its negotiated port back into the mapping
            # (SparkConf semantics) so executors built afterwards
            # inherit it.
            self.driver = manager_cls(self.conf, is_driver=True)
            self.executors = [
                manager_cls(
                    self.conf, is_driver=False, executor_id=f"mini-{i}"
                )
                for i in range(self.NUM_EXECUTORS)
            ]

    def parallelize(self, data: List[Tuple], num_slices: int = 4) -> "MiniRDD":
        chunk = max(1, (len(data) + num_slices - 1) // num_slices)
        return MiniRDD(
            self, [data[i : i + chunk] for i in range(0, len(data), chunk)]
        )

    # -- the engine's shuffle execution, SPI verbs only ------------------
    def _run_shuffle(self, slices, partitioner) -> List[List[Tuple]]:
        sid = self._next_shuffle
        self._next_shuffle += 1
        # register_shuffle returns the manager's canonical handle (the
        # reference picks its own handle class there too); the engine
        # must use it for every subsequent SPI call
        handle = self.driver.register_shuffle(
            _MiniHandle(sid, num_maps=len(slices), partitioner=partitioner)
        )
        try:
            for map_id, part in enumerate(slices):
                ex = self.executors[map_id % len(self.executors)]
                w = ex.get_writer(handle, map_id)
                w.write(iter(part))
                w.stop(True)
            for ex in self.executors:
                ex.finalize_maps(sid)
            n = partitioner.num_partitions
            out: List[List[Tuple]] = []
            for p in range(n):
                ex = self.executors[p % len(self.executors)]
                out.append(list(ex.get_reader(handle, p, p + 1).read()))
            return out
        finally:
            self.driver.unregister_shuffle(sid)
            for ex in self.executors:
                if ex is not self.driver:
                    ex.unregister_shuffle(sid)

    def stop(self) -> None:
        for ex in self.executors:
            if ex is not self.driver:
                ex.stop()
        self.driver.stop()


class MiniRDD:
    """User-facing slice of the API: map / reduceByKey / groupByKey /
    collect — job code never sees the shuffle manager."""

    def __init__(self, ctx: MiniSparkContext, slices: List[List[Tuple]]):
        self._ctx = ctx
        self._slices = slices

    def map(self, fn: Callable) -> "MiniRDD":
        return MiniRDD(self._ctx, [[fn(x) for x in s] for s in self._slices])

    def reduce_by_key(self, fn: Callable, num_partitions: int = 4) -> "MiniRDD":
        parts = self._ctx._run_shuffle(
            self._slices, MiniHashPartitioner(num_partitions)
        )
        out = []
        for part in parts:
            acc: Dict = {}
            for k, v in part:
                acc[k] = fn(acc[k], v) if k in acc else v
            out.append(list(acc.items()))
        return MiniRDD(self._ctx, out)

    def group_by_key(self, num_partitions: int = 4) -> "MiniRDD":
        parts = self._ctx._run_shuffle(
            self._slices, MiniHashPartitioner(num_partitions)
        )
        out = []
        for part in parts:
            acc: Dict = defaultdict(list)
            for k, v in part:
                acc[k].append(v)
            out.append([(k, sorted(vs)) for k, vs in acc.items()])
        return MiniRDD(self._ctx, out)

    def collect(self) -> List[Tuple]:
        return [x for s in self._slices for x in s]


# ----------------------------------------------------------------------
def wordcount_job(ctx: MiniSparkContext) -> List[Tuple[str, int]]:
    """A user job. NOTE: it references only engine API — identical under
    the builtin and the TPU-native shuffle manager."""
    words = (
        ["the", "quick", "brown", "fox"] * 250
        + ["jumps", "over", "the", "lazy", "dog"] * 200
    )
    rdd = ctx.parallelize([(w, 1) for w in words], num_slices=8)
    counts = rdd.reduce_by_key(lambda a, b: a + b, num_partitions=4)
    return sorted(counts.collect())


if __name__ == "__main__":
    # stock engine
    ctx = MiniSparkContext()
    stock = wordcount_job(ctx)
    ctx.stop()
    # one key flips the shuffle plane to the TPU-native framework
    conf = MiniConf().set(
        "engine.shuffle.manager", "sparkrdma_tpu.shuffle.TpuShuffleManager"
    )
    ctx = MiniSparkContext(conf)
    swapped = wordcount_job(ctx)
    ctx.stop()
    assert stock == swapped, "drop-in shuffle changed job results"
    print("drop-in OK:", swapped[:3], "...")
