/* foreign_client — a C-only shuffle endpoint on the native wire.
 *
 * Proof that the framework's transport boundary is language-neutral
 * the way the reference's DiSNI C ABI is (reference pom.xml:67-81:
 * any JVM can consume libdisni; here any language that can open a TCP
 * socket can be a full shuffle peer). This client implements the wire
 * of sparkrdma_tpu/transport/wire.py + rpc.py from scratch — no
 * Python, no framework code — and against a live Python driver +
 * executor it:
 *
 *   1. HELLOs the driver and introduces itself (ManagerHello RPC),
 *   2. PUBLISHES a partition of its own registered memory
 *      (PublishPartitionLocations, num_map_outputs=1) which Python
 *      reducers then fetch with one-sided READs served by THIS file,
 *   3. FETCHES the locations of a Python-published shuffle and pulls
 *      the real bytes with a one-sided READ_REQ.
 *
 * Frames (all big-endian; see transport.cpp:20-31):
 *   SEND      = op(1) payload_len(4) payload        -- RPC segments
 *   READ_REQ  = op(1) req_id(8) n(4) n x [mkey(4) addr(8) len(4)]
 *   READ_RESP = op(1) req_id(8) total_len(8) payload
 *   READ_ERR  = op(1) req_id(8) msg_len(4) msg
 *   HELLO     = op(1) word(4)=(kind<<24)|port id_len(2) executor_id
 *   GOODBYE   = op(1)
 * RPC segment = msg_type(4) payload_len(4) payload  (rpc.py SEG_HEADER)
 *   PUBLISH(0) payload = is_last(1) shuffle(4) partition(4) nmaps(4) locs
 *   FETCH(1)   payload = manager_id shuffle(4) start(4) end(4)
 *   MHELLO(2)  payload = manager_id
 *   manager_id = hlen(2) host port(4) idlen(2) executor_id
 *   location   = manager_id partition(4) addr(8) len(4) mkey(4)
 *
 * Usage: foreign_client <driver_host> <driver_port> <fetch_shuffle>
 *                       <publish_shuffle> <out_path>
 * Prints READY after the listener is up, FETCHED_OK <n> after the
 * remote bytes are on disk, and serves READs until stdin closes.
 */

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#define OP_SEND 1
#define OP_READ_REQ 2
#define OP_READ_RESP 3
#define OP_READ_ERR 4
#define OP_HELLO 5
#define OP_GOODBYE 6

#define MSG_PUBLISH 0
#define MSG_FETCH 1
#define MSG_MHELLO 2

#define MY_ID "c-client-0"
#define MY_MKEY 1u
#define PATTERN_LEN (64 * 1024)
#define MAX_FDS 32
#define MAX_LOCS 64

static uint8_t pattern[PATTERN_LEN];

/* ---------- byte order ---------- */
static void st16(uint8_t *p, uint16_t v) { p[0] = v >> 8; p[1] = v; }
static void st32(uint8_t *p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
static void st64(uint8_t *p, uint64_t v) { st32(p, v >> 32); st32(p + 4, (uint32_t)v); }
static uint16_t ld16(const uint8_t *p) { return ((uint16_t)p[0] << 8) | p[1]; }
static uint32_t ld32(const uint8_t *p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
}
static uint64_t ld64(const uint8_t *p) {
  return ((uint64_t)ld32(p) << 32) | ld32(p + 4);
}

/* ---------- io ---------- */
static int read_full(int fd, void *buf, size_t n) {
  uint8_t *p = buf;
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r == 0) return -1;               /* peer closed */
    if (r < 0) { if (errno == EINTR) continue; return -1; }
    p += r; n -= (size_t)r;
  }
  return 0;
}
static int write_full(int fd, const void *buf, size_t n) {
  const uint8_t *p = buf;
  while (n) {
    ssize_t r = write(fd, p, n);
    if (r < 0) { if (errno == EINTR) continue; return -1; }
    p += r; n -= (size_t)r;
  }
  return 0;
}

static int dial(const char *host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &a.sin_addr) != 1 ||
      connect(fd, (struct sockaddr *)&a, sizeof a) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

/* ---------- frame builders ---------- */
static int send_hello(int fd, int kind, int my_port) {
  uint8_t h[1 + 4 + 2 + sizeof(MY_ID) - 1];
  h[0] = OP_HELLO;
  st32(h + 1, ((uint32_t)kind << 24) | ((uint32_t)my_port & 0xFFFF));
  st16(h + 5, sizeof(MY_ID) - 1);
  memcpy(h + 7, MY_ID, sizeof(MY_ID) - 1);
  return write_full(fd, h, sizeof h);
}

/* manager_id of THIS client into buf; returns length */
static size_t put_mid(uint8_t *b, const char *host, int port) {
  size_t hl = strlen(host), il = sizeof(MY_ID) - 1, o = 0;
  st16(b + o, (uint16_t)hl); o += 2;
  memcpy(b + o, host, hl); o += hl;
  st32(b + o, (uint32_t)port); o += 4;
  st16(b + o, (uint16_t)il); o += 2;
  memcpy(b + o, MY_ID, il); o += il;
  return o;
}

/* wrap one RPC segment in a SEND frame and ship it */
static int send_rpc(int fd, int msg_type, const uint8_t *payload, size_t n) {
  uint8_t hdr[1 + 4 + 4 + 4];
  hdr[0] = OP_SEND;
  st32(hdr + 1, (uint32_t)(8 + n));      /* SEND payload = segment */
  st32(hdr + 5, (uint32_t)msg_type);     /* SEG_HEADER msg_type */
  st32(hdr + 9, (uint32_t)n);            /* SEG_HEADER payload_len */
  if (write_full(fd, hdr, sizeof hdr)) return -1;
  return write_full(fd, payload, n);
}

/* ---------- parsed location of a fetched block ---------- */
typedef struct {
  char host[128];
  int port;
  int partition;
  uint64_t addr;
  uint32_t len;
  uint32_t mkey;
} Loc;

static Loc locs[MAX_LOCS];
static int nlocs = 0;
static int fetch_done = 0; /* saw is_last publish for fetch_shuffle */

/* parse PUBLISH segment payload; collect locations for want_shuffle */
static void parse_publish(const uint8_t *p, size_t n, int want_shuffle) {
  if (n < 13) return;
  int is_last = p[0];
  int shuffle = (int)ld32(p + 1);
  size_t o = 13; /* skip is_last, shuffle, partition, num_map_outputs */
  while (o + 2 <= n && nlocs < MAX_LOCS) {
    uint16_t hl = ld16(p + o); o += 2;
    if (o + hl + 4 + 2 > n) break;
    Loc *L = &locs[nlocs];
    size_t cl = hl < sizeof L->host - 1 ? hl : sizeof L->host - 1;
    memcpy(L->host, p + o, cl); L->host[cl] = 0; o += hl;
    L->port = (int)ld32(p + o); o += 4;
    uint16_t il = ld16(p + o); o += 2 + il; /* skip executor id */
    if (o + 4 + 16 > n) break;
    L->partition = (int)ld32(p + o); o += 4;
    L->addr = ld64(p + o); o += 8;
    L->len = ld32(p + o); o += 4;
    L->mkey = ld32(p + o); o += 4;
    if (shuffle == want_shuffle) nlocs++;
  }
  if (shuffle == want_shuffle && is_last) fetch_done = 1;
}

/* serve one READ_REQ arriving on fd out of our registered pattern */
static int serve_read(int fd) {
  uint8_t h[12];
  if (read_full(fd, h, 12)) return -1;
  uint64_t req_id = ld64(h);
  uint32_t n = ld32(h + 8);
  if (n > 64) return -1;
  uint8_t blocks[64 * 16];
  if (read_full(fd, blocks, (size_t)n * 16)) return -1;
  uint64_t total = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint32_t mkey = ld32(blocks + i * 16);
    uint64_t addr = ld64(blocks + i * 16 + 4);
    uint32_t len = ld32(blocks + i * 16 + 12);
    /* two-sided check: addr + len can wrap uint64 */
    if (mkey != MY_MKEY || addr > PATTERN_LEN || len > PATTERN_LEN - addr) {
      const char *msg = "bad mkey/bounds";
      uint8_t e[13];
      e[0] = OP_READ_ERR;
      st64(e + 1, req_id);
      st32(e + 9, (uint32_t)strlen(msg));
      if (write_full(fd, e, 13) || write_full(fd, msg, strlen(msg)))
        return -1;
      return 0;
    }
    total += len;
  }
  uint8_t r[17];
  r[0] = OP_READ_RESP;
  st64(r + 1, req_id);
  st64(r + 9, total);
  if (write_full(fd, r, 17)) return -1;
  for (uint32_t i = 0; i < n; i++) {
    uint64_t addr = ld64(blocks + i * 16 + 4);
    uint32_t len = ld32(blocks + i * 16 + 12);
    if (write_full(fd, pattern + addr, len)) return -1;
  }
  return 0;
}

/* consume one frame from fd; returns -1 to close the connection */
static int handle_frame(int fd, int fetch_shuffle) {
  uint8_t op;
  if (read_full(fd, &op, 1)) return -1;
  switch (op) {
    case OP_HELLO: {
      uint8_t h[6];
      if (read_full(fd, h, 6)) return -1;
      uint16_t il = ld16(h + 4);
      uint8_t id[512];
      if (il > sizeof id || read_full(fd, id, il)) return -1;
      return 0;
    }
    case OP_SEND: {
      uint8_t l4[4];
      if (read_full(fd, l4, 4)) return -1;
      uint32_t len = ld32(l4);
      if (len > (1u << 22)) return -1;
      uint8_t *seg = malloc(len);
      if (!seg || read_full(fd, seg, len)) { free(seg); return -1; }
      if (len >= 8) {
        uint32_t t = ld32(seg), pl = ld32(seg + 4);
        if (pl <= len - 8 && t == MSG_PUBLISH)
          parse_publish(seg + 8, pl, fetch_shuffle);
        /* MSG_ANNOUNCE and others: membership gossip, ignored */
      }
      free(seg);
      return 0;
    }
    case OP_READ_REQ:
    case 9: /* READ_REQ2: same layout; we always stream (wire.py:31-35) */
      return serve_read(fd);
    case OP_GOODBYE:
      return -1;
    default:
      fprintf(stderr, "foreign_client: unexpected op %d\n", op);
      return -1;
  }
}

/* pull every fetched location's bytes into out, partition-ordered */
static int pull_blocks(const char *out_path, int my_port) {
  FILE *out = fopen(out_path, "wb");
  if (!out) return -1;
  uint64_t total = 0;
  /* partitions ascending so the file is deterministic; a partition
   * may carry SEVERAL locations (one per map output) — consume the
   * minimum-partition unconsumed entry until none remain */
  for (;;) {
    int next = -1;
    for (int i = 0; i < nlocs; i++)
      if (locs[i].partition >= 0 &&
          (next == -1 || locs[i].partition < locs[next].partition))
        next = i;
    if (next == -1) break;
    Loc *L = &locs[next];
    int fd = dial(L->host, L->port);
    if (fd < 0) { fclose(out); return -1; }
    if (send_hello(fd, 1 /* data */, my_port)) { close(fd); fclose(out); return -1; }
    uint8_t rq[13 + 16];
    rq[0] = OP_READ_REQ;
    st64(rq + 1, 42);
    st32(rq + 9, 1);
    st32(rq + 13, L->mkey);
    st64(rq + 17, L->addr);
    st32(rq + 25, L->len);
    if (write_full(fd, rq, sizeof rq)) { close(fd); fclose(out); return -1; }
    uint8_t rh[17];
    if (read_full(fd, rh, 17) || rh[0] != OP_READ_RESP) {
      close(fd); fclose(out); return -1;
    }
    uint64_t got = ld64(rh + 9);
    uint8_t *body = malloc(got);
    if (!body || read_full(fd, body, got)) { free(body); close(fd); fclose(out); return -1; }
    fwrite(body, 1, got, out);
    total += got;
    free(body);
    uint8_t bye = OP_GOODBYE;
    write_full(fd, &bye, 1);
    close(fd);
    L->partition = -1;            /* consumed */
  }
  fclose(out);
  printf("FETCHED_OK %llu\n", (unsigned long long)total);
  fflush(stdout);
  return 0;
}

int main(int argc, char **argv) {
  if (argc != 6) {
    fprintf(stderr,
            "usage: %s driver_host driver_port fetch_shuffle "
            "publish_shuffle out_path\n", argv[0]);
    return 2;
  }
  const char *driver_host = argv[1];
  int driver_port = atoi(argv[2]);
  int fetch_shuffle = atoi(argv[3]);
  int publish_shuffle = atoi(argv[4]);
  const char *out_path = argv[5];
  for (int i = 0; i < PATTERN_LEN; i++) pattern[i] = (uint8_t)(i * 31 + 7);

  /* listener: the driver connects BACK here for announces + replies,
   * and Python reducers connect here to READ our published block */
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (bind(lfd, (struct sockaddr *)&a, sizeof a) || listen(lfd, 16)) {
    perror("listen");
    return 1;
  }
  socklen_t alen = sizeof a;
  getsockname(lfd, (struct sockaddr *)&a, &alen);
  int my_port = ntohs(a.sin_port);

  int dfd = dial(driver_host, driver_port);
  if (dfd < 0) { perror("dial driver"); return 1; }
  if (send_hello(dfd, 0 /* rpc */, my_port)) return 1;

  uint8_t buf[1024];
  size_t n = put_mid(buf, "127.0.0.1", my_port); /* ManagerHello */
  if (send_rpc(dfd, MSG_MHELLO, buf, n)) return 1;

  /* publish partition 0 of our registered pattern (writer publish:
   * partition_id sentinel -1, one map output -> completes the barrier) */
  uint8_t pub[1024];
  size_t o = 0;
  pub[o++] = 1;                      /* is_last */
  st32(pub + o, (uint32_t)publish_shuffle); o += 4;
  st32(pub + o, (uint32_t)-1); o += 4;
  st32(pub + o, 1); o += 4;          /* num_map_outputs */
  o += put_mid(pub + o, "127.0.0.1", my_port);
  st32(pub + o, 0); o += 4;          /* partition_id */
  st64(pub + o, 0); o += 8;          /* addr */
  st32(pub + o, PATTERN_LEN); o += 4;
  st32(pub + o, MY_MKEY); o += 4;
  if (send_rpc(dfd, MSG_PUBLISH, pub, o)) return 1;

  /* request the Python-published shuffle's locations */
  o = put_mid(buf, "127.0.0.1", my_port);
  st32(buf + o, (uint32_t)fetch_shuffle); o += 4;
  st32(buf + o, 0); o += 4;
  st32(buf + o, 1); o += 4;          /* [0, 1) */
  if (send_rpc(dfd, MSG_FETCH, buf, o)) return 1;

  printf("READY %d\n", my_port);
  fflush(stdout);

  struct pollfd fds[MAX_FDS];
  int nfds = 3;
  fds[0].fd = 0;   fds[0].events = POLLIN; /* stdin EOF = shutdown */
  fds[1].fd = lfd; fds[1].events = POLLIN;
  fds[2].fd = dfd; fds[2].events = POLLIN;
  int pulled = 0;
  for (;;) {
    if (poll(fds, (nfds_t)nfds, 1000) < 0) {
      if (errno == EINTR) continue;
      return 1;
    }
    if (fetch_done && !pulled) {
      pulled = 1;
      if (pull_blocks(out_path, my_port)) {
        fprintf(stderr, "foreign_client: pull failed\n");
        return 1;
      }
    }
    for (int i = 0; i < nfds; i++) {
      if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      if (fds[i].fd == 0) {
        char c;
        if (read(0, &c, 1) <= 0) return 0;   /* orchestrator done */
      } else if (fds[i].fd == lfd) {
        int cfd = accept(lfd, NULL, NULL);
        if (cfd >= 0 && nfds < MAX_FDS) {
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          fds[nfds].fd = cfd;
          fds[nfds].events = POLLIN;
          nfds++;
        } else if (cfd >= 0) {
          close(cfd);
        }
      } else {
        if (handle_frame(fds[i].fd, fetch_shuffle)) {
          close(fds[i].fd);
          fds[i] = fds[nfds - 1];
          nfds--;
          i--;
        }
      }
    }
  }
}
