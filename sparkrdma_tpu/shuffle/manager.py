"""TpuShuffleManager — the top-level shuffle plugin entry point.

Analogue of RdmaShuffleManager.scala (reference: /root/reference/src/
main/scala/org/apache/spark/shuffle/rdma/RdmaShuffleManager.scala).
Semantics preserved (SURVEY.md §5.1):

- the **driver** is the metadata hub: executors publish partition
  locations to it and fetch locations from it; executors never gossip
  (:108-119, 376-420),
- driver constructor starts the transport node immediately and writes
  the negotiated port back into the conf (:180-184); executors start
  their node lazily on first writer/reader and introduce themselves
  with a hello RPC (:241-289),
- every hello triggers a full-membership announce to all executors,
  which pre-warm connections in the background (:121-169),
- executor loss prunes its locations from the driver registry
  (:199-221) — detected here via transport peer-loss events,
- RPC dispatch runs on completion threads and must not block
  (:65-178).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import replace
from typing import Dict, List, Optional, Set, Tuple

from sparkrdma_tpu.analysis.lockorder import OrderedLock, named_lock
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.locations import PartitionLocation, ShuffleManagerId
from sparkrdma_tpu.metastore import ShardedMetaStore, StaleEpochError
from sparkrdma_tpu.obs import SpanHandle, Tracer, get_registry, mint_trace_id
from sparkrdma_tpu.obs import now as obs_now
from sparkrdma_tpu.obs.journal import emit as journal_emit
from sparkrdma_tpu.obs.telemetry import TelemetryHub
from sparkrdma_tpu.resilience import SourceHealthRegistry
from sparkrdma_tpu.tenancy import AdmissionController, FairShareExecutor
from sparkrdma_tpu.tenancy import quota as _tquota
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.utils import checksum as _checksum
from sparkrdma_tpu.rpc import (
    AnnounceManagersMsg,
    FetchPartitionLocationsMsg,
    ManagerHelloMsg,
    PublishPartitionLocationsMsg,
    RpcMsg,
)
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.shuffle.stats import ShuffleReaderStats
from sparkrdma_tpu.transport import FnListener, TpuNode, create_node
from sparkrdma_tpu.utils.config import PREFIX, ShuffleWriterMethod, TpuShuffleConf

logger = logging.getLogger(__name__)


class TpuShuffleManager:
    def __init__(
        self,
        conf: TpuShuffleConf,
        is_driver: bool,
        executor_id: Optional[str] = None,
        host: str = "127.0.0.1",
    ):
        # drop-in SPI contract: a foreign engine may pass any plain
        # mapping (its own conf object, the SparkConf role). The driver
        # writes the negotiated listener port back INTO that mapping so
        # executors constructed from it afterwards inherit it — exactly
        # conf.setDriverPort semantics (RdmaShuffleManager.scala:183-184)
        self._external_conf = None
        if not isinstance(conf, TpuShuffleConf):
            self._external_conf = conf
            conf = TpuShuffleConf(dict(conf))
        self.conf = conf
        self.is_driver = is_driver
        self.executor_id = executor_id or ("driver" if is_driver else "executor")
        self.host = host

        self.node: Optional[TpuNode] = None
        self._node_lock = named_lock("manager.node")

        # driver state
        self._manager_ids: Dict[str, ShuffleManagerId] = {}
        # the locations registry: sharded by (shuffle_id, partition
        # range) across lease-replicated metadata peers (control-plane
        # HA, sparkrdma_tpu/metastore). The old monolithic
        # ``_partition_locations`` dict survives as a read-only
        # property materializing the store's primary-copy view.
        self.metastore: Optional[ShardedMetaStore] = (
            ShardedMetaStore(conf, role=self.executor_id) if is_driver else None
        )
        self._registered: Dict[int, BaseShuffleHandle] = {}
        # map-output tracking: fetch replies wait for shuffle completeness
        self._maps_done: Dict[int, int] = {}
        self._deferred_fetches: Dict[int, List[FetchPartitionLocationsMsg]] = {}
        # per-executor attribution of published map outputs, so peer loss
        # can re-arm the barrier (shuffle_id -> executor_id -> count)
        self._maps_by_exec: Dict[int, Dict[str, int]] = {}
        # elastic layer (sparkrdma_tpu/elastic/): first-finisher map
        # ownership (shuffle_id -> map_id -> executor_id; a later
        # publish of an owned map — a speculative clone losing the race
        # — is dropped whole) and the replica registry (shuffle_id ->
        # partition_id -> replica locations). Replicas never enter
        # fetch replies; _on_peer_lost promotes them when their primary
        # executor dies.
        self._map_owner: Dict[int, Dict[int, str]] = {}
        self._replica_locations: Dict[int, Dict[int, List[PartitionLocation]]] = {}
        # executors already processed by _on_peer_lost: a straggling
        # publish from one (a speculative finish racing the loss event)
        # must be dropped whole — accepting it would double-serve next
        # to a promoted replica and corrupt the barrier (found by the
        # modelcheck replica_promotion model)
        self._lost_executors: Set[str] = set()
        # publish/fetch mutation of ONE shuffle's registry serializes on
        # that shuffle's lock, not the manager-wide ``_lock`` — under a
        # contended map pool, concurrent shuffles' publishes used to
        # queue on one lock (WORKLOADS: 21.2 s contended vs 3.2 s
        # uncontended publish busy). ``_lock`` stays the guard for the
        # registry-of-shuffles structure itself and everything not
        # keyed by shuffle id. Ordering: shuffle lock OUTER, ``_lock``
        # inner (held only for dict lookups, never across handler work).
        self._shuffle_locks: Dict[int, OrderedLock] = {}

        # executor state
        self._fetch_futures: Dict[Tuple[int, int], Future] = {}
        self._fetch_acc: Dict[Tuple[int, int], List[PartitionLocation]] = {}
        self._known_managers: List[ShuffleManagerId] = []
        # critical-path attribution: span id of the driver's resolve
        # span per (shuffle_id, start_partition), learned from the
        # location reply's follows extension so the fetch spans it
        # caused can declare the causal edge (obs/critpath.py)
        self._resolve_origins: Dict[Tuple[int, int], SpanHandle] = {}
        # driver side of the same chain: handles of the per-writer
        # publish record spans, so resolve spans follow the publishes
        # they serve (publish -> resolve -> fetch in the Perfetto DAG)
        self._publish_origins: Dict[int, List[SpanHandle]] = {}

        # hot: dict lookups only (see _shuffle_locks comment above) —
        # the lock-order detector enforces that no blocking call runs
        # under it
        self._lock = named_lock("manager.state", hot=True)
        self._stopped = False
        # bounded map-task pool (conf map.parallelism): the engine runs
        # this executor's map tasks through here instead of a sequential
        # loop, so one executor overlaps several shards' write pipelines
        self._map_pool: Optional[ThreadPoolExecutor] = None

        self.reader_stats = (
            ShuffleReaderStats(conf) if conf.collect_shuffle_read_stats else None
        )

        # observability: process-wide registry + per-role tracer. Reader
        # ShuffleMetrics objects are retained (they are tiny dataclasses
        # with no back-references) so metrics_snapshot() can aggregate
        # the read path even after readers are dropped.
        self.registry = get_registry()
        self.tracer = Tracer(
            role=self.executor_id,
            max_spans=conf.trace_max_spans,
            enabled=conf.trace_enabled,
        )
        self._reader_metrics: List[object] = []

        # resilience: per-remote-manager circuit breakers (fetchers and
        # the device IO path consult these before issuing READs) and
        # the conf-driven fault plan for reproducible chaos runs
        self.health = SourceHealthRegistry(conf, role=self.executor_id)
        _faults.ensure_installed(conf.fault_plan, conf.fault_plan_seed)

        # tenancy: the driver admits jobs (bounded in-flight + FIFO
        # queue-with-deadline); every manager installs the process-wide
        # quota brokers (idempotent — first tenancy-enabled conf wins)
        self.admission: Optional[AdmissionController] = None
        if conf.tenancy_enabled:
            _tquota.install(conf)
            if is_driver:
                self.admission = AdmissionController(
                    conf.tenancy_max_concurrent_jobs,
                    conf.tenancy_admit_timeout_ms,
                    role=self.executor_id,
                )

        # cluster telemetry plane: the driver (already the metadata hub
        # for every shuffle) folds executor heartbeats into per-executor
        # time series and runs the straggler detector; its report feeds
        # the health registry as an advisory signal (obs/telemetry.py)
        self.telemetry = None
        if is_driver and conf.telemetry_enabled:
            self.telemetry = TelemetryHub(
                conf, role=self.executor_id, health=self.health,
                registry=self.registry,
            )

        if is_driver:
            # driver starts its node eagerly and records the negotiated
            # port for executors (:180-184)
            self.node = create_node(
                conf,
                host,
                is_executor=False,
                executor_id=self.executor_id,
                recv_listener=self._receive_listener,
                peer_lost_listener=self._on_peer_lost,
            )
            conf.set_driver_port(self.node.port)
            if self._external_conf is not None:
                try:
                    self._external_conf[PREFIX + "driverPort"] = str(self.node.port)
                except TypeError:
                    pass  # immutable mapping: executors need the port passed

        self.resolver = TpuShuffleBlockResolver(self)

        # push/merge plane (shuffle/merge.py): every manager hosts a
        # merge endpoint (receiving pushed blocks for partitions it
        # will reduce) and a push client (shipping its own sealed map
        # blocks toward their reducers). Both are strictly best-effort
        # overlays on the locations API — disabling them changes
        # nothing but read amplification.
        self.push_client = None
        self.merge_endpoint = None
        if conf.push_enabled:
            from sparkrdma_tpu.shuffle import merge as _merge

            self.push_client = _merge.PushClient(self)
            self.merge_endpoint = _merge.MergeEndpoint(self)
            _merge.register_endpoint(self.merge_endpoint)
        # elastic replication plane (sparkrdma_tpu/elastic/): executors
        # host a replica store (receiving peers' map-output copies) and
        # a replica client (shipping their own) when durability is on.
        # Like push/merge, a best-effort overlay on the locations API.
        self.replica_client = None
        self.replica_store = None
        if conf.elastic_replicas > 0 and not is_driver:
            from sparkrdma_tpu import elastic as _elastic

            self.replica_client = _elastic.ReplicaClient(self)
            self.replica_store = _elastic.ReplicaStore(self)
            _elastic.register_store(self.replica_store)
        # publish-time checksum tagging pool (lazy; see _checksummed)
        self._ck_pool: Optional[ThreadPoolExecutor] = None

    # ------------------------------------------------------------------
    # node lifecycle
    # ------------------------------------------------------------------
    @property
    def local_manager_id(self) -> ShuffleManagerId:
        assert self.node is not None, "node not started"
        return ShuffleManagerId(self.host, self.node.port, self.executor_id)

    def start_node_if_missing(self) -> None:
        """Executor lazy init + hello to driver (:241-289)."""
        if self.node is not None:
            return
        with self._node_lock:
            if self.node is not None:
                return
            node = create_node(
                self.conf,
                self.host,
                is_executor=True,
                executor_id=self.executor_id,
                recv_listener=self._receive_listener,
            )
            self.node = node
        ch = self.node.get_channel(self.conf.driver_host, self.conf.driver_port)
        hello = ManagerHelloMsg(self.local_manager_id)
        done = threading.Event()
        ch.send_in_queue(
            FnListener(lambda _: done.set(), lambda e: done.set()),
            hello.to_segments(self.conf.recv_wr_size),
        )
        done.wait(self.conf.connect_timeout_ms / 1000.0)

    # ------------------------------------------------------------------
    # RPC dispatch (reference receiveListener, :65-178)
    # ------------------------------------------------------------------
    def _receive_listener(self, channel, payload: bytes) -> None:
        t0 = time.perf_counter()
        plan = _faults.active()
        if plan is not None:
            payload, handled = plan.on_rpc(
                getattr(channel, "peer_desc", ""), payload
            )
            if handled:
                return
        try:
            msg = RpcMsg.parse_segment(payload)
            if isinstance(msg, ManagerHelloMsg):
                self._handle_hello(msg)
            elif isinstance(msg, FetchPartitionLocationsMsg):
                self._handle_fetch(msg)
            elif isinstance(msg, PublishPartitionLocationsMsg):
                self._handle_publish(msg)
            elif isinstance(msg, AnnounceManagersMsg):
                self._handle_announce(msg)
        except Exception:
            self.registry.counter("rpc.errors", role=self.executor_id).inc()
            logger.exception("error dispatching rpc message")
        else:
            mtype = msg.msg_type.name
            self.registry.counter(
                "rpc.messages", role=self.executor_id, type=mtype
            ).inc()
            self.registry.histogram(
                "rpc.handle_ms", role=self.executor_id, type=mtype
            ).observe((time.perf_counter() - t0) * 1e3)

    def _shuffle_lock(self, shuffle_id: int) -> OrderedLock:
        """Per-shuffle registry lock (driver side). Sharding by
        shuffle_id lets concurrent publishes for independent shuffles
        proceed in parallel; the global ``_lock`` is only held for the
        dict lookup (lock order: shuffle lock OUTER, ``_lock`` inner)."""
        with self._lock:
            return self._shuffle_locks.setdefault(
                shuffle_id, named_lock("manager.shuffle")
            )

    @property
    def _partition_locations(
        self,
    ) -> Dict[int, Dict[int, List[PartitionLocation]]]:
        """Read-only primary-copy view of the sharded registry, in the
        shape the monolithic dict always had (shuffle_id -> pid ->
        locations). Kept for tests and diagnostics; every mutation
        goes through the metastore's epoch-fenced publish/sweep."""
        if self.metastore is None:
            return {}
        return self.metastore.all_entries()

    def _handle_hello(self, msg: ManagerHelloMsg) -> None:
        """Driver: record membership, connect back, announce to all (:121-161)."""
        if not self.is_driver:
            return
        mid = msg.manager_id
        with self._lock:
            self._manager_ids[mid.executor_id] = mid
            members = list(self._manager_ids.values())
        assert self.node is not None
        # warm the driver's active channel back to the new executor (:126-128)
        try:
            self.node.get_channel(mid.host, mid.port)
        except IOError:
            logger.warning("could not connect back to %s", mid)
            return
        announce = AnnounceManagersMsg(members)
        segments = announce.to_segments(self.conf.recv_wr_size)
        for member in members:
            try:
                ch = self.node.get_channel(member.host, member.port)
                ch.send_in_queue(FnListener(), segments)
            except IOError:
                logger.warning("announce to %s failed", member)

    def _handle_announce(self, msg: AnnounceManagersMsg) -> None:
        """Executor: learn membership, pre-warm connections (:163-169)."""
        with self._lock:
            for mid in msg.manager_ids:
                if mid not in self._known_managers:
                    self._known_managers.append(mid)
            to_warm = [m for m in self._known_managers if m.executor_id != self.executor_id]

        def warm():
            for m in to_warm:
                try:
                    assert self.node is not None
                    self.node.get_channel(m.host, m.port, must_retry=False)
                except IOError:
                    pass

        # analysis: ignore[tenant-scope]: cluster-membership pre-warm, no tenant-attributed work
        threading.Thread(target=warm, name="prewarm", daemon=True).start()

    def _handle_fetch(self, msg: FetchPartitionLocationsMsg) -> None:
        """Driver: answer a location fetch for [start, end) (:108-119).

        Replies are deferred until every map output of the shuffle has
        been published (the MapOutputTracker barrier the reference
        delegates to Spark).
        """
        if not self.is_driver:
            return
        with self._shuffle_lock(msg.shuffle_id):
            with self._lock:
                handle = self._registered.get(msg.shuffle_id)
            if handle is not None and self._maps_done.get(msg.shuffle_id, 0) < handle.num_maps:
                self._deferred_fetches.setdefault(msg.shuffle_id, []).append(msg)
                return
        self._reply_fetch(msg)

    def _reply_fetch(self, msg: FetchPartitionLocationsMsg) -> None:
        with self._lock:
            pub_origins = list(self._publish_origins.get(msg.shuffle_id, ()))
        with self.tracer.span(
            "shuffle.resolve",
            shuffle_id=msg.shuffle_id,
            trace_id=msg.trace_id,
            follows=[SpanHandle(msg.trace_id, msg.origin_span)] + pub_origins,
            requester=msg.requester.executor_id,
            partitions=f"{msg.start_partition}:{msg.end_partition}",
        ) as rsp:
            locs: List[PartitionLocation] = []
            with self._shuffle_lock(msg.shuffle_id):
                assert self.metastore is not None
                try:
                    locs = self.metastore.resolve_range(
                        msg.shuffle_id, msg.start_partition, msg.end_partition
                    )
                except StaleEpochError:
                    # every retry re-routed into another takeover: serve
                    # what we can (nothing) rather than wedge the reply
                    logger.warning(
                        "resolve of shuffle %d [%d:%d) exhausted epoch retries",
                        msg.shuffle_id, msg.start_partition, msg.end_partition,
                    )
            reply = PublishPartitionLocationsMsg(
                msg.shuffle_id,
                msg.start_partition,
                locs,
                trace_id=self.tracer.trace_for(msg.shuffle_id) or msg.trace_id,
                origin_span=rsp.span_id if rsp is not None else 0,
            )
            assert self.node is not None
            try:
                ch = self.node.get_channel(msg.requester.host, msg.requester.port)
                ch.send_in_queue(FnListener(), reply.to_segments(self.conf.recv_wr_size))
            except IOError:
                logger.warning("publish reply to %s failed", msg.requester)

    @staticmethod
    def _is_replica_publish(msg: PublishPartitionLocationsMsg) -> bool:
        """A replica publish must divert into the replica registry —
        serving it beside its live primary would read the same map
        output twice. Named so the modelcheck mutation gate can disarm
        the divert and prove the double-serve oracle notices."""
        return bool(msg.locations) and msg.locations[0].block.is_replica

    def _claim_map_owner(
        self, owner_map: Dict[int, str], map_id: int, exec_id: str
    ) -> bool:
        """First-finisher map-ownership claim (caller holds the shuffle
        lock). False = a different executor already owns the map — the
        publish is a speculative clone that lost the race and must be
        dropped whole. The seam between the read and the write is a
        model-checker schedule point: the shuffle lock is what makes
        check-then-claim atomic, and the modelcheck mutation gate proves
        the checker notices when it is not."""
        prev = owner_map.get(map_id)
        if prev is not None and prev != exec_id:
            return False
        schedule_point("proto", "manager.publish.claim")
        owner_map[map_id] = exec_id
        return True

    def _handle_publish(self, msg: PublishPartitionLocationsMsg) -> None:
        if self.is_driver:
            schedule_point("proto", "manager.publish")
            if msg.is_last and msg.partition_id < 0:
                # one span per completed writer publish (not per segment)
                t = obs_now()
                psp = self.tracer.record(
                    "shuffle.publish",
                    t,
                    t,
                    shuffle_id=msg.shuffle_id,
                    trace_id=msg.trace_id,
                    follows=SpanHandle(msg.trace_id, msg.origin_span),
                    locations=len(msg.locations),
                    map_outputs=msg.num_map_outputs,
                )
                if psp is not None:
                    with self._lock:
                        origins = self._publish_origins.setdefault(
                            msg.shuffle_id, []
                        )
                        if len(origins) < 256:  # bound per-shuffle growth
                            origins.append(psp.handle())
            # replica publishes (elastic layer) divert whole into the
            # replica registry: they must never reach fetch replies or
            # the planner's byte totals until a promotion makes them
            # primary (_on_peer_lost)
            if self._is_replica_publish(msg):
                with self._shuffle_lock(msg.shuffle_id):
                    with self._lock:
                        reg = self._replica_locations.setdefault(msg.shuffle_id, {})
                        lost = set(self._lost_executors)
                    for loc in msg.locations:
                        # a replica whose holder is already gone would
                        # never be pruned again — drop it here
                        if loc.manager_id.executor_id in lost:
                            continue
                        if loc.block.is_replica:
                            reg.setdefault(loc.partition_id, []).append(loc)
                return
            # writers publish with partition_id = -1; re-key every location
            # by its own partition id (:68-95). Three phases:
            #   1. under the shuffle lock: generation fence, swept-
            #      publisher fast check, first-finisher ownership claim;
            #   2. OUTSIDE it: per-shard epoch-fenced inserts (the
            #      metastore re-routes and retries stale epochs through
            #      the ladder);
            #   3. under the shuffle lock again: barrier accounting —
            #      AFTER the inserts landed, and only if the publisher
            #      was not swept meanwhile (the per-shard tombstones
            #      dropped its locations; counting it would complete a
            #      barrier whose locations never landed).
            assert self.metastore is not None
            to_reply: List[FetchPartitionLocationsMsg] = []
            exec_id = (
                msg.locations[0].manager_id.executor_id if msg.locations else ""
            )
            with self._shuffle_lock(msg.shuffle_id):
                if msg.meta_epoch and msg.meta_epoch != self.metastore.generation:
                    # a re-adoption sweep started under an older
                    # takeover: reject it whole before it claims
                    # ownership it could block a recompute with
                    self.registry.counter(
                        "metastore.stale_epoch_rejects", role=self.executor_id
                    ).inc()
                    return
                # first-finisher-wins dedup for attributed map publishes:
                # a speculative clone of a map whose original already
                # published (or vice versa) is dropped whole, so the
                # barrier and the location registry never double-count
                owner_map = self._map_owner.setdefault(msg.shuffle_id, {})
                if (
                    msg.num_map_outputs > 0
                    and msg.locations
                    and msg.locations[0].block.source_map >= 0
                ):
                    map_id = msg.locations[0].block.source_map
                    if exec_id in self._lost_executors:
                        # publisher already swept by _on_peer_lost: its
                        # replicas were promoted and its counts pruned;
                        # this straggler's blocks live on a dead node
                        self.registry.counter(
                            "elastic.publishes_dropped", role=self.executor_id
                        ).inc()
                        return
                    if not self._claim_map_owner(owner_map, map_id, exec_id):
                        self.registry.counter(
                            "elastic.publishes_dropped", role=self.executor_id
                        ).inc()
                        return
            try:
                self.metastore.publish(
                    msg.shuffle_id, msg.locations,
                    fence_generation=msg.meta_epoch,
                )
            except StaleEpochError:
                # counted by the store; an adoption-era mismatch or an
                # exhausted retry ladder drops the message whole — the
                # barrier below never runs, so completeness stays honest
                return
            if msg.meta_epoch and msg.num_map_outputs > 0:
                # a generation-matched re-publish after a hub wipe: the
                # crashed registry just re-adopted this map's state
                self.registry.counter(
                    "metastore.adoptions", role=self.executor_id
                ).inc()
                journal_emit(
                    "meta.adopt", role=self.executor_id, executor=exec_id,
                    shuffle_id=msg.shuffle_id, generation=msg.meta_epoch,
                )
            with self._shuffle_lock(msg.shuffle_id):
                with self._lock:
                    handle = self._registered.get(msg.shuffle_id)
                if msg.is_last and msg.num_map_outputs > 0:
                    if exec_id and exec_id in self._lost_executors:
                        # swept between the claim and the inserts: the
                        # per-shard tombstones dropped the locations
                        # (or the sweep pruned them); counting this
                        # publish would complete a barrier whose
                        # locations never landed (meta_lease model)
                        self.registry.counter(
                            "elastic.publishes_dropped", role=self.executor_id
                        ).inc()
                        return
                    done = self._maps_done.get(msg.shuffle_id, 0) + msg.num_map_outputs
                    self._maps_done[msg.shuffle_id] = done
                    if msg.locations:
                        # attribute to the publishing executor so its loss
                        # re-arms the barrier; empty publishes (maps with
                        # no output data) have nothing to lose and stay
                        # counted unconditionally
                        by_exec = self._maps_by_exec.setdefault(msg.shuffle_id, {})
                        by_exec[exec_id] = by_exec.get(exec_id, 0) + msg.num_map_outputs
                    if handle is not None and done >= handle.num_maps:
                        to_reply = self._deferred_fetches.pop(msg.shuffle_id, [])
            # feed the adaptive planner: per-partition byte totals of
            # ORIGINAL locations (merged segments re-cover the same
            # bytes and would double-count; re-adoption publishes were
            # counted the first time around)
            if self.telemetry is not None and msg.partition_id < 0 and not msg.meta_epoch:
                for loc in msg.locations:
                    if not loc.block.merged_cover:
                        # source executor = the DMA lane this block will
                        # pull over (collective schedule lane balancing)
                        self.telemetry.record_partition_bytes(
                            msg.shuffle_id, loc.partition_id,
                            loc.block.length,
                            source=loc.manager_id.executor_id,
                        )
            for fetch in to_reply:
                self._reply_fetch(fetch)
            return
        # executor: location-fetch responses, accumulated until is_last
        self.tracer.bind_shuffle(msg.shuffle_id, msg.trace_id)
        key = (msg.shuffle_id, msg.partition_id)
        with self._lock:
            self._fetch_acc.setdefault(key, []).extend(msg.locations)
            if msg.origin_span:
                # the driver resolve span this reply hands off from;
                # the fetch spans it causes follow it (resolve→fetch)
                self._resolve_origins[key] = SpanHandle(
                    msg.trace_id, msg.origin_span
                )
            if not msg.is_last:
                return
            locs = self._fetch_acc.pop(key, [])
            future = self._fetch_futures.pop(key, None)
        if future is not None:
            future.set_result(locs)

    def resolve_origin(
        self, shuffle_id: int, start_partition: int
    ) -> Optional[SpanHandle]:
        """Causal handle of the driver resolve span that answered this
        (shuffle, range) location fetch, if the reply carried one."""
        with self._lock:
            return self._resolve_origins.get((shuffle_id, start_partition))

    def _on_peer_lost(self, executor_id: str) -> None:
        """Driver: prune a lost executor's locations (:199-221).

        Also subtracts the executor's published map outputs from the
        completeness barrier, so later fetches defer (and eventually
        time out into MetadataFetchFailedError on the reducer) instead
        of receiving a complete-looking but incomplete location set —
        the reference's missing-MapStatus semantics.

        Elastic layer: before re-arming the barrier, any replica of the
        lost executor's blocks (elastic/replication.py, the service
        daemon) is *promoted* into the primary registry — the barrier
        only drops by the maps no replica covers, so a fully replicated
        executor's death costs zero recompute."""
        if not self.is_driver:
            return
        schedule_point("proto", "manager.peer_lost")
        assert self.metastore is not None
        with self._lock:
            self._manager_ids.pop(executor_id, None)
            self._lost_executors.add(executor_id)
            shuffle_ids = set(self._maps_by_exec) | set(self._replica_locations)
        shuffle_ids |= set(self.metastore.shuffle_ids())
        for shuffle_id in shuffle_ids:
            promoted_maps: set = set()
            # per-shuffle seam OUTSIDE the shuffle lock: publishes for
            # other shuffles may interleave between prune steps
            schedule_point("proto", "manager.peer_lost.shuffle")
            with self._shuffle_lock(shuffle_id):
                with self._lock:
                    by_exec = self._maps_by_exec.get(shuffle_id)
                    replicas = self._replica_locations.get(shuffle_id)
                    owner_map = self._map_owner.get(shuffle_id)
                # tombstone + prune shard by shard: a publish racing this
                # sweep either lands before a shard's sweep (pruned) or
                # after it (dropped by the shard's tombstone) — the
                # check holds PER SHARD, never per process
                self.metastore.sweep_executor(executor_id, shuffle_id)
                promoted_locs: List[PartitionLocation] = []
                if replicas is not None:
                    # drop replicas the lost executor itself was holding,
                    # then promote its surviving replicas into the
                    # primary registry (replica_of stays set so the
                    # fetchers' failover rung can identity-match them)
                    promoted_by_holder: Dict[str, set] = {}
                    promoted_slots: set = set()
                    for pid in list(replicas.keys()):
                        keep: List[PartitionLocation] = []
                        for loc in replicas[pid]:
                            if loc.manager_id.executor_id == executor_id:
                                continue
                            if loc.block.replica_of == executor_id:
                                sm = loc.block.source_map
                                if (
                                    sm >= 0
                                    and owner_map is not None
                                    and owner_map.get(sm, executor_id)
                                    != executor_id
                                ):
                                    # the map is owned by a LIVE primary
                                    # (the lost executor lost the dedup
                                    # race to a speculative clone):
                                    # promoting this replica would serve
                                    # the same map twice — drop it
                                    continue
                                if sm >= 0 and (pid, sm) in promoted_slots:
                                    # second replica of the same slot
                                    # (replication factor > 1): one
                                    # promotion serves it, spares drop
                                    continue
                                if sm >= 0:
                                    promoted_slots.add((pid, sm))
                                promoted_locs.append(loc)
                                if loc.block.source_map >= 0:
                                    promoted_maps.add(loc.block.source_map)
                                    promoted_by_holder.setdefault(
                                        loc.manager_id.executor_id, set()
                                    ).add(loc.block.source_map)
                            else:
                                keep.append(loc)
                        replicas[pid] = keep
                    # re-attribute the covered maps to their new holders
                    # so a later loss of the holder re-arms the barrier.
                    # A promoted map may have NO owner/attribution entry
                    # yet (its primary publish raced the loss event and
                    # was tombstone-dropped): claim it for the holder
                    # anyway — and credit the barrier for it, since the
                    # promoted replica IS that map's output — so a
                    # straggling duplicate publish is deduped instead of
                    # double-serving beside the promoted replica (found
                    # by the modelcheck replica_promotion model)
                    if promoted_maps:
                        if by_exec is None or owner_map is None:
                            with self._lock:
                                by_exec = self._maps_by_exec.setdefault(
                                    shuffle_id, {}
                                )
                                owner_map = self._map_owner.setdefault(
                                    shuffle_id, {}
                                )
                        for holder, maps in promoted_by_holder.items():
                            by_exec[holder] = by_exec.get(holder, 0) + len(maps)
                            for m in maps:
                                owner_map[m] = holder
                if promoted_locs:
                    # promoted replicas become primary REGISTRY entries:
                    # epoch-fenced inserts like any publish (their
                    # holders are live, so no tombstone drops them)
                    try:
                        self.metastore.publish(shuffle_id, promoted_locs)
                    except StaleEpochError:
                        logger.warning(
                            "replica promotion for shuffle %d exhausted "
                            "epoch retries", shuffle_id,
                        )
                if owner_map is not None:
                    # uncovered maps lose their owner: the recompute's
                    # re-publish must be accepted, not deduped away
                    for m in [
                        m for m, e in owner_map.items()
                        if e == executor_id and m not in promoted_maps
                    ]:
                        del owner_map[m]
                if by_exec is not None:
                    lost = by_exec.pop(executor_id, 0)
                    # barrier delta: every promoted map is now served by
                    # its replica (+1 each, whether or not the lost
                    # executor's publish ever counted — a tombstone-
                    # dropped publish never did), every counted map of
                    # the lost executor stops being served (-lost);
                    # promoted maps it did publish cancel out
                    delta = len(promoted_maps) - lost
                    if delta:
                        self._maps_done[shuffle_id] = (
                            self._maps_done.get(shuffle_id, 0) + delta
                        )
            if promoted_maps:
                self.registry.counter(
                    "elastic.replica_promotions", role=self.executor_id
                ).inc(len(promoted_maps))
                journal_emit(
                    "elastic.promote", role=self.executor_id,
                    executor=executor_id, shuffle_id=shuffle_id,
                    maps=len(promoted_maps),
                    holders=len(promoted_by_holder),
                )
        logger.info("pruned locations of lost executor %s", executor_id)

    # ------------------------------------------------------------------
    # metadata API (reference :343-420)
    # ------------------------------------------------------------------
    def _with_checksum(self, loc: PartitionLocation) -> PartitionLocation:
        """Attach the publish-time integrity tag to one location.

        Computed HERE — the single funnel every publish path (wrapper
        writer, chunked-agg finalize, device IO, manual test publishes)
        already flows through — by resolving the advertised
        ``(mkey, address, length)`` in the local ProtectionDomain,
        exactly the view a remote READ will be served from. Resolution
        failure (foreign publisher, unregistered test triple) leaves
        the location untagged: integrity is best-effort, never a new
        failure mode."""
        if loc.block.checksum_algo or loc.block.length == 0:
            return loc
        node = self.node
        if node is None:
            return loc
        try:
            view = node.pd.resolve(loc.block.mkey, loc.block.address, loc.block.length)
        except Exception:
            return loc
        algo, crc = _checksum.compute(view)
        if algo == _checksum.ALGO_NONE:
            return loc
        return replace(loc, block=replace(loc.block, checksum=crc, checksum_algo=algo))

    def _checksummed(
        self, locations: List[PartitionLocation]
    ) -> List[PartitionLocation]:
        """Tag a publish batch, sharding the checksum compute across a
        small pool for large batches (conf ``publish.checksumWorkers``;
        0/1 = inline). The contended-publish ledger rows showed the
        tagging loop dominating publish busy time when every executor's
        finalize lands at once — order is preserved, tagging stays the
        single funnel of :meth:`_with_checksum`."""
        workers = self.conf.publish_checksum_workers
        if workers <= 1 or len(locations) < 4 * workers:
            return [self._with_checksum(loc) for loc in locations]
        with self._lock:
            if self._stopped:
                # create-vs-close race: never spin up a pool that
                # stop() has already swept past (it would leak)
                raise RuntimeError(
                    f"manager {self.executor_id} is stopped; cannot publish"
                )
            if self._ck_pool is None:
                self._ck_pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix=f"ck-{self.executor_id}",
                )
            pool = self._ck_pool
        chunk = (len(locations) + workers - 1) // workers
        parts = [locations[i : i + chunk] for i in range(0, len(locations), chunk)]
        futs = [
            pool.submit(lambda ls=ls: [self._with_checksum(loc) for loc in ls])
            for ls in parts
        ]
        out: List[PartitionLocation] = []
        for f in futs:
            out.extend(f.result())
        return out

    def publish_partition_locations(
        self,
        shuffle_id: int,
        partition_id: int,
        locations: List[PartitionLocation],
        num_map_outputs: int = 0,
        meta_epoch: int = 0,
    ) -> None:
        if self.conf.resilience_checksums:
            locations = self._checksummed(locations)
        msg = PublishPartitionLocationsMsg(
            shuffle_id,
            partition_id,
            locations,
            num_map_outputs=num_map_outputs,
            trace_id=self.tracer.trace_for(shuffle_id),
            meta_epoch=meta_epoch,
        )
        self.registry.counter("writer.publishes", role=self.executor_id).inc()
        self.registry.counter("writer.locations_published", role=self.executor_id).inc(
            len(locations)
        )
        if self.is_driver:
            self._handle_publish(msg)
            return
        assert self.node is not None
        with self.tracer.span(
            "shuffle.publish", shuffle_id=shuffle_id, locations=len(locations)
        ) as sp:
            if sp is not None:
                # the driver's publish record follows this span: the
                # executor→driver leg of the cross-role critical path
                msg.origin_span = sp.span_id
            ch = self.node.get_channel(self.conf.driver_host, self.conf.driver_port)
            ch.send_in_queue(FnListener(), msg.to_segments(self.conf.recv_wr_size))

    def metastore_crash(self) -> int:
        """Driver: model hub death (the ``driver:kill`` fault). Every
        registry entry, barrier count, ownership claim, and parked
        replica is gone; leases re-grant under bumped epochs and the
        generation advances. What survives — registered handles,
        deferred fetches, the lost-executor set — is exactly what a
        restarted hub process re-derives from its own job state.
        Returns the new generation; re-adoption sweeps
        (:meth:`republish_for_readoption`) must carry it."""
        assert self.is_driver and self.metastore is not None
        journal_emit("driver.kill", role=self.executor_id)
        generation = self.metastore.wipe()
        with self._lock:
            self._maps_done.clear()
            self._maps_by_exec.clear()
            self._map_owner.clear()
            self._replica_locations.clear()
            self._publish_origins.clear()
        logger.warning(
            "metastore wiped (driver crash); generation now %d", generation
        )
        return generation

    def republish_for_readoption(self, meta_epoch: int = 0) -> int:
        """Executor: re-publish every committed map output (and every
        parked replica) so a wiped hub re-adopts authoritative state —
        a re-publish sweep, never a recompute. Locations rebuild from
        the writer-committed files (committed_map_locations) plus the
        replica registry's lineage tags; ``meta_epoch`` fences the
        sweep against a takeover that started after it. Returns how
        many map publishes were sent."""
        if self.node is None:
            return 0  # never wrote anything: nothing to re-adopt
        count = 0
        for shuffle_id in self.resolver.shuffle_ids():
            data = self.resolver.get_shuffle_data(shuffle_id)
            fn = getattr(data, "committed_map_locations", None)
            if fn is None:
                continue
            for _map_id, locs in sorted(fn(self.local_manager_id).items()):
                self.publish_partition_locations(
                    shuffle_id, -1, locs,
                    num_map_outputs=1, meta_epoch=meta_epoch,
                )
                count += 1
        if self.replica_store is not None:
            count += self.replica_store.republish(meta_epoch)
        return count

    def fetch_remote_partition_locations(
        self, shuffle_id: int, start_partition: int, end_partition: int
    ) -> Future:
        """Async fetch; resolves to List[PartitionLocation] (:376-420)."""
        future: Future = Future()
        key = (shuffle_id, start_partition)
        with self._lock:
            self._fetch_futures[key] = future
            self._fetch_acc.pop(key, None)
        msg = FetchPartitionLocationsMsg(
            self.local_manager_id,
            shuffle_id,
            start_partition,
            end_partition,
            trace_id=self.tracer.trace_for(shuffle_id),
        )
        assert self.node is not None

        def on_fail(e: Exception) -> None:
            with self._lock:
                pending = self._fetch_futures.pop(key, None)
            if pending is not None and not pending.done():
                pending.set_exception(e)

        try:
            # the request span's handle rides the frame so the driver's
            # resolve span follows it (request→resolve causal leg)
            with self.tracer.span(
                "shuffle.fetch_request",
                shuffle_id=shuffle_id,
                partitions=f"{start_partition}:{end_partition}",
            ) as sp:
                if sp is not None:
                    msg.origin_span = sp.span_id
                ch = self.node.get_channel(
                    self.conf.driver_host, self.conf.driver_port
                )
                ch.send_in_queue(
                    FnListener(None, on_fail),
                    msg.to_segments(self.conf.recv_wr_size),
                )
        except IOError as e:
            on_fail(e)
        return future

    # ------------------------------------------------------------------
    # shuffle SPI (reference :187-330)
    # ------------------------------------------------------------------
    def register_shuffle(self, handle) -> BaseShuffleHandle:
        """Driver-only: build the per-partition location registry (:187-239).

        Returns the canonical handle the engine must pass to
        ``get_writer``/``get_reader`` — a foreign engine's duck-typed
        handle (``shuffle_id``, ``num_maps``, ``partitioner`` with
        ``num_partitions`` + ``partition(key)``) is adapted here, the
        same place the reference chooses its own handle class
        (RdmaShuffleManager.scala:231-238)."""
        assert self.is_driver, "register_shuffle must run on the driver"
        if not isinstance(handle, BaseShuffleHandle):
            extra = {}
            serializer = getattr(handle, "serializer", None)
            if serializer is not None:
                extra["serializer"] = serializer
            handle = BaseShuffleHandle(
                shuffle_id=handle.shuffle_id,
                num_maps=handle.num_maps,
                partitioner=handle.partitioner,
                aggregator=getattr(handle, "aggregator", None),
                map_side_combine=bool(getattr(handle, "map_side_combine", False)),
                key_ordering=bool(getattr(handle, "key_ordering", False)),
                **extra,
            )
        with self._lock:
            self._registered[handle.shuffle_id] = handle
        assert self.metastore is not None
        self.metastore.ensure_shuffle(handle.shuffle_id, handle.num_partitions)
        # mint the shuffle's trace id; it rides every Publish/Fetch frame
        # touching this shuffle so spans correlate across roles
        trace_id = mint_trace_id()
        self.tracer.bind_shuffle(handle.shuffle_id, trace_id)
        with self.tracer.span(
            "shuffle.register",
            shuffle_id=handle.shuffle_id,
            num_maps=handle.num_maps,
            num_partitions=handle.num_partitions,
        ):
            pass
        return handle

    def get_writer(self, handle: BaseShuffleHandle, map_id: int):
        from sparkrdma_tpu.shuffle.writer.chunked_agg import ChunkedAggShuffleWriter
        from sparkrdma_tpu.shuffle.writer.wrapper import WrapperShuffleWriter

        self.start_node_if_missing()
        if self.conf.shuffle_writer_method == ShuffleWriterMethod.WRAPPER:
            return WrapperShuffleWriter(self, handle, map_id)
        return ChunkedAggShuffleWriter(self, handle, map_id)

    def get_reader(self, handle: BaseShuffleHandle, start_partition: int, end_partition: int):
        from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

        self.start_node_if_missing()
        reader = TpuShuffleReader(self, handle, start_partition, end_partition)
        with self._lock:
            self._reader_metrics.append(reader.metrics)
        return reader

    @property
    def map_pool(self):
        """This executor's bounded map-task pool (lazy; size = conf
        ``map.parallelism``). Map dispatch layers (engine/context,
        engine/worker) submit map tasks here so per-executor map
        concurrency is a config knob, not a scheduler accident.

        With tenancy enabled the pool dispatches deficit-round-robin
        per tenant (FairShareExecutor) instead of FIFO. Creation and
        the stop() swap share ``_lock`` and creation re-checks
        ``_stopped`` — a lazy create racing close() can neither leak a
        live pool past shutdown nor hand one out (post-close access
        raises instead)."""
        with self._lock:
            if self._stopped:
                raise RuntimeError(
                    f"manager {self.executor_id} is stopped; map_pool is gone"
                )
            if self._map_pool is None:
                if self.conf.tenancy_enabled:
                    self._map_pool = FairShareExecutor(
                        max_workers=self.conf.map_parallelism,
                        weights=self.conf.tenancy_weights,
                        default_weight=self.conf.tenancy_default_weight,
                        quantum_ms=self.conf.tenancy_quantum_ms,
                        thread_name_prefix=f"map-{self.executor_id}",
                        pool=f"map-{self.executor_id}",
                    )
                else:
                    self._map_pool = ThreadPoolExecutor(
                        max_workers=self.conf.map_parallelism,
                        thread_name_prefix=f"map-{self.executor_id}",
                    )
            return self._map_pool

    def finalize_maps(self, shuffle_id: int) -> None:
        """Map-stage barrier hook: chunked-agg data publishes here."""
        from sparkrdma_tpu.shuffle.writer.chunked_agg import ChunkedAggShuffleData

        data = self.resolver.get_shuffle_data(shuffle_id)
        if isinstance(data, ChunkedAggShuffleData):
            data.finalize_and_publish(self)

    def known_executor_ids(self) -> List[str]:
        """Executor ids this manager can name as push destinations:
        announced membership plus itself (executors only — the driver
        never reduces)."""
        with self._lock:
            ids = {m.executor_id for m in self._known_managers}
            ids.update(self._manager_ids.keys())
        if not self.is_driver:
            ids.add(self.executor_id)
        return sorted(ids)

    def map_owners(self, shuffle_id: int) -> Dict[int, str]:
        """Driver: snapshot of first-finisher map ownership (elastic
        layer): map_id -> executor_id of the publish that won. Maps
        whose owner died uncovered are absent — exactly the set a
        partial stage recompute must re-run."""
        with self._shuffle_lock(shuffle_id):
            with self._lock:
                return dict(self._map_owner.get(shuffle_id, {}))

    def unaccounted_maps(self, shuffle_id: int, map_ids) -> List[int]:
        """Driver: the subset of ``map_ids`` with no surviving owner —
        neither the original publish nor a promoted replica covers
        them, so lineage recompute must re-run them."""
        owners = self.map_owners(shuffle_id)
        return sorted(m for m in map_ids if m not in owners)

    def partition_sizes(self, shuffle_id: int) -> Dict[int, int]:
        """Driver: published per-partition byte totals (original
        locations only — merged segments re-cover the same bytes). The
        adaptive partition planner's input; prefers the telemetry
        hub's running totals, falls back to the location registry."""
        if self.telemetry is not None:
            sizes = self.telemetry.partition_bytes(shuffle_id)
            if sizes:
                return sizes
        out: Dict[int, int] = {}
        with self._shuffle_lock(shuffle_id):
            shuffle = (
                self.metastore.entries_for_shuffle(shuffle_id)
                if self.metastore is not None else {}
            )
            for pid, locs in shuffle.items():
                out[pid] = sum(
                    loc.block.length
                    for loc in locs
                    if not loc.block.merged_cover
                )
        return out

    def partition_lane_sizes(self, shuffle_id: int) -> Dict[str, Dict[int, int]]:
        """Driver: the same byte totals split by SOURCE executor
        (source -> pid -> bytes) — the planner's DMA-lane signal for
        lane-balanced reduce cuts (shuffle/planner.py). Telemetry-fed;
        empty when no telemetry hub runs (static/total-bytes planning
        proceeds unchanged)."""
        if self.telemetry is not None:
            return self.telemetry.partition_lane_bytes(shuffle_id)
        return {}

    def unregister_shuffle(self, shuffle_id: int) -> None:
        if self.merge_endpoint is not None:
            self.merge_endpoint.drop_shuffle(shuffle_id)
        if self.replica_store is not None:
            self.replica_store.drop_shuffle(shuffle_id)
        if self.telemetry is not None:
            self.telemetry.drop_partition_bytes(shuffle_id)
        self.resolver.remove_shuffle(shuffle_id)
        if self.metastore is not None:
            self.metastore.drop_shuffle(shuffle_id)
        with self._lock:
            self._registered.pop(shuffle_id, None)
            self._maps_done.pop(shuffle_id, None)
            self._deferred_fetches.pop(shuffle_id, None)
            self._maps_by_exec.pop(shuffle_id, None)
            self._map_owner.pop(shuffle_id, None)
            self._replica_locations.pop(shuffle_id, None)
            self._publish_origins.pop(shuffle_id, None)
            self._shuffle_locks.pop(shuffle_id, None)

    # ------------------------------------------------------------------
    def get_channel_to(self, mid: ShuffleManagerId, purpose: str = "rpc"):
        assert self.node is not None
        return self.node.get_channel(mid.host, mid.port, purpose=purpose)

    @property
    def buffer_manager(self):
        assert self.node is not None
        return self.node.buffer_manager

    def metrics_snapshot(self) -> dict:
        """One live observability dict for this manager.

        The reference scatters its observability across shutdown logs
        (pool stats RdmaBufferManager.java:131-141, fetch histograms
        RdmaShuffleReaderStats.scala:48-75) — here the same counters
        are queryable mid-run so workload artifacts can record them
        (benchmarks/run_workloads.py writes one per e2e run)."""
        snap: dict = {
            "executor_id": self.executor_id,
            "is_driver": self.is_driver,
        }
        node = self.node
        if node is not None:
            snap["transport"] = type(node).__name__
            snap["registered_pool_allocs_by_class"] = {
                str(k): v for k, v in node.buffer_manager.stats().items()
            }
            rps = getattr(node, "read_path_stats", None)
            if rps is not None:
                fast, streamed = rps()
                snap["reads_samehost_fast_path"] = fast
                snap["reads_streamed"] = streamed
        if self.reader_stats is not None:
            snap["fetch_latency_histograms"] = self.reader_stats.snapshot()
        # read-path ShuffleMetrics aggregated over every reader this
        # manager created (live + finished)
        agg = {
            "local_blocks": 0,
            "remote_blocks": 0,
            "local_bytes": 0,
            "remote_bytes": 0,
            "fetch_wait_ms": 0,
            "records_read": 0,
            "sort_spills": 0,
        }
        with self._lock:
            readers = list(self._reader_metrics)
        for m in readers:
            for k in agg:
                agg[k] += getattr(m, k, 0)
        snap["shuffle_read"] = agg
        # circuit-breaker states per tracked remote peer (resilience)
        snap["source_health"] = self.health.states()
        if self.telemetry is not None:
            snap["telemetry"] = self.telemetry.summary()
            snap["slo"] = self.telemetry.slo.summary()
        # the unified registry view: every instrument whose labels are
        # compatible with this manager's role (process-global metrics
        # without a role label are included)
        snap["registry"] = self.registry.snapshot(match={"role": self.executor_id})
        return snap

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            map_pool, self._map_pool = self._map_pool, None
            ck_pool, self._ck_pool = self._ck_pool, None
        if self.admission is not None:
            self.admission.close()  # queued jobs raise AdmissionClosed
        if map_pool is not None:
            map_pool.shutdown(wait=True)
        if ck_pool is not None:
            ck_pool.shutdown(wait=True)
        if self.merge_endpoint is not None:
            from sparkrdma_tpu.shuffle import merge as _merge

            _merge.unregister_endpoint(self.merge_endpoint)
            self.merge_endpoint.stop()
        if self.replica_store is not None:
            from sparkrdma_tpu import elastic as _elastic

            _elastic.unregister_store(self.replica_store)
            self.replica_store.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.reader_stats is not None:
            self.reader_stats.print_stats()
        self.resolver.stop()
        if self.node is not None:
            self.node.stop()
