"""Wrapper writer method (default): sort-shuffle file, mmap'd+registered.

Analogue of wrapper/RdmaWrapperShuffleWriter.scala (reference: /root/
reference/src/main/scala/org/apache/spark/shuffle/rdma/writer/wrapper/
RdmaWrapperShuffleWriter.scala). Semantics preserved:

- record writing is delegated to the sort-shuffle machinery
  (:85-101 → sort_file.write_sorted_file here),
- ``write_index_file_and_commit`` renames the tmp data file and
  mmaps+registers it chunked by ``shuffle_write_block_size`` with
  per-partition locations (:57-74),
- on successful ``stop()`` the writer collects every **non-empty**
  partition's location from the mapped file and publishes to the
  driver with partition_id = -1 (:106-140; the driver re-keys each
  location by its own partition id),
- partitions are servable locally as streams (:40-44).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, replace
from typing import BinaryIO, Dict, List, Optional, Sequence

from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.memory.mapped_file import MappedFile
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.memory.streams import MemoryviewInputStream
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle
from sparkrdma_tpu.shuffle.writer import ShuffleData
from sparkrdma_tpu.shuffle.writer.sort_file import write_sorted_file


@dataclass
class MapStatus:
    map_id: int
    partition_lengths: List[int]


class WrapperShuffleData(ShuffleData):
    def __init__(self, resolver, shuffle_id: int, num_partitions: int):
        self._resolver = resolver
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self._mapped: Dict[int, MappedFile] = {}
        # per-map per-partition block formats (BlockLocation.FORMAT_*):
        # the columnar negotiation outcome travels with the mapped file
        # so every publish path — writer stop, HA re-adoption sweep —
        # advertises the same encoding tag
        self._formats: Dict[int, List[int]] = {}
        self._lock = threading.Lock()

    def new_shuffle_writer(self) -> None:
        pass  # no per-writer state for this method

    def write_index_file_and_commit(
        self,
        map_id: int,
        partition_lengths: Sequence[int],
        data_tmp_path: str,
        partition_formats: Optional[Sequence[int]] = None,
    ) -> None:
        final_path = self._resolver.data_file_path(self.shuffle_id, map_id)
        os.replace(data_tmp_path, final_path)
        mf = MappedFile(
            final_path,
            self._resolver.pd,
            self._resolver.conf.shuffle_write_block_size,
            list(partition_lengths),
        )
        with self._lock:
            old = self._mapped.pop(map_id, None)
            self._mapped[map_id] = mf
            if partition_formats is not None:
                self._formats[map_id] = list(partition_formats)
            else:
                self._formats.pop(map_id, None)
        if old is not None:
            old.dispose()  # speculative re-run replaced the output

    def partition_format(self, map_id: int, pid: int) -> int:
        with self._lock:
            formats = self._formats.get(map_id)
        return formats[pid] if formats else 0

    def get_mapped_file(self, map_id: int) -> MappedFile:
        with self._lock:
            return self._mapped[map_id]

    def handoff_manifest(self) -> List[dict]:
        """Elastic layer: describe every committed map output by file
        path + per-partition lengths — everything the shuffle-service
        daemon needs to re-mmap and re-register the same bytes
        (elastic/service.py) without copying them."""
        with self._lock:
            items = sorted(self._mapped.items())
        return [
            {
                "map_id": map_id,
                "path": os.path.abspath(mf.path),
                "partition_lengths": [
                    mf.get_partition_location(pid).length
                    for pid in range(mf.partition_count())
                ],
            }
            for map_id, mf in items
        ]

    def committed_map_locations(
        self, manager_id
    ) -> Dict[int, List[PartitionLocation]]:
        """Control-plane HA (sparkrdma_tpu/metastore): rebuild the
        publishable locations of every committed map output — the same
        non-empty-partition collection WrapperShuffleWriter.stop()
        published the first time. A wiped hub re-adopts from this sweep
        instead of recomputing; an all-empty map yields [] and is still
        re-published so the map-output barrier re-completes."""
        with self._lock:
            items = sorted(self._mapped.items())
        return {
            map_id: [
                PartitionLocation(
                    manager_id,
                    pid,
                    replace(
                        mf.get_partition_location(pid),
                        source_map=map_id,
                        block_format=self.partition_format(map_id, pid),
                    ),
                )
                for pid in range(mf.partition_count())
                if mf.get_partition_location(pid).length > 0
            ]
            for map_id, mf in items
        }

    def get_input_streams(self, partition_id: int) -> List[BinaryIO]:
        with self._lock:
            files = list(self._mapped.values())
        return [
            MemoryviewInputStream(mf.get_partition_view(partition_id))
            for mf in files
            if mf.get_partition_location(partition_id).length > 0
        ]

    def remove_data_by_map(self, map_id: int) -> None:
        with self._lock:
            mf = self._mapped.pop(map_id, None)
        if mf is not None:
            mf.dispose()

    def dispose(self) -> None:
        with self._lock:
            files = list(self._mapped.values())
            self._mapped.clear()
        for mf in files:
            mf.dispose()


class WrapperShuffleWriter:
    """One map task's writer (reference :80-140)."""

    def __init__(self, manager, handle: BaseShuffleHandle, map_id: int):
        self._manager = manager
        self._handle = handle
        self.map_id = map_id
        self._data: WrapperShuffleData = manager.resolver.get_or_create_shuffle_data(handle)
        self._data.new_shuffle_writer()
        self._lengths: Optional[List[int]] = None
        self._formats: Optional[List[int]] = None
        self._stopped = False

    def write(self, records) -> None:
        resolver = self._manager.resolver
        conf = self._manager.conf
        tmp = resolver.data_tmp_path(self._handle.shuffle_id, self.map_id)
        res = write_sorted_file(
            records, self._handle, resolver.codec, tmp,
            block_format=conf.block_format,
            batch_rows=conf.block_columnar_batch_rows,
        )
        self._data.write_index_file_and_commit(
            self.map_id, res.lengths, tmp, partition_formats=res.formats
        )
        self._lengths = res.lengths
        self._formats = res.formats
        if res.columnar_frames or res.pickle_fallbacks:
            role = self._manager.executor_id
            reg = get_registry()
            reg.counter("block.columnar_blocks", role=role).inc(
                res.columnar_frames
            )
            reg.counter("block.columnar_bytes", role=role).inc(
                res.columnar_bytes
            )
            if res.pickle_fallbacks:
                reg.counter("block.pickle_fallbacks", role=role).inc(
                    res.pickle_fallbacks
                )

    def stop(self, success: bool) -> Optional[MapStatus]:
        if self._stopped:
            return None
        self._stopped = True
        if not success or self._lengths is None:
            self._data.remove_data_by_map(self.map_id)
            return None
        # collect non-empty partition locations and publish (:121-136);
        # an all-empty map output still publishes so the driver's
        # map-output count completes
        mf = self._data.get_mapped_file(self.map_id)
        formats = self._formats or [0] * self._handle.num_partitions
        locs = [
            PartitionLocation(
                self._manager.local_manager_id,
                pid,
                replace(
                    mf.get_partition_location(pid),
                    source_map=self.map_id,
                    block_format=formats[pid],
                ),
            )
            for pid in range(self._handle.num_partitions)
            if mf.get_partition_location(pid).length > 0
        ]
        role = self._manager.executor_id
        reg = get_registry()
        reg.counter("writer.map_outputs", role=role, method="wrapper").inc()
        reg.counter("writer.partitions_written", role=role).inc(len(locs))
        reg.counter("writer.bytes_written", role=role).inc(sum(self._lengths))
        self._manager.publish_partition_locations(
            self._handle.shuffle_id, -1, locs, num_map_outputs=1
        )
        # elastic layer: best-effort replication of this map's bytes to
        # peer executors (conf elastic.replicas; never a write failure)
        client = getattr(self._manager, "replica_client", None)
        if client is not None and locs:
            client.replicate_map(self._handle.shuffle_id, self.map_id, mf)
        return MapStatus(self.map_id, self._lengths)
