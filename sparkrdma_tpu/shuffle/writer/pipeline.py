"""MapTaskPipeline — the pipelined device-accelerated map plane.

WORKLOADS_r05 pinned the e2e TeraSort loss on the map side: a
sequential host-sort -> stage -> publish loop whose 22.95 s wall
exceeded the whole host baseline job. The fix is structural, the same
one the reduce side already uses (fetch/merge overlap, SURVEY §2.3):
run the three map stages as a pipeline over shards,

    sort (device, MapShardSorter)     shard k+1
      -> stage into registered memory  shard k      (writer -> memory/)
        -> publish locations           shard k-1    (driver RPC)

so while shard k stages, shard k+1 sorts on device and shard k-1's
locations upload. Stage concurrency:

- ``parallelism`` sort workers (conf ``map.parallelism``) — the bounded
  map-task pool; sorts are the heavy stage and the device serializes
  them anyway, but extra workers overlap the host-side pad/readback
  halves of adjacent shards,
- one stage worker and one publish worker, fed by bounded queues
  (conf ``map.pipelineDepth``) so at most ``parallelism + depth``
  shards hold staging memory at once.

Abort semantics: the first stage error latches, everything not yet
published drains WITHOUT publishing, and ``run`` re-raises — a map
shard's locations go out atomically (one publish per shard) or not at
all, so an abort can never leave a partial location set for any shard
(the driver's map barrier stays incomplete and fetches keep
deferring).

Observability (docs/OBSERVABILITY.md): per-stage latency histograms
``writer.pipeline.stage_ms{stage=sort|stage|publish}``, the live
``writer.pipeline.inflight`` gauge, and ``writer.pipeline.overlap_ms``
— per-run sum-of-stage-busy minus wall, the measured time the overlap
SAVED (zero means the pipeline degenerated to sequential).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.obs import get_registry, get_tracer

STAGES = ("sort", "stage", "publish")

# stage latencies range from sub-ms (publish RPC enqueue) to multi-s
# (device sort of a GiB shard)
_STAGE_BOUNDS = (1, 5, 10, 50, 100, 500, 1000, 5000, 10000, 60000)

_CLOSE = object()  # queue sentinel: producer is done


@dataclass
class PipelineReport:
    """What one ``run`` measured — the ledger's map-plane attribution."""

    wall_s: float
    stage_busy_s: Dict[str, float]
    overlap_s: float
    results: List[Any] = field(default_factory=list)

    @property
    def busy_total_s(self) -> float:
        return sum(self.stage_busy_s.values())


class MapTaskPipeline:
    """Three-stage bounded pipeline over map-shard items.

    ``sort_fn(item)``, ``stage_fn(item, sorted)``, ``publish_fn(item,
    staged)`` are the stage bodies; any may be None to skip that stage
    (its input passes through). ``run(items)`` returns a
    :class:`PipelineReport` whose ``results[i]`` is the last stage's
    return value for ``items[i]``.
    """

    def __init__(
        self,
        sort_fn: Optional[Callable[[Any], Any]],
        stage_fn: Optional[Callable[[Any, Any], Any]],
        publish_fn: Optional[Callable[[Any, Any], Any]],
        *,
        parallelism: int = 2,
        depth: int = 2,
        role: str = "writer",
    ):
        self._sort_fn = sort_fn
        self._stage_fn = stage_fn
        self._publish_fn = publish_fn
        self._parallelism = max(1, int(parallelism))
        self._depth = max(1, int(depth))
        self._role = role

    # ------------------------------------------------------------------
    def run(self, items: Sequence[Any]) -> PipelineReport:
        items = list(items)
        # the stage/publish threads and sort pool are bare threads: they
        # must inherit the submitting task's tenant so buffer charges
        # and breaker keys stay attributed to the right tenant
        tenant = tenancy.current_tenant()
        reg = get_registry()
        inflight = reg.gauge("writer.pipeline.inflight", role=self._role)
        hists = {
            s: reg.histogram(
                "writer.pipeline.stage_ms",
                bounds=_STAGE_BOUNDS,
                role=self._role,
                stage=s,
            )
            for s in STAGES
        }
        busy = {s: 0.0 for s in STAGES}
        busy_lock = threading.Lock()
        abort = threading.Event()
        errbox: List[BaseException] = []
        err_lock = threading.Lock()
        results: List[Any] = [None] * len(items)

        def fail(e: BaseException) -> None:
            with err_lock:
                if not errbox:
                    errbox.append(e)
            abort.set()

        tracer = get_tracer(self._role)

        def timed(stage: str, follows, fn: Callable, *args):
            """Run one stage body inside a ``writer.pipeline.<stage>``
            span that causally follows the item's previous stage span
            (the queue hand-off edge). Returns (result, span)."""
            t0 = time.perf_counter()
            try:
                with tracer.span(
                    "writer.pipeline." + stage, follows=follows
                ) as sp:
                    return fn(*args), sp
            finally:
                dt = time.perf_counter() - t0
                hists[stage].observe(dt * 1e3)
                with busy_lock:
                    busy[stage] += dt

        # stage-to-stage handoff: bounded, so a slow downstream stage
        # backpressures instead of accumulating every shard's output
        stage_q: "queue.Queue" = queue.Queue(self._depth)
        publish_q: "queue.Queue" = queue.Queue(self._depth)

        def sort_one(idx: int) -> None:
            inflight.add(1)
            try:
                if abort.is_set():
                    inflight.add(-1)
                    return
                out, sp = (
                    timed("sort", None, self._sort_fn, items[idx])
                    if self._sort_fn is not None
                    else (items[idx], None)
                )
                # blocking put IS the backpressure; an abort raised
                # downstream closes the queues only after draining, so
                # this never deadlocks
                schedule_point("queue", "writer.stage_q.put")
                stage_q.put((idx, out, sp))
            except BaseException as e:  # noqa: BLE001 — latch and drain
                inflight.add(-1)
                fail(e)

        def stage_main() -> None:
            while True:
                schedule_point("queue", "writer.stage_q.get")
                got = stage_q.get()
                if got is _CLOSE:
                    publish_q.put(_CLOSE)
                    return
                idx, sorted_out, prev = got
                if abort.is_set():
                    inflight.add(-1)
                    continue
                try:
                    staged, sp = (
                        timed("stage", prev, self._stage_fn, items[idx], sorted_out)
                        if self._stage_fn is not None
                        else (sorted_out, prev)
                    )
                    schedule_point("queue", "writer.publish_q.put")
                    publish_q.put((idx, staged, sp))
                except BaseException as e:  # noqa: BLE001
                    inflight.add(-1)
                    fail(e)

        def publish_main() -> None:
            while True:
                schedule_point("queue", "writer.publish_q.get")
                got = publish_q.get()
                if got is _CLOSE:
                    return
                idx, staged, prev = got
                if abort.is_set():
                    inflight.add(-1)
                    continue
                try:
                    results[idx] = (
                        timed("publish", prev, self._publish_fn, items[idx], staged)[0]
                        if self._publish_fn is not None
                        else staged
                    )
                except BaseException as e:  # noqa: BLE001
                    fail(e)
                finally:
                    inflight.add(-1)

        t_wall0 = time.perf_counter()
        stage_t = threading.Thread(
            target=tenancy.scoped(tenant, stage_main),
            name="map-pipeline-stage",
            daemon=True,
        )
        publish_t = threading.Thread(
            target=tenancy.scoped(tenant, publish_main),
            name="map-pipeline-publish",
            daemon=True,
        )
        stage_t.start()
        publish_t.start()
        pool = ThreadPoolExecutor(
            self._parallelism, thread_name_prefix="map-pipeline-sort"
        )
        try:
            sort_scoped = tenancy.scoped(tenant, sort_one)
            futures = [pool.submit(sort_scoped, i) for i in range(len(items))]
            for f in futures:
                f.result()  # sort_one never raises; this is a join
        finally:
            pool.shutdown(wait=True)
            stage_q.put(_CLOSE)
            stage_t.join()
            publish_t.join()
        wall = time.perf_counter() - t_wall0

        if errbox:
            raise errbox[0]
        overlap = max(0.0, sum(busy.values()) - wall)
        reg.histogram(
            "writer.pipeline.overlap_ms", bounds=_STAGE_BOUNDS, role=self._role
        ).observe(overlap * 1e3)
        return PipelineReport(
            wall_s=wall,
            stage_busy_s=dict(busy),
            overlap_s=overlap,
            results=results,
        )
