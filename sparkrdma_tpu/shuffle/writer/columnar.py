"""ColumnarPartitionWriter — batched columnar framing for one partition.

The map-side half of the columnar block format (DESIGN.md §25,
shuffle/columnar.py): records for one partition accumulate into a
batch; a conforming batch (same-arity tuples of fixed-width numpy
scalars) serializes into ONE columnar frame — column vectors laid out
contiguously so device staging and the reduce-side decode are raw byte
views — and a non-conforming batch falls back to ONE pickle-stream
frame through the same codec the legacy writer uses. The two frame
kinds interleave freely inside a partition block; the reduce side
sniffs the per-frame magic.

A partition whose every frame came out columnar is tagged
``BlockLocation.FORMAT_COLUMNAR`` at publish (the collective compiler's
wave-eligibility signal: such blocks are 8-aligned by construction);
any pickle fallback keeps the tag at the pickle default.
"""

from __future__ import annotations

import pickle
import struct
from typing import List, Tuple

from sparkrdma_tpu.engine.serializer import (
    CompressionCodec,
    frame_columnar,
    frame_compressed,
)
from sparkrdma_tpu.shuffle.columnar import encode_batch

_LEN_PACK = struct.Struct(">I").pack


class ColumnarPartitionWriter:
    """Accumulates records, emits columnar-or-pickle frames per batch."""

    __slots__ = (
        "_codec", "_sink", "_batch", "_batch_rows",
        "columnar_frames", "columnar_bytes", "pickle_fallbacks",
    )

    def __init__(self, codec: CompressionCodec, sink, batch_rows: int = 4096):
        self._codec = codec
        self._sink = sink  # callable(bytes) -> None
        self._batch: List[Tuple] = []
        self._batch_rows = max(1, batch_rows)
        self.columnar_frames = 0
        self.columnar_bytes = 0
        self.pickle_fallbacks = 0

    @property
    def all_columnar(self) -> bool:
        """True when every emitted frame was columnar (and one exists)."""
        return self.columnar_frames > 0 and self.pickle_fallbacks == 0

    def write_record(self, rec: Tuple) -> None:
        self._batch.append(rec)
        if len(self._batch) >= self._batch_rows:
            self.flush_batch()

    def flush_batch(self) -> None:
        if not self._batch:
            return
        payload = encode_batch(self._batch)
        if payload is not None:
            framed = frame_columnar(payload)
            self._sink(framed)
            self.columnar_frames += 1
            self.columnar_bytes += len(framed)
        else:
            # the universal fallback: this batch as one pickle frame
            buf = bytearray()
            for rec in self._batch:
                data = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
                buf += _LEN_PACK(len(data))
                buf += data
            self._sink(frame_compressed(self._codec, bytes(buf)))
            self.pickle_fallbacks += 1
        self._batch.clear()
