"""Sort-shuffle file writer — the Spark sort/spill machinery stand-in.

The reference's Wrapper method delegates record writing to Spark's own
UnsafeShuffleWriter/SortShuffleWriter (reference: wrapper/
RdmaWrapperShuffleWriter.scala:85-101), which produce one data file per
map task with partitions laid out consecutively plus an index of
lengths. This module reproduces that contract: records are routed to
their partition, serialized and compressed into per-partition spooled
scratch streams (spilling to disk past a threshold, the ExternalSorter
role), then concatenated into the final data-tmp file.
"""

from __future__ import annotations

import itertools
import tempfile
from typing import Iterable, List, NamedTuple, Tuple

from sparkrdma_tpu.engine.serializer import (
    CompressedBlockWriter,
    CompressionCodec,
)
from sparkrdma_tpu.locations import BlockLocation
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, combine_by_key

SPOOL_MAX = 8 << 20  # per-partition in-memory spool before spilling to disk


class SortFileResult(NamedTuple):
    """Per-partition byte lengths + block formats, plus frame stats for
    the writer's ``block.*`` metric family (obs/metrics.py)."""

    lengths: List[int]
    formats: List[int]  # BlockLocation.FORMAT_* per partition
    columnar_frames: int
    columnar_bytes: int
    pickle_fallbacks: int


def _conforms(rec) -> bool:
    """Cheap auto-negotiation sniff: does ONE record look columnar-able?
    (The per-batch encoder re-checks the whole batch; this only decides
    whether ``auto`` engages the columnar writers at all.)"""
    import numpy as np

    from sparkrdma_tpu.shuffle.columnar import _code_for

    return (
        type(rec) is tuple
        and len(rec) > 0
        and all(
            isinstance(v, np.generic) and _code_for(v.dtype) is not None
            for v in rec
        )
    )


def write_sorted_file(
    records: Iterable[Tuple],
    handle: BaseShuffleHandle,
    codec: CompressionCodec,
    data_tmp_path: str,
    block_format: str = "pickle",
    batch_rows: int = 4096,
) -> SortFileResult:
    """Write records partitioned+serialized+compressed; returns lengths,
    per-partition block formats, and frame stats.

    Applies map-side combine when the handle requests it (the reference
    reader/writer split this with Spark; SURVEY.md §3.3).

    ``block_format`` negotiates the payload encoding (DESIGN.md §25):
    ``pickle`` is the legacy frame stream; ``columnar`` batches records
    through :class:`ColumnarPartitionWriter` (per-batch pickle fallback
    for non-conforming batches); ``auto`` sniffs the first record and
    picks — fixed-width numpy tuples go columnar, everything else stays
    on the byte-identical legacy path.
    """
    num_partitions = handle.num_partitions
    part = handle.partitioner.partition

    if handle.aggregator is not None and handle.map_side_combine:
        records = combine_by_key(records, handle.aggregator).items()

    if block_format == "auto":
        it = iter(records)
        first = next(it, None)
        if first is None:
            records = ()
        else:
            records = itertools.chain([first], it)
        block_format = (
            "columnar" if first is not None and _conforms(first) else "pickle"
        )

    spools = [tempfile.SpooledTemporaryFile(max_size=SPOOL_MAX) for _ in range(num_partitions)]
    formats = [BlockLocation.FORMAT_PICKLE] * num_partitions
    col_frames = col_bytes = fallbacks = 0

    if block_format == "columnar":
        from sparkrdma_tpu.shuffle.writer.columnar import ColumnarPartitionWriter

        cwriters = [
            ColumnarPartitionWriter(codec, spools[p].write, batch_rows)
            for p in range(num_partitions)
        ]
        for rec in records:
            cwriters[part(rec[0])].write_record(rec)
        for p, w in enumerate(cwriters):
            w.flush_batch()
            if w.all_columnar:
                formats[p] = BlockLocation.FORMAT_COLUMNAR
            col_frames += w.columnar_frames
            col_bytes += w.columnar_bytes
            fallbacks += w.pickle_fallbacks
    else:
        writers = [CompressedBlockWriter(codec, spools[p].write) for p in range(num_partitions)]

        import pickle
        import struct

        pack = struct.Struct(">I").pack
        dumps = pickle.dumps
        flush_size = 256 << 10
        for rec in records:
            data = dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            w = writers[part(rec[0])]
            w.write(pack(len(data)))
            w.write(data)
            if w.pending >= flush_size:
                w.flush_block()
        for p in range(num_partitions):
            writers[p].flush_block()

    lengths = [0] * num_partitions
    with open(data_tmp_path, "wb") as out:
        for p in range(num_partitions):
            spool = spools[p]
            spool.seek(0)
            start = out.tell()
            while True:
                chunk = spool.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
            lengths[p] = out.tell() - start
            spool.close()
    return SortFileResult(lengths, formats, col_frames, col_bytes, fallbacks)
