"""Sort-shuffle file writer — the Spark sort/spill machinery stand-in.

The reference's Wrapper method delegates record writing to Spark's own
UnsafeShuffleWriter/SortShuffleWriter (reference: wrapper/
RdmaWrapperShuffleWriter.scala:85-101), which produce one data file per
map task with partitions laid out consecutively plus an index of
lengths. This module reproduces that contract: records are routed to
their partition, serialized and compressed into per-partition spooled
scratch streams (spilling to disk past a threshold, the ExternalSorter
role), then concatenated into the final data-tmp file.
"""

from __future__ import annotations

import tempfile
from typing import Iterable, List, Tuple

from sparkrdma_tpu.engine.serializer import (
    CompressedBlockWriter,
    CompressionCodec,
)
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, combine_by_key

SPOOL_MAX = 8 << 20  # per-partition in-memory spool before spilling to disk


def write_sorted_file(
    records: Iterable[Tuple],
    handle: BaseShuffleHandle,
    codec: CompressionCodec,
    data_tmp_path: str,
) -> List[int]:
    """Write records partitioned+serialized+compressed; returns lengths.

    Applies map-side combine when the handle requests it (the reference
    reader/writer split this with Spark; SURVEY.md §3.3).
    """
    num_partitions = handle.num_partitions
    part = handle.partitioner.partition

    if handle.aggregator is not None and handle.map_side_combine:
        records = combine_by_key(records, handle.aggregator).items()

    spools = [tempfile.SpooledTemporaryFile(max_size=SPOOL_MAX) for _ in range(num_partitions)]
    writers = [CompressedBlockWriter(codec, spools[p].write) for p in range(num_partitions)]

    import pickle
    import struct

    pack = struct.Struct(">I").pack
    dumps = pickle.dumps
    flush_size = 256 << 10
    for rec in records:
        data = dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        w = writers[part(rec[0])]
        w.write(pack(len(data)))
        w.write(data)
        if w.pending >= flush_size:
            w.flush_block()

    lengths = [0] * num_partitions
    with open(data_tmp_path, "wb") as out:
        for p in range(num_partitions):
            writers[p].flush_block()
            spool = spools[p]
            spool.seek(0)
            start = out.tell()
            while True:
                chunk = spool.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
            lengths[p] = out.tell() - start
            spool.close()
    return lengths
