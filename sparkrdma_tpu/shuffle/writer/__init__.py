"""Shuffle write data plane (L5).

``ShuffleData`` is the per-shuffle storage abstraction shared by both
writer strategies — analogue of the RdmaShuffleData trait (reference:
/root/reference/src/main/scala/org/apache/spark/shuffle/rdma/writer/
RdmaShuffleData.scala:22-28). Both implementations expose identical
semantics and are chosen purely by config (SURVEY.md §5.1 #6).
"""

from __future__ import annotations

from typing import BinaryIO, List, Optional, Sequence


class ShuffleData:
    def new_shuffle_writer(self) -> None:
        """A map-task writer for this shuffle started on this executor."""
        raise NotImplementedError

    def get_input_streams(self, partition_id: int) -> List[BinaryIO]:
        """Local short-circuit read of a partition (no network loop)."""
        raise NotImplementedError

    def remove_data_by_map(self, map_id: int) -> None:
        raise NotImplementedError

    def write_index_file_and_commit(
        self,
        map_id: int,
        partition_lengths: Sequence[int],
        data_tmp_path: str,
        partition_formats: Optional[Sequence[int]] = None,
    ) -> None:
        raise NotImplementedError

    def dispose(self) -> None:
        raise NotImplementedError


from sparkrdma_tpu.shuffle.writer.wrapper import (  # noqa: E402
    WrapperShuffleData,
    WrapperShuffleWriter,
)
from sparkrdma_tpu.shuffle.writer.chunked_agg import (  # noqa: E402
    ChunkedAggShuffleData,
    ChunkedAggShuffleWriter,
)
from sparkrdma_tpu.shuffle.writer.pipeline import (  # noqa: E402
    MapTaskPipeline,
    PipelineReport,
)

__all__ = [
    "ShuffleData",
    "WrapperShuffleData",
    "WrapperShuffleWriter",
    "ChunkedAggShuffleData",
    "ChunkedAggShuffleWriter",
    "MapTaskPipeline",
    "PipelineReport",
]
