"""Writer storage blocks: registered memory or registered file-backed.

Analogue of RdmaWriterBlock.scala (reference: /root/reference/src/main/
scala/org/apache/spark/shuffle/rdma/writer/chunkedpartitionagg/
RdmaWriterBlock.scala): a block SPI with two implementations —
``MemoryWriterBlock`` over a registered buffer (:39-93) and
``FileWriterBlock`` over a registered mapping of a scratch file
(:95-149). Both track the actual readable length and emit
``(address, length, mkey)`` locations for remote one-sided READ.
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import BinaryIO

from sparkrdma_tpu.locations import BlockLocation
from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.memory.streams import MemoryviewInputStream


class WriterBlock:
    """Append-only fixed-capacity storage block."""

    capacity: int

    def remaining(self) -> int:
        raise NotImplementedError

    def append(self, data) -> int:
        """Append up to remaining() bytes; returns bytes written."""
        raise NotImplementedError

    def location(self) -> BlockLocation:
        raise NotImplementedError

    def input_stream(self) -> BinaryIO:
        raise NotImplementedError

    def dispose(self) -> None:
        raise NotImplementedError


class MemoryWriterBlock(WriterBlock):
    def __init__(self, pd: ProtectionDomain, capacity: int):
        self.capacity = capacity
        self._buf = TpuBuffer(pd, capacity)
        self._len = 0
        self._lock = threading.Lock()

    def remaining(self) -> int:
        with self._lock:
            return self.capacity - self._len

    def append(self, data) -> int:
        with self._lock:
            n = min(len(data), self.capacity - self._len)
            if n:
                self._buf.view[self._len : self._len + n] = data[:n]
                self._len += n
            return n

    def location(self) -> BlockLocation:
        with self._lock:
            return BlockLocation(0, self._len, self._buf.mkey)

    def input_stream(self) -> BinaryIO:
        with self._lock:
            return MemoryviewInputStream(self._buf.view[: self._len])

    def dispose(self) -> None:
        self._buf.free()


class FileWriterBlock(WriterBlock):
    """Scratch-file block, mmap'd read-write and registered.

    The reference creates the file through diskBlockManager and maps it
    with RdmaMappedFile (:95-149); here the rw mapping itself is the
    registered region, so appended bytes are immediately remotely
    readable.
    """

    def __init__(self, pd: ProtectionDomain, capacity: int, path: str):
        self.capacity = capacity
        self.path = path
        self._pd = pd
        with open(path, "wb") as f:
            f.truncate(capacity)
        self._file = open(path, "r+b")
        self._mm = mmap.mmap(self._file.fileno(), capacity)
        self._view = memoryview(self._mm)
        self._mkey = pd.register(self._view)
        self._len = 0
        self._lock = threading.Lock()

    def remaining(self) -> int:
        with self._lock:
            return self.capacity - self._len

    def append(self, data) -> int:
        with self._lock:
            n = min(len(data), self.capacity - self._len)
            if n:
                self._view[self._len : self._len + n] = data[:n]
                self._len += n
            return n

    def location(self) -> BlockLocation:
        with self._lock:
            return BlockLocation(0, self._len, self._mkey)

    def input_stream(self) -> BinaryIO:
        with self._lock:
            return MemoryviewInputStream(self._view[: self._len])

    def dispose(self) -> None:
        self._pd.deregister(self._mkey)
        try:
            self._view.release()
            self._mm.close()
        except BufferError:
            pass  # live sub-views keep the mapping alive until GC
        self._file.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
