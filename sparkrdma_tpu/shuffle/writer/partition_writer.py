"""PartitionWriter — per-(shuffle, partition) append-only block log.

Analogue of RdmaShufflePartitionWriter.scala (reference: /root/
reference/src/main/scala/org/apache/spark/shuffle/rdma/writer/
chunkedpartitionagg/RdmaShufflePartitionWriter.scala). Semantics
preserved:

- storage is a list of ``shuffle_write_block_size`` blocks; new blocks
  are **memory** while the executor-wide in-memory budget admits them,
  else **file-backed** scratch blocks (:42-52),
- bump-pointer offset allocation under a lock so concurrent map tasks
  append without interleaving corruption (:54-72),
- exposes every block's ``(address, length, mkey)`` location and local
  input streams (:109-122).
"""

from __future__ import annotations

import threading
from typing import BinaryIO, List

from sparkrdma_tpu.locations import BlockLocation
from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.writer.blocks import (
    FileWriterBlock,
    MemoryWriterBlock,
    WriterBlock,
)

_M_MEM_BLOCKS = get_registry().counter("writer.blocks_memory")
_M_SPILL_BLOCKS = get_registry().counter("writer.blocks_spilled")
_M_SPILL_BYTES = get_registry().counter("writer.spill_bytes")


class PartitionWriter:
    def __init__(self, resolver, shuffle_id: int, partition_id: int, block_size: int):
        self._resolver = resolver
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.block_size = block_size
        self._blocks: List[WriterBlock] = []
        self._lock = threading.Lock()

    def _add_block(self, capacity: int) -> WriterBlock:
        """Memory while under budget, else spill to a scratch file (:42-52)."""
        pd: ProtectionDomain = self._resolver.pd
        if self._resolver.reserve_inmemory_bytes(capacity):
            block = MemoryWriterBlock(pd, capacity)
            block.reserved_bytes = capacity
            _M_MEM_BLOCKS.inc()
            return block
        path = self._resolver.scratch_path(
            f"shuffle_{self.shuffle_id}_p{self.partition_id}_b{len(self._blocks)}"
        )
        block = FileWriterBlock(pd, capacity, path)
        block.reserved_bytes = 0
        _M_SPILL_BLOCKS.inc()
        _M_SPILL_BYTES.inc(capacity)
        return block

    def append_frame(self, framed) -> int:
        """Append one self-delimiting frame, never spanning blocks.

        Frame alignment is a deliberate departure from the reference
        (whose chunked-agg read path could split a compressed stream
        across writer blocks — part of why that method was experimental):
        a frame that does not fit the current block starts a fresh one,
        and an oversized frame gets a dedicated block of its exact size,
        so every published BlockLocation is independently parseable by
        the reader regardless of fetch grouping order.
        """
        mv = memoryview(framed) if not isinstance(framed, memoryview) else framed
        n = len(mv)
        with self._lock:
            if n > self.block_size:
                block = self._add_block(n)
                self._blocks.append(block)
            else:
                if not self._blocks or self._blocks[-1].remaining() < n:
                    self._blocks.append(self._add_block(self.block_size))
                block = self._blocks[-1]
            written = block.append(mv)
            assert written == n
        return n

    def locations(self) -> List[BlockLocation]:
        with self._lock:
            return [b.location() for b in self._blocks if b.location().length > 0]

    def sealed_count(self) -> int:
        """Number of blocks that can no longer change: ``append_frame``
        only ever writes into the LAST block (or starts a new one), so
        every non-tail block is immutable — safe to publish before the
        map barrier (incremental publish, chunked_agg.py)."""
        with self._lock:
            return max(0, len(self._blocks) - 1)

    def locations_range(self, start: int, end: int) -> List[BlockLocation]:
        """Block locations for indices [start, end) — the incremental
        publisher's cursor window. ``end`` may exceed the current block
        count (clamped); callers pass ``sealed_count()`` results so the
        window never includes the mutable tail."""
        with self._lock:
            blocks = self._blocks[start:end]
        return [b.location() for b in blocks if b.location().length > 0]

    def input_streams(self) -> List[BinaryIO]:
        with self._lock:
            return [b.input_stream() for b in self._blocks]

    @property
    def total_length(self) -> int:
        with self._lock:
            return sum(b.location().length for b in self._blocks)

    def dispose(self) -> None:
        with self._lock:
            blocks, self._blocks = self._blocks, []
        for b in blocks:
            reserved = getattr(b, "reserved_bytes", 0)
            b.dispose()
            if reserved:
                self._resolver.release_inmemory_bytes(reserved)
