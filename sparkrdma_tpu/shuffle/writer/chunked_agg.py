"""ChunkedPartitionAgg writer method: serialize straight into registered
chunks, aggregated per partition across all map tasks of an executor.

Analogue of chunkedpartitionagg/RdmaChunkedPartitionAggShuffleWriter.scala
(reference: /root/reference/src/main/scala/org/apache/spark/shuffle/
rdma/writer/chunkedpartitionagg/). Semantics preserved:

- per-partition stream stacks: serializer → compressor → chunked
  scratch buffers (:114-130), flushed into the shared per-partition
  :class:`PartitionWriter` once ``shuffle_write_flush_size`` bytes
  accumulate, with chunk recycling (:154-191),
- all map tasks of one executor append into the same partition logs,
  so the executor publishes **one aggregated location set** instead of
  one per map task (:45-73),
- publication happens at the map-stage barrier via
  ``finalize_and_publish`` (driven by the engine / manager), replacing
  the reference's fragile "last active writer publishes" trigger — and
  per-map partition lengths are tracked accurately, fixing the known
  wrong-MapStatus-lengths quirk (reference TODO at :217-218;
  SURVEY.md §5.1 "known quirks").

Trade-off vs Wrapper (as in the reference): no per-map data removal —
aggregated logs mix map outputs, so a failed map task that already
flushed frames **poisons** the shuffle's data on this executor:
``finalize_and_publish`` then refuses to publish (raising
ShuffleError), forcing the stage to re-run under a fresh shuffle id —
which is exactly how the engine retries failed map stages. A failed
map that never flushed leaves the logs clean and does not poison.
"""

from __future__ import annotations

import logging
import pickle
import struct
import threading
from typing import BinaryIO, Dict, List, Optional

from sparkrdma_tpu.engine.serializer import frame_compressed
from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, combine_by_key
from sparkrdma_tpu.shuffle.writer import ShuffleData
from sparkrdma_tpu.shuffle.writer.chunked_buffer import ChunkedByteBufferOutputStream
from sparkrdma_tpu.shuffle.writer.partition_writer import PartitionWriter
from sparkrdma_tpu.shuffle.writer.wrapper import MapStatus

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")


class ChunkedAggShuffleData(ShuffleData):
    def __init__(self, resolver, shuffle_id: int, num_partitions: int, num_maps: int = 0):
        self._resolver = resolver
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self.num_maps = num_maps
        self._writers: Dict[int, PartitionWriter] = {}
        self._lock = threading.Lock()
        self._active_shuffle_writers = 0
        self._committed_maps = 0
        self._published = False
        self._poisoned = False
        # incremental publish (conf map.incrementalPublish): sealed
        # (non-tail) blocks are immutable, so their locations upload as
        # maps commit; per-pid cursor of blocks already published
        self._incremental = bool(
            getattr(resolver.conf, "map_incremental_publish", False)
        )
        self._sealed_published: Dict[int, int] = {}
        # push/merge plane (shuffle/merge.py): independent per-pid
        # cursors so sealed blocks push toward their reducer whether or
        # not incremental publish is on; seq is a dense per-pid counter
        # assigned under the lock so concurrent commits keep block order
        self._push_cursor: Dict[int, int] = {}
        self._push_seq: Dict[int, int] = {}

    def partition_writer(self, pid: int) -> PartitionWriter:
        with self._lock:
            pw = self._writers.get(pid)
            if pw is None:
                pw = PartitionWriter(
                    self._resolver,
                    self.shuffle_id,
                    pid,
                    self._resolver.conf.shuffle_write_block_size,
                )
                self._writers[pid] = pw
            return pw

    def new_shuffle_writer(self) -> None:
        with self._lock:
            self._active_shuffle_writers += 1

    def commit_map_output(self, manager=None) -> None:
        """A map task finished successfully; counts toward the barrier.

        With ``map.incrementalPublish`` on (and a manager to publish
        through), every SEALED writer block whose location has not gone
        out yet uploads now, overlapping the remaining map compute.
        These segments carry ``num_map_outputs=0`` — the driver treats
        them as location-only and completes the barrier ONLY on the
        final ``finalize_and_publish`` count, so a fetch can never be
        answered from a partial location set (tail blocks and the last
        flushes only ship at finalize)."""
        with self._lock:
            self._active_shuffle_writers -= 1
            self._committed_maps += 1
            publishable = (
                self._incremental
                and manager is not None
                and not self._poisoned
                and not self._published
            )
            window = []
            if publishable:
                for pid, pw in self._writers.items():
                    sealed = pw.sealed_count()
                    cursor = self._sealed_published.get(pid, 0)
                    if sealed > cursor:
                        window.append((pid, pw, cursor, sealed))
                        self._sealed_published[pid] = sealed
            push_blocks = self._collect_push_locked(manager)
        if window:
            locs: List[PartitionLocation] = []
            for pid, pw, start, end in window:
                for block_loc in pw.locations_range(start, end):
                    locs.append(
                        PartitionLocation(manager.local_manager_id, pid, block_loc)
                    )
            if locs:
                get_registry().counter(
                    "writer.incremental_publishes", role=manager.executor_id
                ).inc()
                manager.publish_partition_locations(
                    self.shuffle_id, -1, locs, num_map_outputs=0
                )
        if push_blocks:
            self._push_blocks(manager, push_blocks)

    def _collect_push_locked(self, manager, tail: bool = False) -> List:
        """Under ``self._lock``: advance the push cursors over newly
        sealed blocks (ALL remaining blocks when ``tail``, at finalize)
        and assign each a dense per-pid seq — order fixed here, under
        the lock, so concurrent map commits cannot interleave seqs out
        of block order. Payload resolution happens later, outside."""
        if (
            manager is None
            or getattr(manager, "push_client", None) is None
            or self._poisoned
            or self._published and not tail
        ):
            return []
        out = []
        for pid, pw in self._writers.items():
            sealed = (1 << 30) if tail else pw.sealed_count()
            cursor = self._push_cursor.get(pid, 0)
            if sealed <= cursor:
                continue
            blocks = pw.locations_range(cursor, sealed)
            self._push_cursor[pid] = cursor + len(blocks) if tail else sealed
            for bl in blocks:
                seq = self._push_seq.get(pid, 0)
                self._push_seq[pid] = seq + 1
                out.append((pid, seq, bl))
        return out

    def _push_blocks(self, manager, blocks, final=None) -> None:
        """Resolve block payloads and hand them to the push client.
        Best-effort by design: any failure here is logged and dropped —
        the original locations stay authoritative."""
        client = getattr(manager, "push_client", None)
        if client is None or (not blocks and final is None):
            return
        try:
            manager.start_node_if_missing()
            pd = manager.node.pd
            payloads = [
                (pid, seq, bytes(pd.resolve(bl.mkey, bl.address, bl.length)))
                for pid, seq, bl in blocks
            ]
            client.push_window(
                self.shuffle_id, payloads, self.num_partitions, final=final
            )
        except Exception:
            logger.debug(
                "push window for shuffle %d failed", self.shuffle_id, exc_info=True
            )

    def abort_map_output(self, dirty: bool = False) -> None:
        """A map task failed: it must NOT count toward the driver's
        map-output barrier (its stage will re-run). ``dirty`` means the
        task already flushed frames into the shared logs, which cannot
        be excised — the whole shuffle's data here is now unpublishable.
        Locations already uploaded incrementally are harmless: the
        barrier count never went out, so the driver keeps deferring
        fetches, and the stage re-run's ``unregister_shuffle`` of this
        id drops them."""
        with self._lock:
            self._active_shuffle_writers -= 1
            if dirty:
                self._poisoned = True

    def finalize_and_publish(self, manager) -> None:
        """Publish the aggregated location set once, at the map barrier.

        Publishes even with zero locations (all-empty map outputs) so
        the driver's map-output count completes.
        """
        with self._lock:
            if self._poisoned:
                # a failed map's frames are interleaved in the shared
                # logs; publishing would duplicate its records when the
                # stage re-runs — refuse, forcing a fresh shuffle id
                from sparkrdma_tpu.shuffle.errors import ShuffleError

                raise ShuffleError(
                    f"shuffle {self.shuffle_id} chunked-agg data poisoned by a "
                    "failed map task; stage must re-run under a fresh shuffle id"
                )
            if self._published or self._committed_maps == 0:
                return
            if self._active_shuffle_writers > 0:
                # engine called finalize before every writer stopped —
                # publishing now would expose a partial location set
                logger.warning(
                    "finalize_and_publish with %d active writers on shuffle %d; deferring",
                    self._active_shuffle_writers,
                    self.shuffle_id,
                )
                return
            self._published = True
            writers = dict(self._writers)
            committed = self._committed_maps
            cursors = dict(self._sealed_published)
            push_blocks = self._collect_push_locked(manager, tail=True)
            push_final = None
            if getattr(manager, "push_client", None) is not None:
                push_final = {
                    "counts": {p: n for p, n in self._push_seq.items() if n},
                    "committed": committed,
                    "num_maps": self.num_maps,
                }
        # push the remainder plus the final coverage marker BEFORE the
        # barrier-completing publish below: merge endpoints seal and
        # publish their merged segments inside this synchronous call,
        # so merged locations reach the driver ahead of any deferred
        # fetch reply the barrier releases
        if push_final is not None:
            self._push_blocks(manager, push_blocks, final=push_final)
        # publish everything past each pid's incremental cursor (all of
        # it when incremental mode is off — cursors are then empty); the
        # full map-output count rides THIS message, completing the
        # driver's barrier only once every location is registered there
        locs: List[PartitionLocation] = []
        for pid, pw in writers.items():
            start = cursors.get(pid, 0)
            for block_loc in pw.locations_range(start, 1 << 30):
                locs.append(PartitionLocation(manager.local_manager_id, pid, block_loc))
        reg = get_registry()
        role = manager.executor_id
        reg.counter("writer.map_outputs", role=role, method="chunked_agg").inc(
            committed
        )
        reg.counter("writer.partitions_written", role=role).inc(len(writers))
        manager.publish_partition_locations(
            self.shuffle_id, -1, locs, num_map_outputs=committed
        )

    def get_input_streams(self, partition_id: int) -> List[BinaryIO]:
        with self._lock:
            pw = self._writers.get(partition_id)
        return pw.input_streams() if pw is not None else []

    def write_index_file_and_commit(self, map_id, partition_lengths, data_tmp_path):
        raise NotImplementedError("chunked-agg method does not use index files")

    def remove_data_by_map(self, map_id: int) -> None:
        # aggregated logs cannot excise one map's bytes; see module docstring
        pass

    def dispose(self) -> None:
        with self._lock:
            writers = list(self._writers.values())
            self._writers.clear()
        for pw in writers:
            pw.dispose()


class ChunkedAggShuffleWriter:
    """One map task's writer serializing into the executor-shared logs."""

    def __init__(self, manager, handle: BaseShuffleHandle, map_id: int):
        self._manager = manager
        self._handle = handle
        self.map_id = map_id
        self._data: ChunkedAggShuffleData = manager.resolver.get_or_create_shuffle_data(handle)
        self._data.new_shuffle_writer()
        self._conf = manager.conf
        self._codec = manager.resolver.codec
        self._streams: Dict[int, ChunkedByteBufferOutputStream] = {}
        self._recycled: List = []
        self._lengths = [0] * handle.num_partitions
        self._stopped = False
        self._dirty = False  # True once a frame reached the shared logs

    def _stream(self, pid: int) -> ChunkedByteBufferOutputStream:
        s = self._streams.get(pid)
        if s is None:
            s = ChunkedByteBufferOutputStream(
                self._conf.shuffle_write_chunk_size, recycled=self._recycled
            )
            self._streams[pid] = s
        return s

    def _flush(self, pid: int) -> None:
        """Compress the accumulated chunk data into the partition log."""
        s = self._streams.pop(pid, None)
        if s is None or s.length == 0:
            return
        cbb = s.to_chunked_byte_buffer()
        raw = b"".join(bytes(v) for v in cbb.get_chunks())
        # recycle chunk buffers for the next stream (:173-189)
        for buf, _ in cbb.take_buffers():
            self._recycled.append(buf)
        framed = frame_compressed(self._codec, raw)
        self._data.partition_writer(pid).append_frame(framed)
        self._lengths[pid] += len(framed)
        self._dirty = True
        reg = get_registry()
        role = self._manager.executor_id
        reg.counter("writer.partition_flushes", role=role).inc()
        reg.counter("writer.flush_bytes", role=role).inc(len(framed))

    def write(self, records) -> None:
        part = self._handle.partitioner.partition
        flush_size = self._conf.shuffle_write_flush_size
        if self._handle.aggregator is not None and self._handle.map_side_combine:
            records = combine_by_key(records, self._handle.aggregator).items()
        for rec in records:
            data = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            pid = part(rec[0])
            s = self._stream(pid)
            s.write(_LEN.pack(len(data)))
            s.write(data)
            if s.length >= flush_size:
                self._flush(pid)

    def stop(self, success: bool) -> Optional[MapStatus]:
        if self._stopped:
            return None
        self._stopped = True
        if success:
            for pid in list(self._streams.keys()):
                self._flush(pid)
        for s in self._streams.values():
            s.to_chunked_byte_buffer().dispose()
        self._streams.clear()
        for buf in self._recycled:
            buf.free()
        self._recycled.clear()
        if success:
            self._data.commit_map_output(self._manager)
            return MapStatus(self.map_id, self._lengths)
        self._data.abort_map_output(dirty=self._dirty)
        return None
