"""Growable chunked byte buffers over fixed-size scratch allocations.

Analogues of RdmaChunkedByteBuffer.scala and
RdmaChunkedByteBufferOutputStream.scala (reference: /root/reference/src/
main/scala/org/apache/spark/shuffle/rdma/writer/chunkedpartitionagg/).
An output stream grows by fixed-size **unregistered** chunks (:38-41),
supports chunk recycling across flushes (:28-32), and converts one-shot
into an immutable chunk list, freeing unused chunks (:81-100).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.obs import get_registry

_M_CHUNK_ALLOCS = get_registry().counter("writer.chunk_allocations")
_M_CHUNK_RECYCLES = get_registry().counter("writer.chunk_recycles")


class ChunkedByteBuffer:
    """Immutable view over (buffer, used_length) chunk list (:45)."""

    def __init__(self, chunks: List[Tuple[TpuBuffer, int]]):
        self._chunks = chunks

    @property
    def length(self) -> int:
        return sum(used for _, used in self._chunks)

    def get_chunks(self) -> List[memoryview]:
        return [buf.view[:used] for buf, used in self._chunks]

    def take_buffers(self) -> List[Tuple[TpuBuffer, int]]:
        """Hand off ownership of the underlying chunks (for recycling)."""
        chunks, self._chunks = self._chunks, []
        return chunks

    def dispose(self) -> None:
        for buf, _ in self._chunks:
            buf.free()
        self._chunks = []


class ChunkedByteBufferOutputStream:
    """OutputStream over a growable list of fixed-size scratch chunks."""

    def __init__(
        self,
        chunk_size: int,
        allocate: Optional[Callable[[int], TpuBuffer]] = None,
        recycled: Optional[List[TpuBuffer]] = None,
    ):
        self.chunk_size = chunk_size
        # chunk scratch is framework-owned (copied out at flush, freed or
        # recycled by the writer; no consumer view outlives it) — the
        # native C++ arena's unconditional free is safe here, and this is
        # the serialize-hot-path the reference used Unsafe.allocateMemory
        # for (RdmaBuffer.java:55-64)
        self._allocate = allocate or (
            lambda n: TpuBuffer(None, n, register=False, arena=True)
        )
        self._recycled = recycled or []
        self._chunks: List[TpuBuffer] = []
        self._pos_in_chunk = 0
        self._closed = False

    @property
    def length(self) -> int:
        if not self._chunks:
            return 0
        return (len(self._chunks) - 1) * self.chunk_size + self._pos_in_chunk

    def write(self, data) -> int:
        if self._closed:
            raise ValueError("stream closed")
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        written = 0
        while written < len(mv):
            if not self._chunks or self._pos_in_chunk == self.chunk_size:
                if self._recycled:
                    _M_CHUNK_RECYCLES.inc()
                    self._chunks.append(self._recycled.pop())
                else:
                    _M_CHUNK_ALLOCS.inc()
                    self._chunks.append(self._allocate(self.chunk_size))
                self._pos_in_chunk = 0
            chunk = self._chunks[-1]
            n = min(len(mv) - written, self.chunk_size - self._pos_in_chunk)
            chunk.view[self._pos_in_chunk : self._pos_in_chunk + n] = mv[
                written : written + n
            ]
            self._pos_in_chunk += n
            written += n
        return written

    def to_chunked_byte_buffer(self) -> ChunkedByteBuffer:
        """One-shot conversion; frees nothing here (all chunks are used)."""
        if self._closed:
            raise ValueError("already converted")
        self._closed = True
        out: List[Tuple[TpuBuffer, int]] = []
        for i, chunk in enumerate(self._chunks):
            used = self.chunk_size if i < len(self._chunks) - 1 else self._pos_in_chunk
            if used:
                out.append((chunk, used))
            else:
                chunk.free()
        self._chunks = []
        return ChunkedByteBuffer(out)
