"""Shuffle handles and the pluggable job-semantics interfaces.

Analogue of Spark's BaseShuffleHandle/SerializedShuffleHandle choice the
reference makes in registerShuffle (reference: RdmaShuffleManager.scala:
231-238) plus the dependency attributes (partitioner, serializer,
aggregator, ordering) the reader/writer paths consume
(RdmaShuffleReader.scala:69-112).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from sparkrdma_tpu.engine.serializer import PickleSerializer, Serializer


@dataclass
class Aggregator:
    """combineValuesByKey/combineCombinersByKey semantics.

    create_combiner(v) → c; merge_value(c, v) → c; merge_combiners(c1, c2) → c.
    """

    create_combiner: Callable
    merge_value: Callable
    merge_combiners: Callable


def combine_by_key(records, agg: "Aggregator", values_are_combiners: bool = False) -> dict:
    """The shared combineValuesByKey / combineCombinersByKey fold.

    Used by both writer methods (map-side combine) and the reader
    (reduce-side), keeping the symmetric contract in one place.
    """
    combined: dict = {}
    if values_are_combiners:
        for k, c in records:
            if k in combined:
                combined[k] = agg.merge_combiners(combined[k], c)
            else:
                combined[k] = c
    else:
        for k, v in records:
            if k in combined:
                combined[k] = agg.merge_value(combined[k], v)
            else:
                combined[k] = agg.create_combiner(v)
    return combined


class Partitioner:
    num_partitions: int

    def partition(self, key) -> int:  # pragma: no cover - interface
        raise NotImplementedError


class HashPartitioner(Partitioner):
    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def partition(self, key) -> int:
        return hash(key) % self.num_partitions


class RangePartitioner(Partitioner):
    """Sorted-output partitioner: keys ≤ bounds[i] go to partition i."""

    def __init__(self, bounds):
        self.bounds = list(bounds)
        self.num_partitions = len(self.bounds) + 1

    def partition(self, key) -> int:
        import bisect

        return bisect.bisect_left(self.bounds, key)


@dataclass
class BaseShuffleHandle:
    shuffle_id: int
    num_maps: int
    partitioner: Partitioner
    serializer: Serializer = field(default_factory=PickleSerializer)
    aggregator: Optional[Aggregator] = None
    map_side_combine: bool = False
    key_ordering: bool = False

    @property
    def num_partitions(self) -> int:
        return self.partitioner.num_partitions
