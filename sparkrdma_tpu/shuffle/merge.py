"""Push-based merge plane: map-side pushes into per-reducer merge buffers.

The reduce side of a shuffle classically pays M×R small random reads.
Magnet (LinkedIn, VLDB 2020) inverted the flow: the *map* side pushes
sealed blocks toward the executor expected to reduce them, where they
accumulate into one sequential *merged segment* per partition — reduce
reads become R sequential ones. This module is that plane for the TPU
framework, kept strictly **best-effort** behind the existing
resolver/locations API (DESIGN.md §18):

- :class:`PushClient` rides the map pipeline (chunked-agg writer,
  writer/chunked_agg.py): every time ``PartitionWriter.sealed_count()``
  advances, the freshly sealed blocks' payloads ship to the partition's
  destination executor — a direct call when the destination's
  :class:`MergeEndpoint` lives in this process (in-process contexts), a
  ``{"kind": "push_blocks"}`` task-protocol request otherwise
  (engine/worker.py). Map finalize pushes the remainder and a *final
  marker* carrying the per-partition block counts.
- :class:`MergeEndpoint` runs on every executor: pushed blocks dedup by
  ``(source, partition, seq)`` and buffer under a byte budget. A
  partition **seals** only under *complete coverage* — final markers
  from sources totalling the shuffle's full map count, and every block
  they enumerate present. The sealed segment (payload concatenation in
  (source, seq) order — frames never span writer blocks, so it is a
  valid frame stream) lands in registered memory, gets a publish-time
  checksum, and registers with the driver as a merged location
  (``BlockLocation.merged_cover`` = originals covered, riding the
  0xFFFD wire extension, rpc.py).
- :func:`plan_reads` is the reduce planner's *merged-else-original*
  rule (fetcher.py / device_io.py): a merged location substitutes for
  ALL the partition's originals only when ``merged_cover`` equals
  their count; the originals stay attached as the fallback, so a
  dropped push, an over-budget buffer, or a corrupted merged segment
  (caught by the ordinary checksum gate) silently degrades to the
  original per-map reads — never duplicated, never lost.
"""

from __future__ import annotations

import logging
import re
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.obs import SpanHandle, get_registry
from sparkrdma_tpu.shuffle.writer.blocks import MemoryWriterBlock
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.utils import checksum as _checksum

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")


def _natural(executor_id: str):
    """Sort key treating digit runs numerically (exec-10 after exec-2)."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", executor_id)]


# ----------------------------------------------------------------------
# process-local endpoint registry (the device_fetch arena-registry idiom):
# in-process clusters push by direct call; keyed by (driver_port,
# executor_id) so two live contexts in one process never cross wires.
# ----------------------------------------------------------------------
_endpoints: Dict[Tuple[int, str], "MergeEndpoint"] = {}
_endpoints_lock = threading.Lock()


def register_endpoint(ep: "MergeEndpoint") -> None:
    with _endpoints_lock:
        _endpoints[ep.key] = ep


def unregister_endpoint(ep: "MergeEndpoint") -> None:
    with _endpoints_lock:
        if _endpoints.get(ep.key) is ep:
            del _endpoints[ep.key]


def endpoint_for(driver_port: int, executor_id: str) -> Optional["MergeEndpoint"]:
    with _endpoints_lock:
        return _endpoints.get((driver_port, executor_id))


# ----------------------------------------------------------------------
# merged-else-original read planning (the reduce side's ONE new rule)
# ----------------------------------------------------------------------
def plan_reads(
    locations: Sequence[PartitionLocation],
) -> Tuple[List[PartitionLocation], Dict[int, List[PartitionLocation]]]:
    """Select, per partition, the merged segment OR the originals.

    Returns ``(selected, fallbacks)``: ``selected`` replaces the input
    for fetch planning; ``fallbacks[pid]`` holds the suppressed
    original locations of every partition whose merged segment was
    chosen (the read path re-issues them if the merged read fails).
    A merged location is chosen only when its ``merged_cover`` equals
    the partition's original-location count — anything else (partial
    coverage, duplicate publish, foreign writer in the mix) keeps the
    originals authoritative and drops the merged candidate.
    """
    originals: Dict[int, List[PartitionLocation]] = {}
    merged: Dict[int, List[PartitionLocation]] = {}
    for loc in locations:
        bucket = merged if loc.block.merged_cover else originals
        bucket.setdefault(loc.partition_id, []).append(loc)
    if not merged:
        return list(locations), {}
    selected: List[PartitionLocation] = []
    fallbacks: Dict[int, List[PartitionLocation]] = {}
    for pid in sorted(set(originals) | set(merged)):
        origs = originals.get(pid, [])
        chosen = next(
            (
                m
                for m in merged.get(pid, ())
                if origs and m.block.merged_cover == len(origs)
            ),
            None,
        )
        if chosen is not None:
            selected.append(chosen)
            fallbacks[pid] = origs
        else:
            selected.extend(origs)
    return selected, fallbacks


class _ShuffleMergeState:
    """One shuffle's accumulation on one endpoint."""

    __slots__ = ("blocks", "markers", "sealed", "abandoned", "push_origins")

    def __init__(self):
        # pid -> (source, seq) -> payload bytes
        self.blocks: Dict[int, Dict[Tuple[str, int], bytes]] = {}
        # source -> (counts: pid -> total blocks, committed maps, num_maps)
        self.markers: Dict[str, Tuple[Dict[int, int], int, int]] = {}
        # pid -> registered segment block (None while sealing)
        self.sealed: Dict[int, Optional[MemoryWriterBlock]] = {}
        self.abandoned: Set[int] = set()
        # source -> handle of the map-side push span (obs/trace.py):
        # seal spans causally follow every contributing source's push
        self.push_origins: Dict[str, SpanHandle] = {}


class MergeEndpoint:
    """Per-executor receiver of pushed blocks; seals merged segments."""

    def __init__(self, manager):
        self._manager = manager
        self.key = (manager.conf.driver_port, manager.executor_id)
        self._budget = manager.conf.push_max_buffer_bytes
        self._buffered = 0
        self._shuffles: Dict[int, _ShuffleMergeState] = {}
        # named (PR 12): the endpoint's ingest/seal critical sections are
        # schedule-point seams for the protocol model checker, and the
        # lock-order detector tracks it against manager.state
        self._lock = named_lock("push.endpoint")
        self._stopped = False
        role = manager.executor_id
        reg = get_registry()
        self._m_segments = reg.counter("push.merge_segments", role=role)
        self._m_merged_bytes = reg.counter("push.merged_bytes", role=role)
        self._m_dedup = reg.counter("push.dedup_drops", role=role)
        self._m_budget_drops = reg.counter("push.budget_drops", role=role)

    # -- ingest ---------------------------------------------------------
    def push_blocks(
        self,
        shuffle_id: int,
        source: str,
        blocks: Sequence[Tuple[int, int, bytes]],
        final: Optional[dict] = None,
        origin_span: int = 0,
        origin_trace: int = 0,
    ) -> int:
        """Accept pushed ``(pid, seq, payload)`` blocks from ``source``.

        ``final`` (the source's finalize marker) carries
        ``{"counts": {pid: total}, "committed": n, "num_maps": m}``;
        seal checks run once markers account for every map output.
        ``origin_span``/``origin_trace`` identify the sender's
        ``shuffle.push`` span (the push→merge-seal causal seam,
        obs/trace.py); 0 for legacy or untraced senders. Returns the
        number of newly buffered blocks (dedup/budget drops
        excluded) — purely informational, pushes are fire-and-forget.
        """
        accepted = 0
        schedule_point("proto", "merge.push")
        to_seal: List[Tuple[int, List[Tuple[str, int]], Dict]] = []
        with self._lock:
            if self._stopped:
                return 0
            st = self._shuffles.setdefault(shuffle_id, _ShuffleMergeState())
            if origin_span:
                st.push_origins[source] = SpanHandle(origin_trace, origin_span)
            for pid, seq, payload in blocks or ():
                if self._closed_locked(st, pid):
                    self._m_dedup.inc()
                    continue
                per = st.blocks.setdefault(pid, {})
                if self._dup_locked(per, source, seq):
                    self._m_dedup.inc()
                    continue
                n = len(payload)
                if self._buffered + n > self._budget:
                    # over budget: this partition falls back to its
                    # original locations; free what it buffered so far
                    self._abandon_locked(st, pid)
                    self._m_budget_drops.inc()
                    continue
                per[(source, seq)] = bytes(payload)
                self._buffered += n
                accepted += 1
            if final is not None:
                st.markers[source] = (
                    {int(p): int(n) for p, n in (final.get("counts") or {}).items()},
                    int(final.get("committed", 0)),
                    int(final.get("num_maps", 0)),
                )
            if st.markers:
                to_seal = self._sealable_locked(st)
            origins = list(st.push_origins.values()) if to_seal else []
        for pid, need, payloads in to_seal:
            self._seal(shuffle_id, pid, need, payloads, origins)
        return accepted

    def _closed_locked(self, st: _ShuffleMergeState, pid: int) -> bool:
        """Sealed/abandoned pids accept no further blocks: no buffer
        re-entry after a seal popped the payloads, no ledger churn after
        an abandon freed them. Named predicates (this and
        :meth:`_dup_locked`) so the modelcheck mutation gate can disarm
        exactly one guard at a time."""
        return pid in st.sealed or pid in st.abandoned

    @staticmethod
    def _dup_locked(per: Dict[Tuple[str, int], bytes], source: str, seq: int) -> bool:
        """Redelivery dedup: pushes are fire-and-forget and the task
        protocol may retry, so ``(source, seq)`` must be idempotent."""
        return (source, seq) in per

    def _abandon_locked(self, st: _ShuffleMergeState, pid: int) -> None:
        per = st.blocks.pop(pid, None)
        if per:
            self._buffered -= sum(len(v) for v in per.values())
        st.abandoned.add(pid)

    def _sealable_locked(
        self, st: _ShuffleMergeState
    ) -> List[Tuple[int, List[Tuple[str, int]], Dict]]:
        """Complete-coverage check: a pid seals only when final markers
        account for EVERY map output of the shuffle and every block
        they enumerate for the pid arrived here. Any dropped push or
        divergent routing (sources disagreeing on the destination)
        leaves at least one block missing — no seal, originals win."""
        num_maps = max((nm for (_, _, nm) in st.markers.values()), default=0)
        committed = sum(c for (_, c, _) in st.markers.values())
        if num_maps <= 0 or committed < num_maps:
            return []
        out = []
        all_pids: Set[int] = set()
        for counts, _, _ in st.markers.values():
            all_pids.update(p for p, n in counts.items() if n)
        for pid in sorted(all_pids):
            if pid in st.sealed or pid in st.abandoned:
                continue
            need = [
                (src, seq)
                for src, (counts, _, _) in sorted(st.markers.items())
                for seq in range(counts.get(pid, 0))
            ]
            have = st.blocks.get(pid, {})
            if not need or not all(k in have for k in need):
                continue
            payloads = st.blocks.pop(pid)
            self._buffered -= sum(len(v) for v in payloads.values())
            st.sealed[pid] = None  # sealing placeholder: no late re-entry
            need.sort(key=lambda k: (_natural(k[0]), k[1]))
            out.append((pid, need, payloads))
        return out

    def _seal(
        self,
        shuffle_id: int,
        pid: int,
        need: List[Tuple[str, int]],
        payloads: Dict[Tuple[str, int], bytes],
        origins: Optional[List[SpanHandle]] = None,
    ) -> None:
        """Concatenate coverage into one registered segment + publish."""
        schedule_point("proto", "merge.seal")
        t_seal0 = time.perf_counter()
        manager = self._manager
        total = sum(len(payloads[k]) for k in need)
        admitted = total > 0 and manager.resolver.reserve_inmemory_bytes(total)
        if not admitted:
            with self._lock:
                st = self._shuffles.get(shuffle_id)
                if st is not None:
                    st.sealed.pop(pid, None)
                    st.abandoned.add(pid)
            self._m_budget_drops.inc()
            return
        try:
            manager.start_node_if_missing()
            block = MemoryWriterBlock(manager.node.pd, total)
            block.reserved_bytes = total
            for k in need:
                block.append(payloads[k])
            mkey = block.location().mkey
            view = manager.node.pd.resolve(mkey, 0, total)
            algo, crc = _checksum.compute(view)
            plan = _faults.active()
            if plan is not None:
                # the push:corrupt seam: flip a byte AFTER the checksum
                # tag is computed, so the reduce path's ordinary gate
                # must detect it and fall back to the originals
                plan.on_push("seal", [view], peer=manager.executor_id)
        except Exception:
            logger.exception("sealing merged segment for pid %d failed", pid)
            manager.resolver.release_inmemory_bytes(total)
            with self._lock:
                st = self._shuffles.get(shuffle_id)
                if st is not None:
                    st.sealed.pop(pid, None)
                    st.abandoned.add(pid)
            return
        keep = False
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is not None and not self._stopped:
                st.sealed[pid] = block
                keep = True
        if not keep:
            block.dispose()
            manager.resolver.release_inmemory_bytes(total)
            return
        self._m_segments.inc()
        self._m_merged_bytes.inc(total)
        loc = PartitionLocation(
            manager.local_manager_id,
            pid,
            BlockLocation(
                0,
                total,
                mkey,
                checksum=crc,
                checksum_algo=algo,
                merged_cover=len(need),
            ),
        )
        # the seal span causally follows every contributing source's
        # push span (push→merge-seal seam, obs/trace.py flow events);
        # manager is duck-typed (modelcheck sinks carry no tracer)
        tracer = getattr(manager, "tracer", None)
        if tracer is not None:
            tracer.record(
                "shuffle.merge_seal",
                t_seal0,
                time.perf_counter(),
                shuffle_id=shuffle_id,
                follows=origins,
                pid=pid,
                bytes=total,
                cover=len(need),
            )
        # location-only publish: merged segments never touch the
        # map-output barrier; they only ADD a location class
        manager.publish_partition_locations(shuffle_id, -1, [loc], num_map_outputs=0)

    # -- lifecycle ------------------------------------------------------
    def drop_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            st = self._shuffles.pop(shuffle_id, None)
            if st is None:
                return
            for per in st.blocks.values():
                self._buffered -= sum(len(v) for v in per.values())
            blocks = [b for b in st.sealed.values() if b is not None]
        for b in blocks:
            reserved = getattr(b, "reserved_bytes", 0)
            b.dispose()
            if reserved:
                self._manager.resolver.release_inmemory_bytes(reserved)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            shuffle_ids = list(self._shuffles)
        for sid in shuffle_ids:
            self.drop_shuffle(sid)


class PushClient:
    """Map-side push sender: routes sealed blocks to their reducer's
    executor, in-process (endpoint registry) or over the engine task
    protocol (routes shipped by the driver in ``map_batch``)."""

    def __init__(self, manager):
        self._manager = manager
        self.routes: Dict[str, Tuple[str, int]] = {}
        role = manager.executor_id
        reg = get_registry()
        self._m_pushed_blocks = reg.counter("push.pushed_blocks", role=role)
        self._m_pushed_bytes = reg.counter("push.pushed_bytes", role=role)
        self._m_dropped = reg.counter("push.dropped", role=role)
        self._m_skipped = reg.counter("push.skipped", role=role)
        self._m_errors = reg.counter("push.send_errors", role=role)

    def set_routes(self, routes: Optional[Dict[str, Tuple[str, int]]]) -> None:
        self.routes = {k: tuple(v) for k, v in (routes or {}).items()}

    def _candidates(self) -> List[str]:
        if self.routes:
            ids = set(self.routes) | {self._manager.executor_id}
        else:
            ids = set(self._manager.known_executor_ids())
        return sorted(ids, key=_natural)

    @staticmethod
    def route_for(pid: int, num_partitions: int, candidates: Sequence[str]) -> str:
        """Contiguous-range routing: matches the engine's default
        contiguous reduce assignment so the merged segment usually
        seals on the executor that reads it. Purely a locality
        heuristic — a mismatch still yields ONE sequential (remote)
        merged read."""
        k = len(candidates)
        return candidates[min(k - 1, pid * k // max(1, num_partitions))]

    def push_window(
        self,
        shuffle_id: int,
        blocks: Sequence[Tuple[int, int, bytes]],
        num_partitions: int,
        final: Optional[dict] = None,
    ) -> None:
        """Ship ``(pid, seq, payload)`` blocks toward their reducers;
        a ``final`` marker additionally goes to EVERY candidate so
        endpoints can complete their coverage accounting."""
        cands = self._candidates()
        if not cands:
            if blocks:
                self._m_skipped.inc(len(blocks))
            return
        by_dest: Dict[str, List[Tuple[int, int, bytes]]] = {}
        for item in blocks or ():
            dest = self.route_for(item[0], num_partitions, cands)
            by_dest.setdefault(dest, []).append(item)
        dests = set(by_dest)
        if final is not None:
            dests.update(cands)
        with self._manager.tracer.span(
            "shuffle.push",
            shuffle_id=shuffle_id,
            blocks=len(blocks or ()),
            final=final is not None,
        ) as sp:
            for dest in sorted(dests, key=_natural):
                self._send(
                    dest,
                    {
                        "shuffle_id": shuffle_id,
                        "source": self._manager.executor_id,
                        "blocks": by_dest.get(dest, []),
                        "final": final,
                        # push→merge-seal causal seam (obs/trace.py):
                        # the endpoint's seal span follows this span
                        "origin_span": sp.span_id if sp is not None else 0,
                        "origin_trace": sp.trace_id if sp is not None else 0,
                    },
                )

    def _send(self, dest: str, payload: dict) -> None:
        blocks = payload["blocks"]
        plan = _faults.active()
        if plan is not None and plan.on_push("send", None, peer=dest):
            # injected loss: the message silently never arrives — the
            # destination's coverage stays incomplete, originals win
            self._m_dropped.inc(max(1, len(blocks)))
            return
        ep = endpoint_for(self._manager.conf.driver_port, dest)
        try:
            if ep is not None:
                ep.push_blocks(
                    payload["shuffle_id"],
                    payload["source"],
                    blocks,
                    payload["final"],
                    payload.get("origin_span", 0),
                    payload.get("origin_trace", 0),
                )
            elif dest in self.routes:
                self._send_staged(self.routes[dest], payload)
            else:
                self._m_skipped.inc(max(1, len(blocks)))
                return
        except Exception:
            # best-effort by contract: a failed push is a silent miss
            logger.debug("push to %s failed", dest, exc_info=True)
            self._m_errors.inc()
            return
        if blocks:
            self._m_pushed_blocks.inc(len(blocks))
            self._m_pushed_bytes.inc(sum(len(p) for _, _, p in blocks))

    def _send_staged(self, addr: Tuple[str, int], payload: dict) -> None:
        """Cluster-mode send: block BYTES ride the data plane.

        The payloads are registered in this node's ProtectionDomain and
        only ``(pid, seq, mkey, length)`` descriptors travel the task
        protocol; the receiving worker pulls the bytes with a one-sided
        READ before merging (transport/staging.py). The synchronous
        task reply doubles as the release signal for the
        registrations."""
        from sparkrdma_tpu.transport.staging import stage_payloads

        node = self._manager.node
        blocks = list(payload.get("blocks") or ())
        if node is None or not blocks:
            # no data plane up (or a pure `final` marker): the inline
            # path is already control-plane sized
            self._send_socket(addr, payload)
            return
        data_addr, descs, release = stage_payloads(
            node, [p for _, _, p in blocks]
        )
        try:
            self._send_socket(addr, dict(
                payload,
                blocks=[],
                blocks_rd=[
                    (pid, seq, mkey, length)
                    for (pid, seq, _), (mkey, length) in zip(blocks, descs)
                ],
                data_addr=data_addr,
            ))
        finally:
            release()

    @staticmethod
    def _send_socket(addr: Tuple[str, int], payload: dict) -> None:
        import cloudpickle

        data = cloudpickle.dumps(dict(payload, kind="push_blocks"))
        with socket.create_connection(addr, timeout=10.0) as s:
            s.settimeout(10.0)
            s.sendall(_LEN.pack(len(data)) + data)
            # wait for the reply: the endpoint seals (and SENDS its
            # merged publish) before answering, so a finalize that
            # pushed synchronously precedes the barrier-completing
            # location publish — merged locations beat fetch replies
            hdr = b""
            while len(hdr) < 4:
                chunk = s.recv(4 - len(hdr))
                if not chunk:
                    raise ConnectionError("push peer closed")
                hdr += chunk
            (n,) = _LEN.unpack(hdr)
            got = 0
            while got < n:
                chunk = s.recv(min(1 << 20, n - got))
                if not chunk:
                    raise ConnectionError("push peer closed")
                got += len(chunk)
