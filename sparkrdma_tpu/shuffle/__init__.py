from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

__all__ = ["BaseShuffleHandle", "TpuShuffleManager"]
