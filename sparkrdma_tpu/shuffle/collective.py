"""Whole-stage collective shuffle — the shuffle-schedule compiler.

The device fetch plane (DESIGN.md §17) moves one block per planner
decision: pin, pull, adopt, repeat. This module treats a reduce
stage's ENTIRE published location set as one object to compile: every
device-resident block (0xFFFE extension coordinates) is grouped into
batched DMA *waves* — fixed-shape [rows, bucket] stacks moved in one
mover dispatch — over a ring or all-to-all schedule, with compile-once
programs cached by (rows-class, bucket-class, dtype) exactly like the
exchange executable cache (DESIGN.md §22).

Movers, by regime:

- TPU mesh: ``ops/remote_copy.pallas_wave_pull`` — one Pallas kernel
  epoch issuing ``rows`` ``make_async_remote_copy`` DMAs together
  (start all, wait all), per-row source device ids in a
  scalar-prefetch lane so one executable serves any peer set.
- Everywhere else (and on any TPU-side surprise): an assembled host
  stack lands on the destination in ONE transfer-engine dispatch
  (``emulated_wave_pull``) — still one dispatch + one sync per wave
  instead of per block, which is why the compiled schedule beats the
  per-block pull loop even on the CPU mesh.

Fusion: a partition whose every block rides in one wave can merge in
the same epoch — a cached compaction program gathers the wave's valid
prefixes into one contiguous slab, so the partition lands as ONE
merged device buffer (concatenated in deterministic source order,
composing with the merged-cover contract of shuffle/merge.py) with no
intermediate HBM round trip. Fusion changes the result SHAPE (one
buffer per partition), so callers opt in per fetch.

Degrade ladder (every rung silent, byte-identical):

| condition                                   | outcome             |
|---------------------------------------------|---------------------|
| ``collective.enabled`` off                   | per-block planner   |
| < ``collective.minBlocks`` device blocks     | per-block planner   |
| block fails eligibility (size/dtype/arena)   | per-block planner   |
| slab evicted/spilled between plan and pin    | host triple, degrade++ |
| wave mover fails                             | host triple, degrade++ |
"""

from __future__ import annotations

import functools
import logging
import time
from contextlib import ExitStack
from typing import Dict, List, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.ops import remote_copy
from sparkrdma_tpu.ops.exchange import round_bucket, round_rows
from sparkrdma_tpu.ops.hbm_arena import DeviceBuffer, DeviceBufferManager
from sparkrdma_tpu.shuffle.device_fetch import visible_arena

logger = logging.getLogger(__name__)


def merge_order_key(loc: PartitionLocation) -> Tuple:
    """Deterministic within-partition merge order — the order fused
    slabs concatenate in, and the order tests/benches sort per-block
    results into when comparing against a fused result."""
    return (
        loc.manager_id.executor_id,
        loc.block.mkey,
        loc.block.address,
        loc.block.arena_handle,
    )


@functools.lru_cache(maxsize=64)
def _compaction_program(rows_b: int, bucket_elems: int, dtype_str: str):
    """Jitted fetch->merge compaction: gather every row's valid prefix
    of a landed [rows_b, bucket_elems] wave into one contiguous flat
    lane — the merge half of the fused epoch. Pure gather math (no
    dynamic shapes): position j belongs to the row whose element span
    covers it, looked up against the inclusive end-offsets lane. On
    TPU, XLA keeps the gather in the same HBM residency as the landed
    wave — fetch to merged slab with no host round trip.

    Cached per (rows class, bucket class, dtype); rows and buckets are
    both power-of-two bucketed upstream, so ragged stages reuse these
    executables."""
    import jax
    import jax.numpy as jnp

    jnp.dtype(dtype_str)  # validate the cache key up front
    total = rows_b * bucket_elems

    def fn(stacked, starts, ends):
        j = jnp.arange(total, dtype=jnp.int32)
        row = jnp.searchsorted(ends, j, side="right")
        row = jnp.minimum(row, rows_b - 1)
        col = jnp.clip(j - starts[row], 0, bucket_elems - 1)
        return stacked[row, col]

    return jax.jit(fn)


class _Row:
    """One device-resident block scheduled into a wave."""

    __slots__ = ("loc", "elems", "live")

    def __init__(self, loc: PartitionLocation, elems: int):
        self.loc = loc
        self.elems = elems
        self.live = True


class CollectiveWave:
    """One batched mover dispatch: ``rows`` blocks of one bucket class."""

    __slots__ = ("rows", "bucket_elems", "rows_b", "lane")

    def __init__(self, rows: List[_Row], bucket_elems: int, lane: str):
        self.rows = rows
        self.bucket_elems = bucket_elems
        self.rows_b = round_rows(len(rows))
        self.lane = lane  # primary source executor (ring ordering key)


class CollectivePlan:
    """A compiled reduce-stage fetch schedule.

    ``passthrough`` locations never entered the schedule (collective
    off, too few device blocks, or per-block ineligibility) — the
    caller runs them through the pre-existing per-block loop, which
    preserves exactly the old behavior when the compiler declines."""

    __slots__ = ("schedule", "waves", "passthrough", "fusable_pids",
                 "device_blocks")

    def __init__(self, schedule: str, waves: List[CollectiveWave],
                 passthrough: List[PartitionLocation],
                 fusable_pids: frozenset, device_blocks: int):
        self.schedule = schedule
        self.waves = waves
        self.passthrough = passthrough
        self.fusable_pids = fusable_pids
        self.device_blocks = device_blocks


class CollectiveResult:
    """One landed slab: a single block, or a fused per-partition merge
    (``fused`` — ``locs`` then lists every covered block in merge
    order and ``dev.length`` is their summed payload)."""

    __slots__ = ("pid", "dev", "locs", "fused")

    def __init__(self, pid: int, dev: DeviceBuffer,
                 locs: List[PartitionLocation], fused: bool):
        self.pid = pid
        self.dev = dev
        self.locs = locs
        self.fused = fused


class ShuffleScheduleCompiler:
    """Compile + execute whole-stage device fetch schedules."""

    def __init__(self, conf, dev: DeviceBufferManager, executor_id: str,
                 tracer=None):
        self._conf = conf
        self._dev = dev
        self._executor_id = executor_id
        self._tracer = tracer
        # program-cache bookkeeping (the lru_caches hold the programs;
        # this counts resolutions for the compile-churn metrics)
        self._seen_programs: set = set()
        self._cache_lock = named_lock("collective.compiler")
        reg = get_registry()
        role = executor_id
        self._m_plans = reg.counter("collective.plans", role=role)
        self._m_blocks = reg.counter("collective.blocks", role=role)
        self._m_bytes = reg.counter("collective.bytes", role=role)
        self._m_fused = reg.counter("collective.fused_merges", role=role)
        self._m_degrades = reg.counter("collective.degrades", role=role)
        self._m_compiles = reg.counter("collective.compiles", role=role)
        self._m_cache_hits = reg.counter("collective.cache_hits", role=role)
        self._m_plan_ms = reg.histogram("collective.plan_ms", role=role)
        # the device-fetch plane's counters stay the one source of truth
        # for "blocks that moved HBM->HBM" vs "device offers declined":
        # a landed wave row IS a device pull, a degraded row IS a
        # fallback. collective.* adds the schedule-level detail on top.
        self._m_plane_pulls = reg.counter(
            "device_fetch.plane.pulls", role=role
        )
        self._m_plane_bytes = reg.counter(
            "device_fetch.plane.bytes", role=role
        )
        self._m_plane_fallbacks = reg.counter(
            "device_fetch.plane.fallbacks", role=role
        )

    # ------------------------------------------------------------------
    # plan
    # ------------------------------------------------------------------
    def plan(self, locations: Sequence[PartitionLocation],
             dtype=np.uint8) -> CollectivePlan:
        """Compile the stage's location set into a wave schedule.

        Eligibility here mirrors the per-block planner's static checks
        (device extension present, above minBlockBytes, source arena
        mesh-visible) plus an elem-alignment check the stacked layout
        needs; residency/dtype are re-checked under the pin at execute
        time, where a miss degrades to the host triple."""
        t0 = time.perf_counter()
        conf = self._conf
        itemsize = np.dtype(dtype).itemsize
        if not conf.collective_enabled or not conf.device_fetch_enabled:
            return CollectivePlan("off", [], list(locations), frozenset(), 0)
        min_bytes = conf.device_fetch_min_block_bytes
        eligible: List[PartitionLocation] = []
        passthrough: List[PartitionLocation] = []
        per_pid_total: Dict[int, int] = {}
        for loc in locations:
            per_pid_total[loc.partition_id] = (
                per_pid_total.get(loc.partition_id, 0) + 1
            )
            b = loc.block
            if (
                b.has_device
                and b.length >= min_bytes
                and b.length % itemsize == 0
                and b.arena_offset % itemsize == 0
                and visible_arena(loc.manager_id.executor_id) is not None
            ):
                eligible.append(loc)
            else:
                passthrough.append(loc)
        if len(eligible) < conf.collective_min_blocks:
            # too small a stage for a wave: the per-block planner keeps
            # the whole set (it may still pull the stragglers one by one)
            return CollectivePlan(
                "off", [], list(locations), frozenset(), 0
            )

        # merge order: partition-major so a fused pid's rows are
        # contiguous, source-ordered within the partition
        eligible.sort(key=lambda loc: (loc.partition_id, merge_order_key(loc)))
        per_pid_eligible: Dict[int, int] = {}
        for loc in eligible:
            per_pid_eligible[loc.partition_id] = (
                per_pid_eligible.get(loc.partition_id, 0) + 1
            )

        lanes = sorted({loc.manager_id.executor_id for loc in eligible})
        schedule = conf.collective_schedule
        if schedule == "auto":
            schedule = "a2a" if len(lanes) > 2 else "ring"

        # wave formation: pid-group granularity (fusion needs a pid's
        # rows in ONE wave), split only when a single pid alone
        # overflows the wave budget (that pid becomes unfusable)
        wave_budget = conf.collective_wave_bytes
        waves: List[CollectiveWave] = []
        fusable: set = set()
        cur_rows: List[_Row] = []
        cur_max_len = 0

        def seal():
            nonlocal cur_rows, cur_max_len
            if cur_rows:
                bucket = round_bucket(cur_max_len)
                waves.append(CollectiveWave(
                    cur_rows, bucket // itemsize,
                    cur_rows[0].loc.manager_id.executor_id,
                ))
                cur_rows, cur_max_len = [], 0

        i = 0
        n = len(eligible)
        while i < n:
            pid = eligible[i].partition_id
            j = i
            group_bytes = 0
            group_max = 0
            while j < n and eligible[j].partition_id == pid:
                group_bytes += round_bucket(eligible[j].block.length)
                group_max = max(group_max, eligible[j].block.length)
                j += 1
            group = eligible[i:j]
            if group_bytes > wave_budget and len(group) > 1:
                # oversized pid: seal what we have, stream the pid
                # through dedicated waves, leave it unfusable
                seal()
                for loc in group:
                    cur_rows.append(_Row(loc, loc.block.length // itemsize))
                    cur_max_len = max(cur_max_len, loc.block.length)
                    if sum(round_bucket(r.loc.block.length)
                           for r in cur_rows) >= wave_budget:
                        seal()
                seal()
            else:
                cur_bytes = sum(
                    round_bucket(r.loc.block.length) for r in cur_rows
                )
                if cur_rows and cur_bytes + group_bytes > wave_budget:
                    seal()
                for loc in group:
                    cur_rows.append(_Row(loc, loc.block.length // itemsize))
                cur_max_len = max(cur_max_len, group_max)
                # fusable iff every one of the pid's published blocks
                # made it into the schedule (full device cover, the
                # merged-cover rule of shuffle/merge.py) and they share
                # this wave
                if per_pid_eligible[pid] == per_pid_total[pid]:
                    fusable.add(pid)
            i = j
        seal()

        if schedule == "ring":
            # lane-major wave order: one source lane in flight at a
            # time, walking the ring — the flow-controlled schedule
            waves.sort(key=lambda w: lanes.index(w.lane))
        self._m_plan_ms.observe((time.perf_counter() - t0) * 1e3)
        return CollectivePlan(
            schedule, waves, passthrough, frozenset(fusable), len(eligible)
        )

    # ------------------------------------------------------------------
    # execute
    # ------------------------------------------------------------------
    def execute(
        self,
        shuffle_id: int,
        plan: CollectivePlan,
        dtype=np.uint8,
        fused: bool = False,
    ) -> Tuple[List[CollectiveResult], List[PartitionLocation]]:
        """Run the compiled schedule; returns ``(results, degraded)``.

        ``degraded`` lists every scheduled block that missed (evicted
        mid-stage, stale coordinates, mover failure) — the caller host-
        fetches them; with fusion on, a miss also unfuses its partition
        (the survivors land per block, the host fills the gap), so the
        byte content of the stage is identical on every path."""
        if not plan.waves:
            return [], []
        fused = bool(fused) and self._conf.collective_fused_merge
        self._schedule_label = plan.schedule
        reg = get_registry()
        results: List[CollectiveResult] = []
        degraded: List[PartitionLocation] = []
        self._m_plans.inc()
        span = (
            self._tracer.span(
                "shuffle.collective", shuffle_id=shuffle_id,
                schedule=plan.schedule, waves=len(plan.waves),
                blocks=plan.device_blocks,
            )
            if self._tracer is not None
            else None
        )
        ctx = span if span is not None else _null_ctx()
        with ctx:
            # pids that lose a row to degradation must not fuse: the
            # host path refills per block, so survivors stay per block
            unfusable: set = set()
            landed: List[Tuple[CollectiveWave, object, List[int], object]] = []
            for wave in plan.waves:
                out = self._run_wave(shuffle_id, wave, dtype, reg)
                if out is None:
                    # whole-wave mover failure: every row degrades
                    for row in wave.rows:
                        degraded.append(row.loc)
                        unfusable.add(row.loc.partition_id)
                    self._m_degrades.inc(len(wave.rows))
                    self._m_plane_fallbacks.inc(len(wave.rows))
                    continue
                stacked_dev, dead, stacked_host = out
                for i in dead:
                    degraded.append(wave.rows[i].loc)
                    unfusable.add(wave.rows[i].loc.partition_id)
                if dead:
                    self._m_degrades.inc(len(dead))
                    self._m_plane_fallbacks.inc(len(dead))
                landed.append((wave, stacked_dev, dead, stacked_host))

            for wave, stacked_dev, dead, stacked_host in landed:
                results.extend(self._adopt_wave(
                    wave, stacked_dev, dtype,
                    fused, plan.fusable_pids - unfusable,
                    stacked_host=stacked_host,
                ))
        return results, degraded

    # ------------------------------------------------------------------
    def _program_key_seen(self, key) -> None:
        with self._cache_lock:
            if key in self._seen_programs:
                self._m_cache_hits.inc()
            else:
                self._seen_programs.add(key)
                self._m_compiles.inc()

    def _run_wave(self, shuffle_id, wave: CollectiveWave, dtype, reg):
        """Pin, assemble, and move one wave. Returns ``(stacked_dev,
        dead_row_indices, stacked_host)`` or None on a whole-wave
        mover failure; ``stacked_host`` is the host-side assembly the
        emulated mover staged from (adoption compacts it with plain
        numpy instead of the device gather when off TPU)."""
        t0 = time.perf_counter()
        itemsize = np.dtype(dtype).itemsize
        rows_b = wave.rows_b
        b_elems = wave.bucket_elems
        stacked = np.zeros((rows_b, b_elems), dtype=dtype)
        dead: List[int] = []
        try:
            with ExitStack() as pins:
                for i, row in enumerate(wave.rows):
                    blk = row.loc.block
                    arena = visible_arena(row.loc.manager_id.executor_id)
                    src = None
                    if arena is not None:
                        src = pins.enter_context(
                            arena.pinned_if_resident(blk.arena_handle)
                        )
                    if (
                        src is None
                        or blk.arena_offset + blk.length > src.capacity
                        or np.dtype(src.array.dtype) != np.dtype(dtype)
                    ):
                        row.live = False
                        dead.append(i)
                        continue
                    # the emulated gather: source HBM -> host lane of
                    # the assembled stack (the TPU path skips this and
                    # DMAs source-side shards directly)
                    host = np.asarray(src.array).view(dtype)
                    off = blk.arena_offset // itemsize
                    stacked[i, : row.elems] = host[off : off + row.elems]
            if len(dead) == len(wave.rows):
                # every row died at the pin: nothing to move; the
                # caller degrades them all (tuple keeps the uniform
                # "landed" return shape, distinct from mover failure)
                return None, dead, None
            key = ("wave", rows_b, b_elems, np.dtype(dtype).name)
            self._program_key_seen(key)
            stacked_dev = None
            if remote_copy.is_tpu_mesh():
                # batched-DMA kernel epoch: one compiled program per
                # (rows class, bucket class, dtype), per-row source ids
                # in the scalar-prefetch lane. Any bring-up surprise
                # degrades to the transfer engine below — same bytes.
                try:
                    stacked_dev = self._pallas_wave(wave, stacked)
                except Exception:
                    logger.exception(
                        "pallas wave mover failed; using transfer engine"
                    )
            if stacked_dev is None:
                stacked_dev = remote_copy.emulated_wave_pull(
                    stacked, self._dev.device
                )
        except Exception:
            logger.exception("collective wave failed; degrading to host")
            return None
        live = len(wave.rows) - len(dead)
        nbytes = sum(r.elems * itemsize for r in wave.rows if r.live)
        self._m_blocks.inc(live)
        self._m_bytes.inc(nbytes)
        self._m_plane_pulls.inc(live)
        self._m_plane_bytes.inc(nbytes)
        reg.counter(
            "collective.waves", role=self._executor_id,
            schedule=self._schedule_label,
        ).inc()
        reg.histogram(
            "collective.wave_ms", role=self._executor_id,
            schedule=self._schedule_label,
        ).observe((time.perf_counter() - t0) * 1e3)
        if self._tracer is not None:
            # per-wave span (dma-wave attribution, obs/attr.py): nests
            # under execute()'s shuffle.collective span via the
            # contextvar parent, so the critical path can enter the
            # wave level instead of one opaque multi-wave slice
            self._tracer.record(
                "shuffle.collective.wave",
                t0,
                time.perf_counter(),
                shuffle_id=shuffle_id,
                rows=live,
                bytes=nbytes,
            )
        return stacked_dev, dead, stacked

    # conf-resolved schedule of the plan currently executing (execute()
    # runs plans one at a time per endpoint; set before the wave loop)
    _schedule_label = "ring"

    def _pallas_wave(self, wave: CollectiveWave, stacked: np.ndarray):
        """TPU mover: run the wave as one batched remote-DMA kernel
        epoch (``ops/remote_copy._wave_pull_program``). The send-layout
        shards carry the wave on every source device; the per-row id
        lane names which peer's DMA lands each row. Returns the landed
        [rows_b, bucket] stack committed to the local device, or raises
        (caller falls back to the transfer engine)."""
        import jax

        n = remote_copy.mesh_device_count()
        rows_b = wave.rows_b
        ids = np.zeros((rows_b,), dtype=np.int32)
        for i, row in enumerate(wave.rows):
            ids[i] = max(0, row.loc.block.device_coords) % n
        sharded = jax.device_put(np.tile(stacked, (n, 1)))
        landed = remote_copy.pallas_wave_pull(ids, sharded)
        return jax.device_put(
            np.asarray(landed)[:rows_b], self._dev.device
        )

    def _adopt_wave(self, wave, stacked_dev, dtype, fused, fusable_pids,
                    stacked_host=None):
        """Slice a landed wave into arena slabs: fused partitions land
        as one merged slab; everything else lands per block. Fused
        compaction runs the cached device gather when the wave is TPU-
        resident, and a plain numpy concatenate off-TPU (the emulated
        mover assembled ``stacked_host`` anyway, and a device gather
        program is pure overhead on the single-core harness)."""
        itemsize = np.dtype(dtype).itemsize
        out: List[CollectiveResult] = []
        flat = None
        starts_e = None
        if fused:
            # per-row element offsets (host-known lengths), feeding the
            # cached compaction gather
            counts = np.array(
                [r.elems if r.live else 0 for r in wave.rows]
                + [0] * (wave.rows_b - len(wave.rows)),
                dtype=np.int32,
            )
            ends_e = np.cumsum(counts, dtype=np.int32)
            starts_e = ends_e - counts
            need = any(
                r.live and r.loc.partition_id in fusable_pids
                for r in wave.rows
            )
            if need and stacked_host is not None and (
                not remote_copy.is_tpu_mesh()
            ):
                flat = np.concatenate(
                    [stacked_host[i, : r.elems]
                     for i, r in enumerate(wave.rows) if r.live]
                    or [np.empty(0, dtype=dtype)]
                )
            elif need:
                key = ("compact", wave.rows_b, wave.bucket_elems,
                       np.dtype(dtype).name)
                self._program_key_seen(key)
                prog = _compaction_program(
                    wave.rows_b, wave.bucket_elems, np.dtype(dtype).name
                )
                flat = prog(stacked_dev, starts_e, ends_e)

        i = 0
        n = len(wave.rows)
        while i < n:
            row = wave.rows[i]
            pid = row.loc.partition_id
            j = i
            while j < n and wave.rows[j].loc.partition_id == pid:
                j += 1
            group = [r for r in wave.rows[i:j] if r.live]
            if not group:
                i = j
                continue
            if fused and flat is not None and pid in fusable_pids:
                lo = int(starts_e[i])
                hi = lo + sum(r.elems for r in group)
                seg = flat[lo:hi]
                if isinstance(seg, np.ndarray):
                    # host-compacted: the merged slab moves in ONE put
                    import jax

                    seg = jax.device_put(seg, self._dev.device)
                dev = self._dev.get(seg.size * itemsize)
                try:
                    dev = dev.put_array(seg)
                except Exception:
                    dev.free()
                    raise
                out.append(CollectiveResult(
                    pid, dev, [r.loc for r in group], True
                ))
                self._m_fused.inc()
            else:
                for k, r in enumerate(wave.rows[i:j]):
                    if not r.live:
                        continue
                    rowv = stacked_dev[i + k, : r.elems]
                    dev = self._dev.get(r.elems * itemsize)
                    try:
                        dev = dev.put_array(rowv)
                    except Exception:
                        dev.free()
                        raise
                    out.append(CollectiveResult(pid, dev, [r.loc], False))
            i = j
        return out


def _null_ctx():
    import contextlib

    return contextlib.nullcontext()
