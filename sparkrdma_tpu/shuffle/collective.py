"""Whole-stage collective shuffle — the pipelined shuffle-schedule compiler.

The device fetch plane (DESIGN.md §17) moves one block per planner
decision: pin, pull, adopt, repeat. This module treats a reduce
stage's ENTIRE published location set as one object to compile: every
device-resident block (0xFFFE extension coordinates) is grouped into
batched DMA *waves* — fixed-shape [rows, bucket] stacks moved in one
mover dispatch — over a ring or all-to-all schedule, with compile-once
programs cached by (rows-class, bucket-class, dtype) exactly like the
exchange executable cache (DESIGN.md §22).

Waves run as a double-buffered PIPELINE (``collective.pipelineDepth``
in-flight entries): wave N+1's remote DMAs are dispatched while wave
N's rows merge, so the drain epoch of every wave but the last overlaps
a wave's worth of in-flight transfer. The host-plane passthrough reads
overlap with both ends — issued before the first wave, drained
concurrently with the last via the caller's ``drain`` callback.

Movers, by regime:

- TPU mesh: ``ops/remote_copy.pallas_wave_pull`` — one Pallas kernel
  epoch issuing ``rows`` ``make_async_remote_copy`` DMAs together
  (start all, wait all), per-row source device ids in a
  scalar-prefetch lane so one executable serves any peer set.
  Consecutive same-class waves coalesce into the depth-aware
  ``pallas_pipelined_wave_pull`` program — one DMA-semaphore array per
  in-flight wave, wave d+1 started before wave d drains.
- Everywhere else (and on any TPU-side surprise): the emulated mover's
  ISSUE/CONSUME halves (``emulated_row_pull_start`` /
  ``emulated_wave_wait``) — per-row pulls started together without
  waiting, landed slabs adopted directly (the same single-copy
  semantics as the per-block planner, batched, async, and overlapped
  across waves), which is why the compiled schedule beats the
  per-block pull loop even on the CPU mesh. Rows the fast lane cannot
  carry (nonzero arena offset, class mismatch, fused partitions that
  merge host-side) ride an assembled host stack and land through the
  compile-free ``stage_view`` path.

Fusion: a partition whose every block rides in one wave can merge in
the same epoch — a cached compaction program gathers the wave's valid
prefixes into one contiguous slab, so the partition lands as ONE
merged device buffer (concatenated in deterministic source order,
composing with the merged-cover contract of shuffle/merge.py) with no
intermediate HBM round trip. Fusion changes the result SHAPE (one
buffer per partition), so callers opt in per fetch.

Self-tuning: the compiler's :class:`~sparkrdma_tpu.shuffle.autotune.
WaveAutoTuner` re-derives the effective ``collective.waveBytes`` per
(shuffle, stage-shape) signature from the stage's own wave stats plus
the job's TimeBreakdown and profiler gap frames — the second identical
stage of a job already runs with the adjusted cut.

Degrade ladder (every rung silent, byte-identical):

| condition                                   | outcome             |
|---------------------------------------------|---------------------|
| ``collective.enabled`` off                   | per-block planner   |
| < ``collective.minBlocks`` device blocks     | per-block planner   |
| block fails eligibility (size/dtype/arena)   | per-block planner   |
| slab evicted/spilled between plan and pin    | host triple, degrade++ |
| wave mover fails (issue OR landing)          | host triple, degrade++ |
| row adoption fails mid-pipeline              | host triple, degrade++ |
| abort unwinds with waves in flight           | pins closed, rows degrade |
"""

from __future__ import annotations

import functools
import logging
import time
from collections import deque
from contextlib import ExitStack
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.ops import remote_copy
from sparkrdma_tpu.ops.exchange import round_bucket, round_rows
from sparkrdma_tpu.ops.hbm_arena import (
    DeviceBuffer,
    DeviceBufferManager,
    _size_class,
)
from sparkrdma_tpu.shuffle.autotune import (
    WaveAutoTuner,
    WaveReport,
    stage_signature,
)
from sparkrdma_tpu.shuffle.device_fetch import visible_arena

logger = logging.getLogger(__name__)


def merge_order_key(loc: PartitionLocation) -> Tuple:
    """Deterministic within-partition merge order — the order fused
    slabs concatenate in, and the order tests/benches sort per-block
    results into when comparing against a fused result."""
    return (
        loc.manager_id.executor_id,
        loc.block.mkey,
        loc.block.address,
        loc.block.arena_handle,
    )


@functools.lru_cache(maxsize=64)
def _compaction_program(rows_b: int, bucket_elems: int, dtype_str: str):
    """Jitted fetch->merge compaction: gather every row's valid prefix
    of a landed [rows_b, bucket_elems] wave into one contiguous flat
    lane — the merge half of the fused epoch. Pure gather math (no
    dynamic shapes): position j belongs to the row whose element span
    covers it, looked up against the inclusive end-offsets lane. On
    TPU, XLA keeps the gather in the same HBM residency as the landed
    wave — fetch to merged slab with no host round trip.

    Cached per (rows class, bucket class, dtype); rows and buckets are
    both power-of-two bucketed upstream, so ragged stages reuse these
    executables."""
    import jax
    import jax.numpy as jnp

    jnp.dtype(dtype_str)  # validate the cache key up front
    total = rows_b * bucket_elems

    def fn(stacked, starts, ends):
        j = jnp.arange(total, dtype=jnp.int32)
        row = jnp.searchsorted(ends, j, side="right")
        row = jnp.minimum(row, rows_b - 1)
        col = jnp.clip(j - starts[row], 0, bucket_elems - 1)
        return stacked[row, col]

    return jax.jit(fn)


class _Row:
    """One device-resident block scheduled into a wave."""

    __slots__ = ("loc", "elems", "live")

    def __init__(self, loc: PartitionLocation, elems: int):
        self.loc = loc
        self.elems = elems
        self.live = True


class CollectiveWave:
    """One batched mover dispatch: ``rows`` blocks of one bucket class."""

    __slots__ = ("rows", "bucket_elems", "rows_b", "lane")

    def __init__(self, rows: List[_Row], bucket_elems: int, lane: str):
        self.rows = rows
        self.bucket_elems = bucket_elems
        self.rows_b = round_rows(len(rows))
        self.lane = lane  # primary source executor (ring ordering key)


class CollectivePlan:
    """A compiled reduce-stage fetch schedule.

    ``passthrough`` locations never entered the schedule (collective
    off, too few device blocks, or per-block ineligibility) — the
    caller runs them through the pre-existing per-block loop, which
    preserves exactly the old behavior when the compiler declines.

    ``sig``/``stage_bytes``/``max_group_bytes`` feed the wave
    self-tuner after execution (None/0 when the compiler declined)."""

    __slots__ = ("schedule", "waves", "passthrough", "fusable_pids",
                 "device_blocks", "sig", "stage_bytes", "max_group_bytes")

    def __init__(self, schedule: str, waves: List[CollectiveWave],
                 passthrough: List[PartitionLocation],
                 fusable_pids: frozenset, device_blocks: int,
                 sig: Optional[Tuple] = None, stage_bytes: int = 0,
                 max_group_bytes: int = 0):
        self.schedule = schedule
        self.waves = waves
        self.passthrough = passthrough
        self.fusable_pids = fusable_pids
        self.device_blocks = device_blocks
        self.sig = sig
        self.stage_bytes = stage_bytes
        self.max_group_bytes = max_group_bytes


class CollectiveResult:
    """One landed slab: a single block, or a fused per-partition merge
    (``fused`` — ``locs`` then lists every covered block in merge
    order and ``dev.length`` is their summed payload)."""

    __slots__ = ("pid", "dev", "locs", "fused")

    def __init__(self, pid: int, dev: DeviceBuffer,
                 locs: List[PartitionLocation], fused: bool):
        self.pid = pid
        self.dev = dev
        self.locs = locs
        self.fused = fused


class _InflightWave:
    """One pipeline entry: a wave (or a same-class TPU kernel run of
    them) whose transfers are airborne. Pins stay held from issue to
    consume — the source slabs must survive until the recv semaphores
    land; the pipeline bounds the held set to ``depth`` entries."""

    __slots__ = ("waves", "pins", "t0", "dead", "all_dead", "row_arrs",
                 "row_views", "stacked_hosts", "landed", "nbytes", "live")

    def __init__(self, waves: List[CollectiveWave], pins: ExitStack,
                 t0: float):
        self.waves = waves
        self.pins = pins
        self.t0 = t0
        self.dead: List[_Row] = []
        self.all_dead = False
        # per wave: fast-lane in-flight arrays (row index -> array)
        self.row_arrs: List[Dict[int, object]] = []
        # per wave: zero-copy host views of pinned sources (fused CPU
        # rows — the merge concatenates straight from these, skipping
        # the stacked-assembly copy; valid only while pins are held)
        self.row_views: List[Dict[int, np.ndarray]] = []
        # per wave: assembled host stack (None when every row rode the
        # fast lane or a view)
        self.stacked_hosts: List[Optional[np.ndarray]] = []
        # TPU/fallback in-flight device object: ("single"|"pipelined",
        # async sharded result) or ("emulated", [stacks])
        self.landed = None
        self.nbytes = 0
        self.live = 0

    def close(self) -> None:
        try:
            self.pins.close()
        except Exception:
            logger.exception("collective pin release failed")


class ShuffleScheduleCompiler:
    """Compile + execute whole-stage device fetch schedules."""

    def __init__(self, conf, dev: DeviceBufferManager, executor_id: str,
                 tracer=None):
        self._conf = conf
        self._dev = dev
        self._executor_id = executor_id
        self._tracer = tracer
        # program-cache bookkeeping (the lru_caches hold the programs;
        # this counts resolutions for the compile-churn metrics)
        self._seen_programs: set = set()
        self._cache_lock = named_lock("collective.compiler")
        self._tuner = WaveAutoTuner(conf, executor_id)
        reg = get_registry()
        role = executor_id
        self._m_plans = reg.counter("collective.plans", role=role)
        self._m_blocks = reg.counter("collective.blocks", role=role)
        self._m_bytes = reg.counter("collective.bytes", role=role)
        self._m_fused = reg.counter("collective.fused_merges", role=role)
        self._m_degrades = reg.counter("collective.degrades", role=role)
        self._m_compiles = reg.counter("collective.compiles", role=role)
        self._m_cache_hits = reg.counter("collective.cache_hits", role=role)
        self._m_plan_ms = reg.histogram("collective.plan_ms", role=role)
        self._m_overlap = reg.counter(
            "collective.wave_overlap_ms", role=role
        )
        self._m_inflight = reg.histogram(
            "collective.wave_inflight", role=role
        )
        # the device-fetch plane's counters stay the one source of truth
        # for "blocks that moved HBM->HBM" vs "device offers declined":
        # a landed wave row IS a device pull, a degraded row IS a
        # fallback. collective.* adds the schedule-level detail on top.
        self._m_plane_pulls = reg.counter(
            "device_fetch.plane.pulls", role=role
        )
        self._m_plane_bytes = reg.counter(
            "device_fetch.plane.bytes", role=role
        )
        self._m_plane_fallbacks = reg.counter(
            "device_fetch.plane.fallbacks", role=role
        )

    # ------------------------------------------------------------------
    # plan
    # ------------------------------------------------------------------
    def plan(self, locations: Sequence[PartitionLocation],
             dtype=np.uint8) -> CollectivePlan:
        """Compile the stage's location set into a wave schedule.

        Eligibility here mirrors the per-block planner's static checks
        (device extension present, above minBlockBytes, source arena
        mesh-visible) plus an elem-alignment check the stacked layout
        needs; residency/dtype are re-checked under the pin at execute
        time, where a miss degrades to the host triple."""
        t0 = time.perf_counter()
        conf = self._conf
        itemsize = np.dtype(dtype).itemsize
        if not conf.collective_enabled or not conf.device_fetch_enabled:
            return CollectivePlan("off", [], list(locations), frozenset(), 0)
        min_bytes = conf.device_fetch_min_block_bytes
        eligible: List[PartitionLocation] = []
        passthrough: List[PartitionLocation] = []
        per_pid_total: Dict[int, int] = {}
        for loc in locations:
            per_pid_total[loc.partition_id] = (
                per_pid_total.get(loc.partition_id, 0) + 1
            )
            b = loc.block
            if (
                b.has_device
                and b.length >= min_bytes
                and b.length % itemsize == 0
                and b.arena_offset % itemsize == 0
                and visible_arena(loc.manager_id.executor_id) is not None
            ):
                eligible.append(loc)
            else:
                passthrough.append(loc)
        if len(eligible) < conf.collective_min_blocks:
            # too small a stage for a wave: the per-block planner keeps
            # the whole set (it may still pull the stragglers one by one)
            return CollectivePlan(
                "off", [], list(locations), frozenset(), 0
            )

        # merge order: partition-major so a fused pid's rows are
        # contiguous, source-ordered within the partition
        eligible.sort(key=lambda loc: (loc.partition_id, merge_order_key(loc)))
        per_pid_eligible: Dict[int, int] = {}
        per_pid_bytes: Dict[int, int] = {}
        stage_bytes = 0
        max_len = 0
        for loc in eligible:
            pid = loc.partition_id
            per_pid_eligible[pid] = per_pid_eligible.get(pid, 0) + 1
            bucketed = round_bucket(loc.block.length)
            per_pid_bytes[pid] = per_pid_bytes.get(pid, 0) + bucketed
            stage_bytes += bucketed
            max_len = max(max_len, loc.block.length)
        max_group_bytes = max(per_pid_bytes.values())

        lanes = sorted({loc.manager_id.executor_id for loc in eligible})
        schedule = conf.collective_schedule
        if schedule == "auto":
            schedule = "a2a" if len(lanes) > 2 else "ring"

        # the self-tuned cut: a stage shape the tuner has observed runs
        # with its adjusted budget (never below the fusion floor — a
        # partition's rows must share one wave — and never above the
        # operator's configured cap)
        sig = stage_signature(
            schedule, len(lanes), round_rows(len(eligible)),
            round_bucket(max_len), np.dtype(dtype).name,
        )
        wave_budget = conf.collective_wave_bytes
        tuned = self._tuner.wave_bytes_for(sig)
        if tuned:
            wave_budget = min(max(tuned, max_group_bytes), wave_budget)

        # wave formation: pid-group granularity (fusion needs a pid's
        # rows in ONE wave), split only when a single pid alone
        # overflows the wave budget (that pid becomes unfusable)
        waves: List[CollectiveWave] = []
        fusable: set = set()
        cur_rows: List[_Row] = []
        cur_max_len = 0

        def seal():
            nonlocal cur_rows, cur_max_len
            if cur_rows:
                bucket = round_bucket(cur_max_len)
                waves.append(CollectiveWave(
                    cur_rows, bucket // itemsize,
                    cur_rows[0].loc.manager_id.executor_id,
                ))
                cur_rows, cur_max_len = [], 0

        i = 0
        n = len(eligible)
        while i < n:
            pid = eligible[i].partition_id
            j = i
            group_max = 0
            while j < n and eligible[j].partition_id == pid:
                group_max = max(group_max, eligible[j].block.length)
                j += 1
            group = eligible[i:j]
            group_bytes = per_pid_bytes[pid]
            if group_bytes > wave_budget and len(group) > 1:
                # oversized pid: seal what we have, stream the pid
                # through dedicated waves, leave it unfusable
                seal()
                for loc in group:
                    cur_rows.append(_Row(loc, loc.block.length // itemsize))
                    cur_max_len = max(cur_max_len, loc.block.length)
                    if sum(round_bucket(r.loc.block.length)
                           for r in cur_rows) >= wave_budget:
                        seal()
                seal()
            else:
                cur_bytes = sum(
                    round_bucket(r.loc.block.length) for r in cur_rows
                )
                if cur_rows and cur_bytes + group_bytes > wave_budget:
                    seal()
                for loc in group:
                    cur_rows.append(_Row(loc, loc.block.length // itemsize))
                cur_max_len = max(cur_max_len, group_max)
                # fusable iff every one of the pid's published blocks
                # made it into the schedule (full device cover, the
                # merged-cover rule of shuffle/merge.py) and they share
                # this wave
                if per_pid_eligible[pid] == per_pid_total[pid]:
                    fusable.add(pid)
            i = j
        seal()

        if schedule == "ring":
            # lane-major wave order: one source lane in flight at a
            # time, walking the ring — the flow-controlled schedule.
            # Index lookups go through a precomputed map: the linear
            # lanes.index() scan inside a sort key is O(waves * lanes)
            # work a wide stage pays on every plan
            lane_index = {lane: k for k, lane in enumerate(lanes)}
            waves.sort(key=lambda w: lane_index[w.lane])
        self._m_plan_ms.observe((time.perf_counter() - t0) * 1e3)
        return CollectivePlan(
            schedule, waves, passthrough, frozenset(fusable), len(eligible),
            sig=sig, stage_bytes=stage_bytes,
            max_group_bytes=max_group_bytes,
        )

    # ------------------------------------------------------------------
    # execute
    # ------------------------------------------------------------------
    def execute(
        self,
        shuffle_id: int,
        plan: CollectivePlan,
        dtype=np.uint8,
        fused: bool = False,
        drain=None,
    ) -> Tuple[List[CollectiveResult], List[PartitionLocation]]:
        """Run the compiled schedule as a double-buffered pipeline;
        returns ``(results, degraded)``.

        Up to ``collective.pipelineDepth`` entries stay in flight:
        entry N+1's transfers are DISPATCHED before entry N's rows are
        waited on and adopted, so merge epochs overlap in-flight DMA.
        ``drain``, when given, is called with no arguments between
        pipeline steps — the host-plane caller passes its non-blocking
        arrivals drain so passthrough READs are consumed WHILE waves
        are in flight rather than after the last one.

        ``degraded`` lists every scheduled block that missed (evicted
        mid-stage, stale coordinates, mover failure, adoption failure)
        — the caller host-fetches them; with fusion on, a miss also
        unfuses its partition (the survivors land per block, the host
        fills the gap), so the byte content of the stage is identical
        on every path. Per-entry failures never raise; if an exception
        DOES unwind (e.g. out of ``drain``), every in-flight entry's
        pins are closed on the way out — no slab or pin outlives the
        stage."""
        if not plan.waves:
            return [], []
        fused = bool(fused) and self._conf.collective_fused_merge
        depth = max(1, self._conf.collective_pipeline_depth)
        self._schedule_label = plan.schedule
        reg = get_registry()
        results: List[CollectiveResult] = []
        degraded: List[PartitionLocation] = []
        self._m_plans.inc()
        stats = {"dispatch_ms": 0.0, "wave_ms": 0.0, "overlap_ms": 0.0}
        span = (
            self._tracer.span(
                "shuffle.collective", shuffle_id=shuffle_id,
                schedule=plan.schedule, waves=len(plan.waves),
                blocks=plan.device_blocks, depth=depth,
            )
            if self._tracer is not None
            else None
        )
        ctx = span if span is not None else _null_ctx()
        with ctx:
            # pids that lose a row to degradation must not fuse: the
            # host path refills per block, so survivors stay per block
            unfusable: set = set()
            inflight: Deque[_InflightWave] = deque()

            def _degrade_rows(rows: List[_Row]) -> None:
                if not rows:
                    return
                for row in rows:
                    degraded.append(row.loc)
                    unfusable.add(row.loc.partition_id)
                self._m_degrades.inc(len(rows))
                self._m_plane_fallbacks.inc(len(rows))

            def _consume_next() -> None:
                entry = inflight.popleft()
                self._consume_entry(
                    entry, shuffle_id, dtype, fused, plan.fusable_pids,
                    unfusable, results, _degrade_rows, reg,
                    overlapped=bool(inflight), stats=stats,
                )
                if drain is not None:
                    drain()

            try:
                for group in self._coalesce(plan.waves, depth):
                    while len(inflight) >= depth:
                        _consume_next()
                    entry = self._issue_entry(
                        shuffle_id, group, dtype, fused,
                        plan.fusable_pids, reg,
                        overlapped=bool(inflight), stats=stats,
                    )
                    if entry is None:
                        # whole-entry mover failure: every row degrades
                        _degrade_rows(
                            [r for w in group for r in w.rows]
                        )
                        continue
                    _degrade_rows(entry.dead)
                    if entry.all_dead:
                        continue
                    inflight.append(entry)
                    self._m_inflight.observe(float(len(inflight)))
                    if drain is not None:
                        drain()
                while inflight:
                    _consume_next()
            finally:
                # abort drain (an exception is unwinding): release every
                # in-flight entry's pins and degrade its unadopted rows
                # — leak-free by construction, and the caller's host
                # refill keeps the stage byte-identical when it survives
                while inflight:
                    entry = inflight.popleft()
                    entry.close()
                    _degrade_rows(
                        [r for w in entry.waves for r in w.rows if r.live]
                    )
        # close the loop: feed the stage's wave stats back into the
        # per-shape cut for the NEXT identical stage
        if plan.sig is not None:
            try:
                self._tuner.observe(plan.sig, WaveReport(
                    stage_bytes=plan.stage_bytes,
                    min_group_bytes=plan.max_group_bytes,
                    waves=len(plan.waves),
                    depth=depth,
                    dispatch_ms=stats["dispatch_ms"],
                    wave_ms=stats["wave_ms"],
                    overlap_ms=stats["overlap_ms"],
                ))
            except Exception:
                logger.exception("wave autotune observe failed")
        return results, degraded

    # ------------------------------------------------------------------
    def _program_key_seen(self, key) -> None:
        with self._cache_lock:
            if key in self._seen_programs:
                self._m_cache_hits.inc()
            else:
                self._seen_programs.add(key)
                self._m_compiles.inc()

    def _coalesce(
        self, waves: List[CollectiveWave], depth: int
    ) -> List[List[CollectiveWave]]:
        """Group consecutive same-class waves into depth-aware kernel
        runs. TPU only: the run becomes ONE ``pallas_pipelined_wave_
        pull`` epoch with a DMA-semaphore array per in-flight wave. Off
        TPU every wave is its own pipeline entry — the overlap happens
        at the host level (issue N+1 while N merges)."""
        if depth <= 1 or not remote_copy.is_tpu_mesh():
            return [[w] for w in waves]
        groups: List[List[CollectiveWave]] = []
        i = 0
        while i < len(waves):
            j = i + 1
            while (
                j < len(waves)
                and j - i < depth
                and waves[j].rows_b == waves[i].rows_b
                and waves[j].bucket_elems == waves[i].bucket_elems
            ):
                j += 1
            groups.append(list(waves[i:j]))
            i = j
        return groups

    def _issue_entry(
        self, shuffle_id: int, waves: List[CollectiveWave], dtype,
        fused: bool, fusable_pids: frozenset, reg, overlapped: bool,
        stats: Dict[str, float],
    ) -> Optional[_InflightWave]:
        """Pin, assemble, and DISPATCH one pipeline entry without
        waiting — the issue half of the double buffer. Rows that fail
        the under-pin residency re-check come back in ``entry.dead``
        (the caller degrades them); a mover surprise returns None and
        the whole entry degrades. The entry's pins stay held until its
        consume: the source slabs must outlive the in-flight DMAs."""
        t0 = time.perf_counter()
        itemsize = np.dtype(dtype).itemsize
        tpu = remote_copy.is_tpu_mesh()
        pins = ExitStack()
        entry = _InflightWave(waves, pins, t0)
        try:
            for wave in waves:
                rows_b, b_elems = wave.rows_b, wave.bucket_elems
                stacked: Optional[np.ndarray] = (
                    np.zeros((rows_b, b_elems), dtype=dtype) if tpu else None
                )
                arrs: Dict[int, object] = {}
                views: Dict[int, np.ndarray] = {}
                for i, row in enumerate(wave.rows):
                    blk = row.loc.block
                    arena = visible_arena(row.loc.manager_id.executor_id)
                    src = None
                    if arena is not None:
                        src = pins.enter_context(
                            arena.pinned_if_resident(blk.arena_handle)
                        )
                    if (
                        src is None
                        or blk.arena_offset + blk.length > src.capacity
                        or np.dtype(src.array.dtype) != np.dtype(dtype)
                    ):
                        row.live = False
                        entry.dead.append(row)
                        continue
                    fuse_row = fused and row.loc.partition_id in fusable_pids
                    if (
                        not tpu
                        and not fuse_row
                        and blk.arena_offset == 0
                        and src.array.nbytes == _size_class(blk.length)
                    ):
                        # fast lane: START the row's pull now (async;
                        # same-device sources go through a jitted copy,
                        # cross-device through the transfer engine) and
                        # adopt the landed slab whole at consume — the
                        # per-block planner's single-copy semantics,
                        # batched and overlapped
                        arrs[i] = remote_copy.emulated_row_pull_start(
                            src.array, self._dev.device
                        )
                        continue
                    host = np.asarray(src.array).view(dtype)
                    off = blk.arena_offset // itemsize
                    if not tpu and fuse_row:
                        # fused CPU row: hold a zero-copy view of the
                        # pinned source — the merge at consume
                        # concatenates straight from it, skipping the
                        # stacked-assembly copy (the pin stays held
                        # through adoption, so the view stays valid)
                        views[i] = host[off : off + row.elems]
                        continue
                    # the emulated gather: source HBM -> host lane of
                    # the assembled stack (the TPU path DMAs
                    # source-side shards instead; off TPU this lane
                    # carries offset/class-mismatched rows)
                    if stacked is None:
                        stacked = np.zeros((rows_b, b_elems), dtype=dtype)
                    stacked[i, : row.elems] = host[off : off + row.elems]
                entry.row_arrs.append(arrs)
                entry.row_views.append(views)
                entry.stacked_hosts.append(stacked)
            live_rows = [r for w in waves for r in w.rows if r.live]
            if not live_rows:
                # every row died at the pin: nothing to move; the
                # caller degrades them all
                pins.close()
                entry.all_dead = True
                return entry
            if tpu:
                entry.landed = self._dispatch_pallas(waves, entry, dtype)
            if len(waves) > 1:
                key = ("wave-pipe", len(waves), waves[0].rows_b,
                       waves[0].bucket_elems, np.dtype(dtype).name)
                self._program_key_seen(key)
            else:
                for wave in waves:
                    key = ("wave", wave.rows_b, wave.bucket_elems,
                           np.dtype(dtype).name)
                    self._program_key_seen(key)
        except Exception:
            logger.exception("collective wave issue failed; degrading to host")
            pins.close()
            return None
        entry.live = len(live_rows)
        entry.nbytes = sum(r.elems * itemsize for r in live_rows)
        dispatch_ms = (time.perf_counter() - t0) * 1e3
        reg.histogram(
            "collective.wave_dispatch_ms", role=self._executor_id,
            schedule=self._schedule_label,
        ).observe(dispatch_ms)
        stats["dispatch_ms"] += dispatch_ms
        if overlapped:
            # this dispatch ran while earlier waves were still in
            # flight — the pipeline's whole point, surfaced as a
            # counter the benches assert on
            stats["overlap_ms"] += dispatch_ms
            self._m_overlap.inc(dispatch_ms)
        return entry

    def _dispatch_pallas(self, waves: List[CollectiveWave],
                         entry: _InflightWave, dtype):
        """START the entry's remote DMAs as one kernel epoch (the
        depth-aware double-buffered program when the entry carries a
        same-class run) WITHOUT waiting; consume slices the landed
        result per wave. The send-layout shards carry the waves on
        every source device; the per-row id lane names which peer's
        DMA lands each row. Any bring-up surprise falls back to the
        transfer engine — same bytes."""
        import jax

        n = remote_copy.mesh_device_count()
        try:
            if len(waves) == 1:
                wave = waves[0]
                ids = np.zeros((wave.rows_b,), dtype=np.int32)
                for i, row in enumerate(wave.rows):
                    ids[i] = max(0, row.loc.block.device_coords) % n
                sharded = jax.device_put(
                    np.tile(entry.stacked_hosts[0], (n, 1))
                )
                return ("single", remote_copy.pallas_wave_pull(ids, sharded))
            depth = len(waves)
            rows_b = waves[0].rows_b
            b_elems = waves[0].bucket_elems
            ids = np.zeros((depth, rows_b), dtype=np.int32)
            stack = np.zeros((depth, rows_b, b_elems), dtype=dtype)
            for d, wave in enumerate(waves):
                stack[d] = entry.stacked_hosts[d]
                for i, row in enumerate(wave.rows):
                    ids[d, i] = max(0, row.loc.block.device_coords) % n
            sharded = jax.device_put(np.tile(stack, (n, 1, 1)))
            return (
                "pipelined",
                remote_copy.pallas_pipelined_wave_pull(ids, sharded, depth),
            )
        except Exception:
            logger.exception("pallas wave mover failed; using transfer engine")
            return ("emulated", [
                remote_copy.emulated_wave_issue(
                    entry.stacked_hosts[d], self._dev.device
                )
                for d in range(len(waves))
            ])

    def _consume_entry(
        self, entry: _InflightWave, shuffle_id: int, dtype, fused: bool,
        fusable_pids: frozenset, unfusable: set, results, _degrade_rows,
        reg, overlapped: bool, stats: Dict[str, float],
    ) -> None:
        """Wait for one entry's transfers (the recv-semaphore wait),
        release its pins, and adopt its rows into arena slabs. Never
        raises: a landing failure degrades the entry, an adoption
        failure degrades the affected rows — the pipeline keeps
        flowing either way."""
        t0 = time.perf_counter()
        role = self._executor_id
        try:
            waiting: List[object] = [
                a for arrs in entry.row_arrs for a in arrs.values()
            ]
            if entry.landed is not None:
                _, obj = entry.landed
                waiting.extend(obj if isinstance(obj, list) else [obj])
            remote_copy.emulated_wave_wait(waiting)
            stacked_devs = self._landed_stacks(entry)
        except Exception:
            logger.exception(
                "collective wave landing failed; degrading to host"
            )
            entry.close()
            _degrade_rows(
                [r for w in entry.waves for r in w.rows if r.live]
            )
            return
        # pins stay held through adoption: the fused merge reads
        # zero-copy views of the source slabs (the finally releases
        # them even if an adopt body throws)
        itemsize = np.dtype(dtype).itemsize
        now = time.perf_counter()
        try:
            for d, wave in enumerate(entry.waves):
                live = [r for r in wave.rows if r.live]
                if not live:
                    continue
                nbytes = sum(r.elems * itemsize for r in live)
                self._m_blocks.inc(len(live))
                self._m_bytes.inc(nbytes)
                self._m_plane_pulls.inc(len(live))
                self._m_plane_bytes.inc(nbytes)
                reg.counter(
                    "collective.waves", role=role,
                    schedule=self._schedule_label,
                ).inc()
                out, failed = self._adopt_wave(
                    wave,
                    stacked_devs[d] if stacked_devs is not None else None,
                    dtype, fused, fusable_pids - unfusable,
                    stacked_host=entry.stacked_hosts[d],
                    row_arrs=entry.row_arrs[d],
                    row_views=entry.row_views[d],
                )
                results.extend(out)
                _degrade_rows(failed)
                reg.histogram(
                    "collective.wave_ms", role=role,
                    schedule=self._schedule_label,
                ).observe((now - entry.t0) * 1e3)
                stats["wave_ms"] += (now - entry.t0) * 1e3
                if self._tracer is not None:
                    # per-wave span (dma-wave attribution, obs/attr.py):
                    # nests under execute()'s shuffle.collective span
                    # via the contextvar parent, so the critical path
                    # can enter the wave level instead of one opaque
                    # multi-wave slice
                    self._tracer.record(
                        "shuffle.collective.wave",
                        entry.t0,
                        time.perf_counter(),
                        shuffle_id=shuffle_id,
                        rows=len(live),
                        bytes=nbytes,
                    )
        finally:
            entry.close()
        consume_ms = (time.perf_counter() - t0) * 1e3
        if overlapped:
            # this merge ran with later waves' DMAs already airborne
            stats["overlap_ms"] += consume_ms
            self._m_overlap.inc(consume_ms)

    # conf-resolved schedule of the plan currently executing (execute()
    # runs plans one at a time per endpoint; set before the wave loop)
    _schedule_label = "ring"

    def _landed_stacks(self, entry: _InflightWave):
        """Per-wave landed device stacks for the TPU/fallback paths
        (None on the pure emulated path, whose rows adopt from the
        fast-lane arrays and the host assembly directly)."""
        if entry.landed is None:
            return None
        import jax

        kind, obj = entry.landed
        if kind == "emulated":
            return obj
        if kind == "single":
            wave = entry.waves[0]
            arr = np.asarray(obj)[: wave.rows_b]
            return [jax.device_put(arr, self._dev.device)]
        arr = np.asarray(obj)[: len(entry.waves)]
        return [
            jax.device_put(arr[d], self._dev.device)
            for d in range(len(entry.waves))
        ]

    def _adopt_wave(self, wave, stacked_dev, dtype, fused, fusable_pids,
                    stacked_host=None, row_arrs=None, row_views=None):
        """Adopt a landed wave into arena slabs: fused partitions land
        as one merged slab; everything else lands per block. Returns
        ``(results, failed_rows)`` — adoption failures degrade their
        rows instead of unwinding the pipeline.

        Row sources, one merge order: fast-lane rows adopt their
        landed slab-class array whole (``put_array``, no pad program —
        classes match by construction); fused CPU rows concatenate
        from zero-copy views of the still-pinned sources (one copy,
        not assembly + copy); assembled rows stage their exact payload
        through the compile-free ``stage_view`` path; TPU rows slice
        the landed device stack. Fused compaction runs the cached
        device gather when the wave is TPU-resident, and a plain numpy
        concatenate off-TPU (a device gather program is pure overhead
        on the single-core harness)."""
        itemsize = np.dtype(dtype).itemsize
        row_arrs = row_arrs or {}
        row_views = row_views or {}
        out: List[CollectiveResult] = []
        failed: List[_Row] = []
        flat = None
        starts_e = None
        if fused:
            # per-row element offsets (host-known lengths), feeding the
            # cached compaction gather
            counts = np.array(
                [r.elems if r.live else 0 for r in wave.rows]
                + [0] * (wave.rows_b - len(wave.rows)),
                dtype=np.int32,
            )
            ends_e = np.cumsum(counts, dtype=np.int32)
            starts_e = ends_e - counts
            need = any(
                r.live and r.loc.partition_id in fusable_pids
                for r in wave.rows
            )
            if need and not remote_copy.is_tpu_mesh() and (
                row_views or stacked_host is not None
            ):
                flat = np.concatenate(
                    [row_views[i] if i in row_views
                     else stacked_host[i, : r.elems]
                     for i, r in enumerate(wave.rows) if r.live]
                    or [np.empty(0, dtype=dtype)]
                )
            elif need and stacked_dev is not None:
                key = ("compact", wave.rows_b, wave.bucket_elems,
                       np.dtype(dtype).name)
                self._program_key_seen(key)
                prog = _compaction_program(
                    wave.rows_b, wave.bucket_elems, np.dtype(dtype).name
                )
                flat = prog(stacked_dev, starts_e, ends_e)

        i = 0
        n = len(wave.rows)
        while i < n:
            row = wave.rows[i]
            pid = row.loc.partition_id
            j = i
            while j < n and wave.rows[j].loc.partition_id == pid:
                j += 1
            group = [r for r in wave.rows[i:j] if r.live]
            if not group:
                i = j
                continue
            try:
                if fused and flat is not None and pid in fusable_pids:
                    lo = int(starts_e[i])
                    hi = lo + sum(r.elems for r in group)
                    seg = flat[lo:hi]
                    if isinstance(seg, np.ndarray):
                        # host-compacted: the merged slab moves in ONE
                        # put (a class-exact segment adopts with no
                        # pad program and no second copy)
                        import jax

                        seg = jax.device_put(seg, self._dev.device)
                    dev = self._dev.get(seg.size * itemsize)
                    try:
                        dev = dev.put_array(seg)
                    except Exception:
                        dev.free()
                        raise
                    out.append(CollectiveResult(
                        pid, dev, [r.loc for r in group], True
                    ))
                    self._m_fused.inc()
                else:
                    for k, r in enumerate(wave.rows[i:j]):
                        if not r.live:
                            continue
                        nbytes = r.elems * itemsize
                        if (i + k) in row_arrs:
                            # fast lane: the landed slab-class array
                            # swaps in whole (classes match — no pad
                            # program, no second transfer)
                            dev = self._dev.get(nbytes)
                            try:
                                dev = dev.put_array(row_arrs[i + k])
                            except Exception:
                                dev.free()
                                raise
                            dev.length = nbytes
                        elif (i + k) in row_views:
                            # fused-pid row whose partition unfused
                            # mid-stage: stage its zero-copy source
                            # view (pins are still held)
                            dev = self._dev.stage_view(
                                row_views[i + k], nbytes, dtype,
                            )
                        elif stacked_dev is not None:
                            rowv = stacked_dev[i + k, : r.elems]
                            dev = self._dev.get(nbytes)
                            try:
                                dev = dev.put_array(rowv)
                            except Exception:
                                dev.free()
                                raise
                        else:
                            # assembled row: exact payload through the
                            # compile-free staging path
                            dev = self._dev.stage_view(
                                stacked_host[i + k, : r.elems],
                                nbytes, dtype,
                            )
                        out.append(
                            CollectiveResult(pid, dev, [r.loc], False)
                        )
            except Exception:
                logger.exception(
                    "wave adoption failed for partition %d; degrading", pid
                )
                failed.extend(group)
            i = j
        return out, failed


def _null_ctx():
    import contextlib

    return contextlib.nullcontext()
