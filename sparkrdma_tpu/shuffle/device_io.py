"""Device shuffle IO — HBM staging on both ends of the shuffle.

The north-star data path (SURVEY.md §7, BASELINE.json): map outputs
stage from device HBM into *registered* host memory, locations publish
to the driver hub, and reducers pull with one-sided READs landing
blocks back into pooled HBM slabs for device compute — the tiered
HBM -> host-registered -> HBM store of SURVEY.md §7.3(4).

This is the raw-block sibling of the record-oriented writer/reader
stack: same control plane (publish / fetch-locations / barrier), same
registered-memory data plane, no serializer in the way. Each published
partition block is one pooled registered buffer whose
``(mkey, 0, length)`` triple is the advertised location.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.ops.hbm_arena import (
    DeviceBuffer,
    DeviceBufferManager,
    _size_class,
)
from sparkrdma_tpu.shuffle.errors import FetchFailedError, MetadataFetchFailedError
from sparkrdma_tpu.transport import FnListener, mapped_delivery_enabled
from sparkrdma_tpu.utils import checksum as _checksum

logger = logging.getLogger(__name__)


class DeviceShuffleIO:
    """Per-executor device-block shuffle endpoint."""

    def __init__(self, manager, device=None):
        self._manager = manager
        manager.start_node_if_missing()
        conf = manager.conf
        self._dev = DeviceBufferManager(
            device=device,
            max_bytes=conf.hbm_max_bytes,
            prealloc=conf.max_agg_prealloc,
            prealloc_size=conf.max_agg_block,
            max_host_bytes=conf.hbm_host_spill_max_bytes,
            spill_dir=conf.hbm_spill_dir or None,
        )
        # published host-side registered buffers per shuffle (kept alive
        # until unpublish — the serving side of one-sided READs)
        self._published: Dict[int, List] = {}
        self._lock = threading.Lock()
        # fetch-phase accounting (tunnel-vs-framework attribution):
        #   transport_s — waiting for bytes to ARRIVE in host memory
        #     (RPC, one-sided READ, pread/mmap, sockets): framework.
        #   stage_s — host -> HBM device transfers (jax.device_put via
        #     stage_view): the accelerator link (on this rig, the axon
        #     tunnel), NOT framework code.
        self._fetch_stats = {
            "fetch_transport_s": 0.0,
            "fetch_stage_s": 0.0,
            "fetch_bytes": 0,
        }

    @property
    def device_buffers(self) -> DeviceBufferManager:
        return self._dev

    # ------------------------------------------------------------------
    # map side: device -> registered host memory -> locations
    # ------------------------------------------------------------------
    def stage_device_blocks(
        self, shuffle_id: int, partitions: Dict[int, "object"]
    ) -> List[PartitionLocation]:
        """Stage per-partition device arrays into registered buffers and
        return their locations WITHOUT publishing — the stage half of
        the map pipeline, so the next shard's device sort can overlap
        this shard's driver RPC (publish_staged)."""
        mgr = self._manager
        locs: List[PartitionLocation] = []
        staged = []
        for pid, arr in partitions.items():
            # HBM -> registered memory in ONE host copy: the device
            # readback lands in a host array and its bytes move straight
            # into the registered shm view (no intermediate tobytes()/
            # write() materializations — SURVEY.md §7.3(3))
            host = np.asarray(arr)
            nbytes = host.nbytes
            buf = mgr.buffer_manager.get(nbytes)
            np.frombuffer(buf.view, dtype=np.uint8, count=nbytes)[:] = (
                host.reshape(-1).view(np.uint8)
            )
            staged.append(buf)
            locs.append(
                PartitionLocation(
                    mgr.local_manager_id,
                    pid,
                    BlockLocation(0, nbytes, buf.mkey),
                )
            )
        # buffers go under shuffle ownership as soon as they're staged:
        # a publish failure (or an aborted pipeline) still releases them
        # through unpublish/stop
        with self._lock:
            self._published.setdefault(shuffle_id, []).extend(staged)
        return locs

    def publish_staged(
        self,
        shuffle_id: int,
        locs: List[PartitionLocation],
        num_map_outputs: int = 1,
    ) -> None:
        """Publish previously staged locations (one publish = one map
        output for the driver's completeness barrier)."""
        self._manager.publish_partition_locations(
            shuffle_id, -1, locs, num_map_outputs=num_map_outputs
        )

    def publish_device_blocks(
        self,
        shuffle_id: int,
        partitions: Dict[int, "object"],
        num_map_outputs: int = 1,
    ) -> None:
        """Stage + publish in one call (the non-pipelined composition)."""
        locs = self.stage_device_blocks(shuffle_id, partitions)
        self.publish_staged(shuffle_id, locs, num_map_outputs=num_map_outputs)

    # ------------------------------------------------------------------
    # reduce side: one-sided READ -> HBM slab
    # ------------------------------------------------------------------
    def fetch_device_blocks(
        self,
        shuffle_id: int,
        start_partition: int,
        end_partition: int,
        dtype=np.uint8,
        timeout_s: Optional[float] = None,
    ) -> Dict[int, List[DeviceBuffer]]:
        """Pull every block of ``[start, end)`` into HBM slabs.

        Local blocks short-circuit from the publisher's own registered
        buffer (never looping through the network, SURVEY.md §5.1 #2).
        ``dtype`` types the staged slabs (host-side reinterpret; see
        ``DeviceBufferManager.stage_view``) so device consumers read
        keys, not bytes. Returns pid -> list of DeviceBuffers (caller
        frees).

        ``timeout_s`` is ONE deadline for the whole fetch (the
        reference's future-timeout wrapper semantics,
        RdmaShuffleFetcherIterator.scala:108-122) — not a per-block
        allowance, so one slow peer costs at most one timeout, never
        ``n_blocks ×``. The clock starts BEFORE the metadata RPC: the
        location fetch and the data reads share the same wall budget,
        so the worst case is 1× ``timeout_s``, not metadata-timeout +
        data-timeout. Fetched blocks are validated against their
        published checksum before staging; a mismatch earns one
        same-source refetch, then FetchFailedError.
        Arrived buffers stage in COMPLETION order while
        slower reads are still in flight: staging (the expensive
        host->HBM transfer on this rig) overlaps the waiting instead of
        serializing behind issue order."""
        mgr = self._manager
        conf = mgr.conf
        if timeout_s is None:
            timeout_s = conf.fetch_location_timeout_ms / 1000.0
        t_transport = t_stage = 0.0
        n_bytes = 0
        # the deadline covers metadata + data: started before the
        # location RPC, and the data-wait loop below runs on whatever
        # budget that RPC left over
        deadline = time.monotonic() + timeout_s
        future = mgr.fetch_remote_partition_locations(
            shuffle_id, start_partition, end_partition
        )
        tw = time.perf_counter()
        try:
            locations: List[PartitionLocation] = future.result(
                timeout=max(0.0, deadline - time.monotonic())
            )
        except Exception as e:
            raise MetadataFetchFailedError(shuffle_id, start_partition, str(e))
        finally:
            # the location RPC is transport: bytes can't arrive before
            # the driver answers where they are
            t_transport += time.perf_counter() - tw
            with self._lock:
                self._fetch_stats["fetch_transport_s"] += t_transport
            t_transport = 0.0

        out: Dict[int, List[DeviceBuffer]] = {}
        my_id = mgr.executor_id
        # Each in-flight read OWNS its destination buffer through its
        # completion listener: the buffer returns to the pool only once
        # the transport is provably done writing into it (completion or
        # channel latch) — never on a timeout racing a late payload.
        pending: List[Optional[Tuple]] = []
        # completion-order wake-ups: every read completion (success or
        # failure) posts its pending index here, so the caller stages
        # whatever arrived FIRST and learns of failures immediately
        # rather than when issue order reaches them
        arrivals: "queue.Queue[int]" = queue.Queue()

        def start_read_mapped(idx, loc, ch):
            """Mapped-delivery flavor (native transport): no pooled
            destination buffer at all. Same-host blocks arrive as
            zero-copy page-cache mappings; remote ones as one malloc'd
            blob. Ownership dance mirrors start_read: whoever turns out
            to be the last owner (caller or listener) releases."""
            done = threading.Event()
            errbox: list = []
            box: dict = {}
            lock = threading.Lock()
            owner = {"who": "caller"}

            def on_ok(delivery):
                box["d"] = delivery
                done.set()
                with lock:
                    release = (
                        owner["who"] == "listener" and not owner.get("done")
                    )
                    if release:
                        owner["done"] = True
                if release and delivery is not None:
                    delivery.release()
                arrivals.put(idx)

            def on_fail(e):
                errbox.append(e)
                done.set()
                arrivals.put(idx)

            def abandon_or_reclaim():
                with lock:
                    if done.is_set():
                        completed = not owner.get("done")
                        owner["done"] = True
                    else:
                        owner["who"] = "listener"
                        completed = False
                if completed:
                    d = box.get("d")
                    if d is not None:
                        d.release()

            ch.read_mapped_in_queue(
                FnListener(on_ok, on_fail),
                [(loc.block.mkey, loc.block.address, loc.block.length)],
            )
            return (loc, box, done, errbox, abandon_or_reclaim)

        def start_read(idx, loc, reg, ch):
            done = threading.Event()
            errbox: list = []
            lock = threading.Lock()
            owner = {"who": "caller"}  # flipped to "listener" on abandon

            def on_done(err=None):
                if err is not None:
                    errbox.append(err)
                done.set()
                with lock:
                    # on_failure may legally fire more than once; recycle
                    # exactly once
                    recycle = owner["who"] == "listener" and not owner.get("recycled")
                    if recycle:
                        owner["recycled"] = True
                if recycle:
                    mgr.buffer_manager.put(reg)
                # duplicate posts are harmless: the arrival loop skips
                # indices it has already consumed
                arrivals.put(idx)

            def abandon_or_reclaim():
                """Caller gives up: recycle now if the read already
                completed, else hand ownership to the listener."""
                with lock:
                    if done.is_set():
                        completed = True
                    else:
                        owner["who"] = "listener"
                        completed = False
                if completed:
                    mgr.buffer_manager.put(reg)

            ch.read_in_queue(
                FnListener(lambda _: on_done(), on_done),
                [reg.view[: loc.block.length]],
                [(loc.block.mkey, loc.block.address, loc.block.length)],
            )
            return (loc, reg, done, errbox, abandon_or_reclaim)

        try:
            for loc in locations:
                if loc.manager_id.executor_id == my_id:
                    # local short-circuit straight from the registered
                    # region — DMA'd directly, never copied to bytes.
                    # Resolve up to a full slab class past the block's
                    # start (pooled regions span one, so this usually
                    # covers it) to hit stage_view's compile- and
                    # copy-free branch; only a region tail (mapped-file
                    # chunk) falls back to the host-pad branch.
                    pd = mgr.node.pd
                    avail = (
                        pd.region_length(loc.block.mkey) - loc.block.address
                    )
                    span = min(_size_class(loc.block.length), avail)
                    view = pd.resolve(loc.block.mkey, loc.block.address, span)
                    ts = time.perf_counter()
                    dev = self._dev.stage_view(view, loc.block.length, dtype)
                    t_stage += time.perf_counter() - ts
                    n_bytes += loc.block.length
                    out.setdefault(loc.partition_id, []).append(dev)
                    continue
                ch = mgr.get_channel_to(loc.manager_id, purpose="data")
                if mapped_delivery_enabled(conf, ch):
                    pending.append(start_read_mapped(len(pending), loc, ch))
                else:
                    reg = mgr.buffer_manager.get(loc.block.length)
                    pending.append(start_read(len(pending), loc, reg, ch))

            remaining = {i for i, e in enumerate(pending) if e is not None}
            refetched: set = set()
            while remaining:
                budget = deadline - time.monotonic()
                tw = time.perf_counter()
                try:
                    if budget > 0:
                        idx = arrivals.get(timeout=budget)
                    else:
                        # the deadline bounds the WAITING, not the
                        # consumption of reads that already landed:
                        # staging time (host->HBM transfers) may have
                        # eaten the budget while completions queued up —
                        # drain those without blocking before failing
                        idx = arrivals.get_nowait()
                except queue.Empty:
                    # the final (possibly full-budget) wait is transport
                    # time too — without this the failure case records
                    # near-zero transport for a fetch that spent its
                    # whole wall waiting on it
                    t_transport += time.perf_counter() - tw
                    # deadline spent with reads still outstanding
                    slow = pending[next(iter(remaining))][0]
                    raise FetchFailedError(
                        slow.manager_id, shuffle_id, -1, slow.partition_id,
                        f"fetch deadline ({timeout_s:.1f}s) exceeded with "
                        f"{len(remaining)} block(s) outstanding",
                    )
                t_transport += time.perf_counter() - tw
                if idx not in remaining:
                    continue  # duplicate completion post
                loc, obj, done, errbox, _abandon = pending[idx]
                if not done.is_set():
                    # stale post from a superseded (refetched) attempt;
                    # the live read posts idx again on completion
                    continue
                if errbox:
                    mgr.health.record_failure(loc.manager_id.executor_id)
                    raise FetchFailedError(
                        loc.manager_id, shuffle_id, -1, loc.partition_id,
                        str(errbox[0]),
                    )
                # integrity gate before the expensive host->HBM stage
                if isinstance(obj, dict):
                    d = obj["d"]
                    ck_view = d.views[0] if d.views else b""
                else:
                    ck_view = obj.view[: loc.block.length]
                if not _checksum.verify(
                    ck_view, loc.block.checksum, loc.block.checksum_algo
                ):
                    if isinstance(obj, dict):
                        obj["d"].release()
                    else:
                        mgr.buffer_manager.put(obj)
                    get_registry().counter(
                        "resilience.checksum_failures", role=my_id
                    ).inc()
                    if idx in refetched:
                        mgr.health.record_failure(loc.manager_id.executor_id)
                        raise FetchFailedError(
                            loc.manager_id, shuffle_id, -1, loc.partition_id,
                            "checksum mismatch persisted across refetch",
                        )
                    refetched.add(idx)
                    get_registry().counter(
                        "resilience.retries", role=my_id
                    ).inc()
                    ch = mgr.get_channel_to(loc.manager_id, purpose="data")
                    if isinstance(obj, dict):
                        pending[idx] = start_read_mapped(idx, loc, ch)
                    else:
                        reg2 = mgr.buffer_manager.get(loc.block.length)
                        pending[idx] = start_read(idx, loc, reg2, ch)
                    continue
                mgr.health.record_success(loc.manager_id.executor_id)
                ts = time.perf_counter()
                if isinstance(obj, dict):
                    # mapped delivery: stage straight from the page-cache
                    # mapping (or fallback blob) — the socket/pread copy
                    # of the buffer path never happened. stage_view
                    # blocks until the device transfer completes, so
                    # releasing the mapping right after is safe.
                    d = obj["d"]
                    view = d.views[0] if d.views else b""
                    dev = self._dev.stage_view(view, loc.block.length, dtype)
                    d.release()
                else:
                    # registered buffer -> HBM directly (one DMA, no pad
                    # program: the pooled source spans a full slab
                    # class); the buffer returns to the pool only after
                    # the transfer, which device_put completes
                    # synchronously for host sources
                    dev = self._dev.stage_view(obj.view, loc.block.length, dtype)
                    mgr.buffer_manager.put(obj)  # pooled reuse, not a cold free
                t_stage += time.perf_counter() - ts
                n_bytes += loc.block.length
                pending[idx] = None
                remaining.discard(idx)
                out.setdefault(loc.partition_id, []).append(dev)
            return out
        except Exception:
            # release everything: staged device slabs are freed here;
            # each unconsumed destination buffer is recycled atomically
            # by whichever side (caller / completion listener) turns out
            # to be its last owner
            for bufs in out.values():
                for dev in bufs:
                    dev.free()
            for entry in pending:
                if entry is None:
                    continue
                entry[4]()  # abandon_or_reclaim
            raise
        finally:
            with self._lock:
                self._fetch_stats["fetch_transport_s"] += t_transport
                self._fetch_stats["fetch_stage_s"] += t_stage
                self._fetch_stats["fetch_bytes"] += n_bytes
            reg = get_registry()
            reg.histogram("device_fetch.transport_ms").observe(t_transport * 1e3)
            reg.histogram("device_fetch.stage_ms").observe(t_stage * 1e3)
            reg.counter("device_fetch.bytes").inc(n_bytes)

    # ------------------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Manager counters + the device (HBM) pool's: allocation per
        size class, live budget, and host-tier spill count."""
        snap = self._manager.metrics_snapshot()
        snap["hbm_pool_allocs_by_class"] = {
            str(k): v for k, v in self._dev.stats().items()
        }
        snap["hbm_in_use_bytes"] = self._dev.in_use_bytes
        snap["hbm_spill_count"] = self._dev.spill_count
        snap["hbm_disk_spill_count"] = self._dev.disk_spill_count
        with self._lock:
            snap.update(
                {k: round(v, 3) if isinstance(v, float) else v
                 for k, v in self._fetch_stats.items()}
            )
        return snap

    def unpublish(self, shuffle_id: int) -> None:
        """Release the registered buffers serving a shuffle's blocks."""
        with self._lock:
            staged = self._published.pop(shuffle_id, [])
        for buf in staged:
            self._manager.buffer_manager.put(buf)

    def stop(self) -> None:
        with self._lock:
            shuffles = list(self._published.keys())
        for sid in shuffles:
            self.unpublish(sid)
        self._dev.stop()
