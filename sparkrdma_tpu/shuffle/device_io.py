"""Device shuffle IO — HBM staging on both ends of the shuffle.

The north-star data path (SURVEY.md §7, BASELINE.json): map outputs
stage from device HBM into *registered* host memory, locations publish
to the driver hub, and reducers pull with one-sided READs landing
blocks back into pooled HBM slabs for device compute — the tiered
HBM -> host-registered -> HBM store of SURVEY.md §7.3(4).

This is the raw-block sibling of the record-oriented writer/reader
stack: same control plane (publish / fetch-locations / barrier), same
registered-memory data plane, no serializer in the way. Each published
partition block is one pooled registered buffer whose
``(mkey, 0, length)`` triple is the advertised location.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.ops.hbm_arena import (
    DeviceBuffer,
    DeviceBufferManager,
    _size_class,
)
from sparkrdma_tpu.shuffle.collective import ShuffleScheduleCompiler
from sparkrdma_tpu.shuffle.device_fetch import (
    DeviceFetchPlane,
    DevicePulledBlock,
    register_arena,
    unregister_arena,
)
from sparkrdma_tpu.shuffle.errors import FetchFailedError, MetadataFetchFailedError
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.transport import FnListener, mapped_delivery_enabled
from sparkrdma_tpu.utils import checksum as _checksum

logger = logging.getLogger(__name__)


def _start_read_mapped(mgr, arrivals, idx, loc, ch):
    """Issue one mapped-delivery READ (native transport): no pooled
    destination buffer at all. Same-host blocks arrive as zero-copy
    page-cache mappings; remote ones as one malloc'd blob. Each
    in-flight read OWNS its delivery through its completion listener:
    whoever turns out to be the last owner (caller or listener)
    releases — never a timeout racing a late payload. Returns
    ``(loc, box, done, errbox, abandon_or_reclaim)``; every completion
    (success or failure) posts ``idx`` to ``arrivals``."""
    done = threading.Event()
    errbox: list = []
    box: dict = {}
    lock = threading.Lock()
    owner = {"who": "caller"}

    def on_ok(delivery):
        box["d"] = delivery
        done.set()
        with lock:
            release = owner["who"] == "listener" and not owner.get("done")
            if release:
                owner["done"] = True
        if release and delivery is not None:
            delivery.release()
        arrivals.put(idx)

    def on_fail(e):
        errbox.append(e)
        done.set()
        arrivals.put(idx)

    def abandon_or_reclaim():
        with lock:
            if done.is_set():
                completed = not owner.get("done")
                owner["done"] = True
            else:
                owner["who"] = "listener"
                completed = False
        if completed:
            d = box.get("d")
            if d is not None:
                d.release()

    ch.read_mapped_in_queue(
        FnListener(on_ok, on_fail),
        [(loc.block.mkey, loc.block.address, loc.block.length)],
    )
    return (loc, box, done, errbox, abandon_or_reclaim)


def _start_read(mgr, arrivals, idx, loc, reg, ch):
    """Issue one buffer-landing READ into pooled registered memory
    ``reg``. Same ownership dance and return shape as
    :func:`_start_read_mapped` (the second element is ``reg``)."""
    done = threading.Event()
    errbox: list = []
    lock = threading.Lock()
    owner = {"who": "caller"}  # flipped to "listener" on abandon

    def on_done(err=None):
        if err is not None:
            errbox.append(err)
        done.set()
        with lock:
            # on_failure may legally fire more than once; recycle
            # exactly once
            recycle = owner["who"] == "listener" and not owner.get("recycled")
            if recycle:
                owner["recycled"] = True
        if recycle:
            mgr.buffer_manager.put(reg)
        # duplicate posts are harmless: the arrival loop skips
        # indices it has already consumed
        arrivals.put(idx)

    def abandon_or_reclaim():
        """Caller gives up: recycle now if the read already
        completed, else hand ownership to the listener."""
        with lock:
            if done.is_set():
                completed = True
            else:
                owner["who"] = "listener"
                completed = False
        if completed:
            mgr.buffer_manager.put(reg)

    ch.read_in_queue(
        FnListener(lambda _: on_done(), on_done),
        [reg.view[: loc.block.length]],
        [(loc.block.mkey, loc.block.address, loc.block.length)],
    )
    return (loc, reg, done, errbox, abandon_or_reclaim)


class HostBlock:
    """A fetched-but-unverified shuffle block in host memory — the
    hand-off unit between the reduce pipeline's fetch stage (transport:
    :meth:`DeviceShuffleIO.fetch_host_blocks`) and its decode/staging
    stages (:meth:`verify_host_block` / :meth:`stage_host_block`).

    ``view`` spans the whole backing resource (a full slab-class pooled
    buffer, a local registered span, or a mapped window) so staging can
    hit ``stage_view``'s copy-free branch; payload bytes are
    ``data`` (= ``view[:length]``). ``release()`` is idempotent and
    returns the backing resource to wherever it came from."""

    __slots__ = ("shuffle_id", "loc", "length", "view", "kind", "_release", "_released")

    def __init__(self, shuffle_id, loc, view, kind, release):
        self.shuffle_id = shuffle_id
        self.loc = loc
        self.length = loc.block.length
        self.view = view
        self.kind = kind  # "local" | "buffer" | "mapped"
        self._release = release
        self._released = False

    @property
    def data(self):
        return self.view[: self.length]

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._release is not None:
            self._release()


class DeviceShuffleIO:
    """Per-executor device-block shuffle endpoint."""

    def __init__(self, manager, device=None):
        self._manager = manager
        manager.start_node_if_missing()
        conf = manager.conf
        self._dev = DeviceBufferManager(
            device=device,
            max_bytes=conf.hbm_max_bytes,
            prealloc=conf.max_agg_prealloc,
            prealloc_size=conf.max_agg_block,
            max_host_bytes=conf.hbm_host_spill_max_bytes,
            spill_dir=conf.hbm_spill_dir or None,
        )
        # published host-side registered buffers per shuffle (kept alive
        # until unpublish — the serving side of one-sided READs)
        self._published: Dict[int, List] = {}
        # device fetch plane (DESIGN.md §17): arena-staged copies of the
        # same published blocks, served HBM->HBM to mesh-visible pullers;
        # the registry entry is what makes THIS endpoint's arena visible
        self._arena_published: Dict[int, List[DeviceBuffer]] = {}
        register_arena(manager.executor_id, self._dev)
        self._plane = DeviceFetchPlane(conf, self._dev, manager.executor_id)
        # whole-stage schedule compiler (DESIGN.md §22): batches the
        # stage's device-resident blocks into compiled DMA waves; the
        # per-block plane above stays the path for its passthrough set
        self._collective = ShuffleScheduleCompiler(
            conf, self._dev, manager.executor_id,
            tracer=getattr(manager, "tracer", None),
        )
        self._lock = threading.Lock()
        # fetch-phase accounting (tunnel-vs-framework attribution):
        #   transport_s — waiting for bytes to ARRIVE in host memory
        #     (RPC, one-sided READ, pread/mmap, sockets): framework.
        #   stage_s — host -> HBM device transfers (jax.device_put via
        #     stage_view): the accelerator link (on this rig, the axon
        #     tunnel), NOT framework code.
        self._fetch_stats = {
            "fetch_transport_s": 0.0,
            "fetch_stage_s": 0.0,
            "fetch_bytes": 0,
        }

    @property
    def device_buffers(self) -> DeviceBufferManager:
        return self._dev

    # ------------------------------------------------------------------
    # map side: device -> registered host memory -> locations
    # ------------------------------------------------------------------
    def stage_device_blocks(
        self,
        shuffle_id: int,
        partitions: Dict[int, "object"],
        block_format: int = 0,
    ) -> List[PartitionLocation]:
        """Stage per-partition device arrays into registered buffers and
        return their locations WITHOUT publishing — the stage half of
        the map pipeline, so the next shard's device sort can overlap
        this shard's driver RPC (publish_staged).

        ``block_format`` tags every staged block's encoding
        (``BlockLocation.FORMAT_*``). Device-staged bytes already carry
        their layout in the array dtype, so columnar-encoded payloads
        (DESIGN.md §25) advertise ``FORMAT_COLUMNAR`` here and reducers
        consume them pickle-free straight off the arena."""
        mgr = self._manager
        conf = mgr.conf
        dev_plane = conf.device_fetch_enabled
        dev_min = conf.device_fetch_min_block_bytes
        locs: List[PartitionLocation] = []
        staged = []
        arena_staged: List[DeviceBuffer] = []
        for pid, arr in partitions.items():
            # HBM -> registered memory in ONE host copy: the device
            # readback lands in a host array and its bytes move straight
            # into the registered shm view (no intermediate tobytes()/
            # write() materializations — SURVEY.md §7.3(3))
            host = np.asarray(arr)
            nbytes = host.nbytes
            buf = mgr.buffer_manager.get(nbytes)
            np.frombuffer(buf.view, dtype=np.uint8, count=nbytes)[:] = (
                host.reshape(-1).view(np.uint8)
            )
            staged.append(buf)
            # integrity tag computed HERE, while the bytes are still
            # cache-hot from the copy above and this runs on the map
            # pool's parallel stage workers — the manager's publish-time
            # funnel (_with_checksum) skips already-tagged locations, so
            # the serial publish RPC no longer pays a CRC per block
            ck_algo = ck = 0
            if conf.resilience_checksums and nbytes:
                ck_algo, ck = _checksum.compute(host.reshape(-1).view(np.uint8))
            block = BlockLocation(
                0, nbytes, buf.mkey, checksum=ck, checksum_algo=ck_algo,
                block_format=block_format,
            )
            if dev_plane and nbytes >= dev_min:
                # keep a second, device-resident copy in the HBM arena
                # and advertise its coordinates: a mesh-visible reducer
                # pulls it HBM->HBM (device_fetch.py) while the host
                # triple above stays the durable fallback. Best-effort —
                # arena pressure (MemoryError) just skips the extension.
                try:
                    abuf = self._dev.stage_view(
                        host.reshape(-1).view(np.uint8), nbytes,
                        dtype=host.dtype,
                    )
                except MemoryError:
                    abuf = None
                if abuf is not None:
                    arena_staged.append(abuf)
                    block = BlockLocation(
                        0, nbytes, buf.mkey,
                        checksum=ck, checksum_algo=ck_algo,
                        device_coords=getattr(self._dev.device, "id", 0),
                        arena_handle=abuf.handle,
                        arena_offset=0,
                        block_format=block_format,
                    )
            locs.append(PartitionLocation(mgr.local_manager_id, pid, block))
        # buffers go under shuffle ownership as soon as they're staged:
        # a publish failure (or an aborted pipeline) still releases them
        # through unpublish/stop
        with self._lock:
            self._published.setdefault(shuffle_id, []).extend(staged)
            self._arena_published.setdefault(shuffle_id, []).extend(arena_staged)
        return locs

    def publish_staged(
        self,
        shuffle_id: int,
        locs: List[PartitionLocation],
        num_map_outputs: int = 1,
    ) -> None:
        """Publish previously staged locations (one publish = one map
        output for the driver's completeness barrier)."""
        self._manager.publish_partition_locations(
            shuffle_id, -1, locs, num_map_outputs=num_map_outputs
        )

    def publish_staged_batch(
        self,
        shuffle_id: int,
        windows: List[List[PartitionLocation]],
        num_map_outputs_each: int = 1,
    ) -> None:
        """Publish N staged shards' location windows in ONE driver RPC.

        The driver's publish handler already *sums* ``num_map_outputs``
        into its completeness barrier and keys every location by its
        own partition id, so a batch is just the concatenated windows
        plus the summed count — no new RPC type. This is the map loop's
        answer to publish contention: instead of N serial round-trips
        through the driver's per-shuffle lock, the executor pays one."""
        if not windows:
            return
        locs = [loc for window in windows for loc in window]
        self._manager.publish_partition_locations(
            shuffle_id, -1, locs,
            num_map_outputs=num_map_outputs_each * len(windows),
        )

    def publish_device_blocks(
        self,
        shuffle_id: int,
        partitions: Dict[int, "object"],
        num_map_outputs: int = 1,
    ) -> None:
        """Stage + publish in one call (the non-pipelined composition)."""
        locs = self.stage_device_blocks(shuffle_id, partitions)
        self.publish_staged(shuffle_id, locs, num_map_outputs=num_map_outputs)

    # ------------------------------------------------------------------
    # reduce side: one-sided READ -> HBM slab
    # ------------------------------------------------------------------
    def _apply_merged_plan(
        self, locations: List[PartitionLocation], my_id: str
    ) -> List[PartitionLocation]:
        """Merged-else-original read selection (shuffle/merge.py).

        A partition fully covered by a push-merged segment reads as ONE
        sequential block instead of N per-map fetches. The device plane
        only takes LOCAL merged segments (push routing lands them on
        the reducing executor; a mis-routed segment just uses the
        originals) and verifies them here — the local short-circuit in
        the fetch loops skips the per-block checksum gate, and a
        corrupted seal must detect and fall back, never surface."""
        from sparkrdma_tpu.shuffle import merge as _merge

        selected, fallbacks = _merge.plan_reads(locations)
        if not fallbacks:
            return selected
        out: List[PartitionLocation] = []
        for loc in selected:
            if not loc.block.merged_cover:
                out.append(loc)
                continue
            origs = fallbacks.get(loc.partition_id, [])
            if loc.manager_id.executor_id != my_id:
                out.extend(origs)
                continue
            try:
                pd = self._manager.node.pd
                view = pd.resolve(
                    loc.block.mkey, loc.block.address, loc.block.length
                )
                if not _checksum.verify(
                    view, loc.block.checksum, loc.block.checksum_algo
                ):
                    raise ValueError("merged segment checksum mismatch")
            except Exception:
                logger.warning(
                    "merged segment for partition %d failed verification; "
                    "reading originals", loc.partition_id,
                )
                get_registry().counter("push.fallbacks", role=my_id).inc()
                get_registry().counter(
                    "resilience.checksum_failures", role=my_id
                ).inc()
                out.extend(origs)
                continue
            get_registry().counter("reader.merged_reads", role=my_id).inc()
            out.append(loc)
        return out

    def fetch_device_blocks(
        self,
        shuffle_id: int,
        start_partition: int,
        end_partition: int,
        dtype=np.uint8,
        timeout_s: Optional[float] = None,
        fused: bool = False,
    ) -> Dict[int, List[DeviceBuffer]]:
        """Pull every block of ``[start, end)`` into HBM slabs.

        Local blocks short-circuit from the publisher's own registered
        buffer (never looping through the network, SURVEY.md §5.1 #2).
        ``dtype`` types the staged slabs (host-side reinterpret; see
        ``DeviceBufferManager.stage_view``) so device consumers read
        keys, not bytes. Returns pid -> list of DeviceBuffers (caller
        frees).

        ``timeout_s`` is ONE deadline for the whole fetch (the
        reference's future-timeout wrapper semantics,
        RdmaShuffleFetcherIterator.scala:108-122) — not a per-block
        allowance, so one slow peer costs at most one timeout, never
        ``n_blocks ×``. The clock starts BEFORE the metadata RPC: the
        location fetch and the data reads share the same wall budget,
        so the worst case is 1× ``timeout_s``, not metadata-timeout +
        data-timeout. Fetched blocks are validated against their
        published checksum before staging; a mismatch earns one
        same-source refetch, then FetchFailedError.
        Arrived buffers stage in COMPLETION order while
        slower reads are still in flight: staging (the expensive
        host->HBM transfer on this rig) overlaps the waiting instead of
        serializing behind issue order.

        Device-resident blocks route through the whole-stage schedule
        compiler (shuffle/collective.py, DESIGN.md §22): the host READs
        for the non-device remainder are issued FIRST, then the
        compiled DMA waves run while those reads are in flight. With
        ``fused=True`` a partition fully covered by one wave lands as
        ONE merged slab (its blocks concatenated in deterministic
        source order) — callers opt in because it changes the result
        shape; the ``collective.fusedMerge`` knob is the global
        off-switch."""
        mgr = self._manager
        conf = mgr.conf
        if timeout_s is None:
            timeout_s = conf.fetch_location_timeout_ms / 1000.0
        t_transport = t_stage = 0.0
        n_bytes = 0
        # the deadline covers metadata + data: started before the
        # location RPC, and the data-wait loop below runs on whatever
        # budget that RPC left over
        deadline = time.monotonic() + timeout_s
        future = mgr.fetch_remote_partition_locations(
            shuffle_id, start_partition, end_partition
        )
        tw = time.perf_counter()
        try:
            locations: List[PartitionLocation] = future.result(
                timeout=max(0.0, deadline - time.monotonic())
            )
        except Exception as e:
            raise MetadataFetchFailedError(shuffle_id, start_partition, str(e))
        finally:
            # the location RPC is transport: bytes can't arrive before
            # the driver answers where they are
            t_transport += time.perf_counter() - tw
            with self._lock:
                self._fetch_stats["fetch_transport_s"] += t_transport
            t_transport = 0.0

        out: Dict[int, List[DeviceBuffer]] = {}
        my_id = mgr.executor_id
        locations = self._apply_merged_plan(locations, my_id)
        # whole-stage compile: device-resident blocks batch into DMA
        # waves; everything the compiler declines comes back in
        # cplan.passthrough and takes the per-block loop unchanged
        cplan = self._collective.plan(locations, dtype)
        # Each in-flight read OWNS its destination buffer through its
        # completion listener: the buffer returns to the pool only once
        # the transport is provably done writing into it (completion or
        # channel latch) — never on a timeout racing a late payload.
        pending: List[Optional[Tuple]] = []
        # completion-order wake-ups: every read completion (success or
        # failure) posts its pending index here, so the caller stages
        # whatever arrived FIRST and learns of failures immediately
        # rather than when issue order reaches them
        arrivals: "queue.Queue[int]" = queue.Queue()

        try:
            def _issue(loc, allow_pull=True):
                nonlocal t_stage, n_bytes
                if allow_pull:
                    # device plane: an arena-resident source pulls
                    # HBM->HBM and skips host transport AND staging;
                    # any planner refusal (spilled, too small, foreign
                    # arena, dtype) silently continues into the host
                    # path below
                    dev = self._plane.try_pull(loc, dtype)
                    if dev is not None:
                        out.setdefault(loc.partition_id, []).append(dev)
                        return
                if loc.manager_id.executor_id == my_id:
                    # local short-circuit straight from the registered
                    # region — DMA'd directly, never copied to bytes.
                    # Resolve up to a full slab class past the block's
                    # start (pooled regions span one, so this usually
                    # covers it) to hit stage_view's compile- and
                    # copy-free branch; only a region tail (mapped-file
                    # chunk) falls back to the host-pad branch.
                    pd = mgr.node.pd
                    avail = (
                        pd.region_length(loc.block.mkey) - loc.block.address
                    )
                    span = min(_size_class(loc.block.length), avail)
                    view = pd.resolve(loc.block.mkey, loc.block.address, span)
                    ts = time.perf_counter()
                    dev = self._dev.stage_view(view, loc.block.length, dtype)
                    t_stage += time.perf_counter() - ts
                    n_bytes += loc.block.length
                    out.setdefault(loc.partition_id, []).append(dev)
                    return
                ch = mgr.get_channel_to(loc.manager_id, purpose="data")
                if mapped_delivery_enabled(conf, ch):
                    pending.append(
                        _start_read_mapped(mgr, arrivals, len(pending), loc, ch)
                    )
                else:
                    reg = mgr.buffer_manager.get(loc.block.length)
                    pending.append(
                        _start_read(mgr, arrivals, len(pending), loc, reg, ch)
                    )

            refetched: set = set()

            def _process_arrival(idx):
                """Consume one posted completion: error gate, checksum
                gate (one same-source refetch), then host->HBM staging.
                Shared by the blocking drain loop below and the
                non-blocking drain the wave pipeline calls between
                entries — passthrough READs stage WHILE waves are in
                flight instead of queueing behind the last one."""
                nonlocal t_stage, n_bytes
                entry = pending[idx]
                if entry is None:
                    return  # duplicate completion post
                loc, obj, done, errbox, _abandon = entry
                if not done.is_set():
                    # stale post from a superseded (refetched) attempt;
                    # the live read posts idx again on completion
                    return
                if errbox:
                    mgr.health.record_failure(loc.manager_id.executor_id)
                    raise FetchFailedError(
                        loc.manager_id, shuffle_id, -1, loc.partition_id,
                        str(errbox[0]),
                    )
                # integrity gate before the expensive host->HBM stage
                if isinstance(obj, dict):
                    d = obj["d"]
                    ck_view = d.views[0] if d.views else b""
                else:
                    ck_view = obj.view[: loc.block.length]
                if not _checksum.verify(
                    ck_view, loc.block.checksum, loc.block.checksum_algo
                ):
                    if isinstance(obj, dict):
                        obj["d"].release()
                    else:
                        mgr.buffer_manager.put(obj)
                    get_registry().counter(
                        "resilience.checksum_failures", role=my_id
                    ).inc()
                    if idx in refetched:
                        mgr.health.record_failure(loc.manager_id.executor_id)
                        raise FetchFailedError(
                            loc.manager_id, shuffle_id, -1, loc.partition_id,
                            "checksum mismatch persisted across refetch",
                        )
                    refetched.add(idx)
                    get_registry().counter(
                        "resilience.retries", role=my_id
                    ).inc()
                    ch = mgr.get_channel_to(loc.manager_id, purpose="data")
                    if isinstance(obj, dict):
                        pending[idx] = _start_read_mapped(mgr, arrivals, idx, loc, ch)
                    else:
                        reg2 = mgr.buffer_manager.get(loc.block.length)
                        pending[idx] = _start_read(mgr, arrivals, idx, loc, reg2, ch)
                    return
                mgr.health.record_success(loc.manager_id.executor_id)
                ts = time.perf_counter()
                if isinstance(obj, dict):
                    # mapped delivery: stage straight from the page-cache
                    # mapping (or fallback blob) — the socket/pread copy
                    # of the buffer path never happened. stage_view
                    # blocks until the device transfer completes, so
                    # releasing the mapping right after is safe.
                    d = obj["d"]
                    view = d.views[0] if d.views else b""
                    dev = self._dev.stage_view(view, loc.block.length, dtype)
                    d.release()
                else:
                    # registered buffer -> HBM directly (one DMA, no pad
                    # program: the pooled source spans a full slab
                    # class); the buffer returns to the pool only after
                    # the transfer, which device_put completes
                    # synchronously for host sources
                    dev = self._dev.stage_view(obj.view, loc.block.length, dtype)
                    mgr.buffer_manager.put(obj)  # pooled reuse, not a cold free
                t_stage += time.perf_counter() - ts
                n_bytes += loc.block.length
                pending[idx] = None
                out.setdefault(loc.partition_id, []).append(dev)

            def _drain_ready():
                # non-blocking: consume whatever already landed, return
                # the moment the queue is dry — never waits on transport
                while True:
                    try:
                        idx = arrivals.get_nowait()
                    except queue.Empty:
                        return
                    _process_arrival(idx)

            for loc in cplan.passthrough:
                _issue(loc)
            # compiled waves run NOW, while the host READs issued above
            # are in flight — DMA epochs overlap host-plane transport,
            # and the drain callback consumes landed READs between
            # pipeline entries (before the waves finish)
            results, degraded = self._collective.execute(
                shuffle_id, cplan, dtype, fused=fused, drain=_drain_ready
            )
            for r in results:
                out.setdefault(r.pid, []).append(r.dev)
            # rows the waves lost (evicted mid-stage, mover surprise)
            # re-issue through the host path: silent, byte-identical
            for loc in degraded:
                _issue(loc, allow_pull=False)

            while any(e is not None for e in pending):
                budget = deadline - time.monotonic()
                tw = time.perf_counter()
                try:
                    if budget > 0:
                        idx = arrivals.get(timeout=budget)
                    else:
                        # the deadline bounds the WAITING, not the
                        # consumption of reads that already landed:
                        # staging time (host->HBM transfers) may have
                        # eaten the budget while completions queued up —
                        # drain those without blocking before failing
                        idx = arrivals.get_nowait()
                except queue.Empty:
                    # the final (possibly full-budget) wait is transport
                    # time too — without this the failure case records
                    # near-zero transport for a fetch that spent its
                    # whole wall waiting on it
                    t_transport += time.perf_counter() - tw
                    # deadline spent with reads still outstanding
                    left = [e for e in pending if e is not None]
                    slow = left[0][0]
                    raise FetchFailedError(
                        slow.manager_id, shuffle_id, -1, slow.partition_id,
                        f"fetch deadline ({timeout_s:.1f}s) exceeded with "
                        f"{len(left)} block(s) outstanding",
                    )
                t_transport += time.perf_counter() - tw
                _process_arrival(idx)
            return out
        except Exception:
            # release everything: staged device slabs are freed here;
            # each unconsumed destination buffer is recycled atomically
            # by whichever side (caller / completion listener) turns out
            # to be its last owner
            for bufs in out.values():
                for dev in bufs:
                    dev.free()
            for entry in pending:
                if entry is None:
                    continue
                entry[4]()  # abandon_or_reclaim
            raise
        finally:
            with self._lock:
                self._fetch_stats["fetch_transport_s"] += t_transport
                self._fetch_stats["fetch_stage_s"] += t_stage
                self._fetch_stats["fetch_bytes"] += n_bytes
            reg = get_registry()
            reg.histogram("device_fetch.transport_ms").observe(t_transport * 1e3)
            reg.histogram("device_fetch.stage_ms").observe(t_stage * 1e3)
            reg.counter("device_fetch.bytes").inc(n_bytes)

    # ------------------------------------------------------------------
    # reduce side, split-phase: the ReduceTaskPipeline's stage bodies
    # (DESIGN.md §16). fetch_host_blocks is transport only; checksum
    # verification moves to verify_host_block (a decode-pool worker) and
    # host->HBM transfer to stage_host_block (the staging thread), so
    # the three overlap across groups instead of serializing per block
    # the way fetch_device_blocks does.
    # ------------------------------------------------------------------
    def fetch_host_blocks(
        self,
        shuffle_id: int,
        start_partition: int,
        end_partition: int,
        timeout_s: Optional[float] = None,
        dtype=np.uint8,
    ) -> Dict[int, List[HostBlock]]:
        """Transport half of a reduce-group fetch: pull every block of
        ``[start, end)`` into host memory and return unverified
        :class:`HostBlock` handles (pid -> blocks, each list in
        completion order). No checksum, no HBM staging — those belong
        to :meth:`verify_host_block` / :meth:`stage_host_block` on
        later pipeline stages. Same single-deadline semantics and
        ownership rules as :meth:`fetch_device_blocks`; the caller owns
        every returned handle (``release()`` in a finally).

        ``dtype`` is the slab type :meth:`stage_host_block` will later
        be asked for: the device-pull planner needs it up front (a
        pulled slab arrives typed), so callers that stage non-uint8
        pass it here too. Blocks the planner claims come back as
        :class:`DevicePulledBlock` entries — already in HBM, flowing
        through the same verify/stage seams."""
        mgr = self._manager
        conf = mgr.conf
        if timeout_s is None:
            timeout_s = conf.fetch_location_timeout_ms / 1000.0
        t_transport = 0.0
        n_bytes = 0
        deadline = time.monotonic() + timeout_s
        future = mgr.fetch_remote_partition_locations(
            shuffle_id, start_partition, end_partition
        )
        tw = time.perf_counter()
        try:
            locations: List[PartitionLocation] = future.result(
                timeout=max(0.0, deadline - time.monotonic())
            )
        except Exception as e:
            raise MetadataFetchFailedError(shuffle_id, start_partition, str(e))
        finally:
            t_transport += time.perf_counter() - tw

        out: Dict[int, List[HostBlock]] = {}
        my_id = mgr.executor_id
        locations = self._apply_merged_plan(locations, my_id)
        # whole-stage compile, UNFUSED: the split-phase pipeline's
        # verify/stage seams are per block, so every wave row comes
        # back as its own DevicePulledBlock
        cplan = self._collective.plan(locations, dtype)
        pending: List[Optional[Tuple]] = []
        arrivals: "queue.Queue[int]" = queue.Queue()
        try:
            def _issue(loc, allow_pull=True):
                nonlocal n_bytes
                if allow_pull:
                    dev = self._plane.try_pull(loc, dtype)
                    if dev is not None:
                        out.setdefault(loc.partition_id, []).append(
                            DevicePulledBlock(shuffle_id, loc, dev)
                        )
                        return
                if loc.manager_id.executor_id == my_id:
                    # local short-circuit: the handle aliases the
                    # publisher's registered span directly (released by
                    # unpublish, so release() is a no-op); span up to a
                    # full slab class for stage_view's copy-free branch
                    pd = mgr.node.pd
                    avail = (
                        pd.region_length(loc.block.mkey) - loc.block.address
                    )
                    span = min(_size_class(loc.block.length), avail)
                    view = pd.resolve(loc.block.mkey, loc.block.address, span)
                    n_bytes += loc.block.length
                    out.setdefault(loc.partition_id, []).append(
                        HostBlock(shuffle_id, loc, view, "local", None)
                    )
                    return
                ch = mgr.get_channel_to(loc.manager_id, purpose="data")
                if mapped_delivery_enabled(conf, ch):
                    pending.append(
                        _start_read_mapped(mgr, arrivals, len(pending), loc, ch)
                    )
                else:
                    reg = mgr.buffer_manager.get(loc.block.length)
                    pending.append(
                        _start_read(mgr, arrivals, len(pending), loc, reg, ch)
                    )

            def _process_arrival(idx):
                """Wrap one landed READ as a HostBlock handle. Shared
                by the blocking drain loop and the wave pipeline's
                between-entry drain (host transport completes while
                DMA waves are still in flight)."""
                nonlocal n_bytes
                entry = pending[idx]
                if entry is None:
                    return  # duplicate completion post
                loc, obj, done, errbox, _abandon = entry
                if not done.is_set():
                    return
                if errbox:
                    mgr.health.record_failure(loc.manager_id.executor_id)
                    raise FetchFailedError(
                        loc.manager_id, shuffle_id, -1, loc.partition_id,
                        str(errbox[0]),
                    )
                mgr.health.record_success(loc.manager_id.executor_id)
                if isinstance(obj, dict):
                    d = obj["d"]
                    view = d.views[0] if d.views else memoryview(b"")
                    hb = HostBlock(shuffle_id, loc, view, "mapped", d.release)
                else:
                    hb = HostBlock(
                        shuffle_id, loc, obj.view, "buffer",
                        lambda o=obj: mgr.buffer_manager.put(o),
                    )
                n_bytes += loc.block.length
                pending[idx] = None
                out.setdefault(loc.partition_id, []).append(hb)

            def _drain_ready():
                while True:
                    try:
                        idx = arrivals.get_nowait()
                    except queue.Empty:
                        return
                    _process_arrival(idx)

            for loc in cplan.passthrough:
                _issue(loc)
            # waves overlap the in-flight host READs issued above; the
            # drain callback consumes landed READs between pipeline
            # entries
            results, degraded = self._collective.execute(
                shuffle_id, cplan, dtype, fused=False, drain=_drain_ready
            )
            for r in results:
                out.setdefault(r.pid, []).append(
                    DevicePulledBlock(shuffle_id, r.locs[0], r.dev)
                )
            for loc in degraded:
                _issue(loc, allow_pull=False)

            while any(e is not None for e in pending):
                budget = deadline - time.monotonic()
                tw = time.perf_counter()
                try:
                    if budget > 0:
                        idx = arrivals.get(timeout=budget)
                    else:
                        idx = arrivals.get_nowait()
                except queue.Empty:
                    t_transport += time.perf_counter() - tw
                    left = [e for e in pending if e is not None]
                    slow = left[0][0]
                    raise FetchFailedError(
                        slow.manager_id, shuffle_id, -1, slow.partition_id,
                        f"fetch deadline ({timeout_s:.1f}s) exceeded with "
                        f"{len(left)} block(s) outstanding",
                    )
                t_transport += time.perf_counter() - tw
                _process_arrival(idx)
            return out
        except Exception:
            for blocks in out.values():
                for hb in blocks:
                    hb.release()
            for entry in pending:
                if entry is None:
                    continue
                entry[4]()  # abandon_or_reclaim
            raise
        finally:
            with self._lock:
                self._fetch_stats["fetch_transport_s"] += t_transport
                self._fetch_stats["fetch_bytes"] += n_bytes
            reg_ = get_registry()
            reg_.histogram("device_fetch.transport_ms").observe(t_transport * 1e3)
            reg_.counter("device_fetch.bytes").inc(n_bytes)

    def _refetch_host_block(self, hb: HostBlock) -> HostBlock:
        """One bounded synchronous re-read of a block whose payload
        failed the decode-stage checksum gate. ``hb`` must already be
        released by the caller."""
        mgr = self._manager
        loc = hb.loc
        if loc.manager_id.executor_id == mgr.executor_id:
            pd = mgr.node.pd
            avail = pd.region_length(loc.block.mkey) - loc.block.address
            span = min(_size_class(loc.block.length), avail)
            view = pd.resolve(loc.block.mkey, loc.block.address, span)
            return HostBlock(hb.shuffle_id, loc, view, "local", None)
        conf = mgr.conf
        timeout_s = conf.fetch_location_timeout_ms / 1000.0
        arrivals: "queue.Queue[int]" = queue.Queue()
        ch = mgr.get_channel_to(loc.manager_id, purpose="data")
        tw = time.perf_counter()
        if mapped_delivery_enabled(conf, ch):
            entry = _start_read_mapped(mgr, arrivals, 0, loc, ch)
        else:
            reg = mgr.buffer_manager.get(loc.block.length)
            entry = _start_read(mgr, arrivals, 0, loc, reg, ch)
        _loc, obj, done, errbox, abandon = entry
        ok = done.wait(timeout_s)
        t = time.perf_counter() - tw
        with self._lock:
            self._fetch_stats["fetch_transport_s"] += t
            if ok and not errbox:
                self._fetch_stats["fetch_bytes"] += loc.block.length
        get_registry().histogram("device_fetch.transport_ms").observe(t * 1e3)
        if not ok:
            abandon()  # read still in flight: listener becomes the owner
            raise FetchFailedError(
                loc.manager_id, hb.shuffle_id, -1, loc.partition_id,
                f"refetch deadline ({timeout_s:.1f}s) exceeded",
            )
        if errbox:
            abandon()  # completed with error: recycles the destination
            mgr.health.record_failure(loc.manager_id.executor_id)
            raise FetchFailedError(
                loc.manager_id, hb.shuffle_id, -1, loc.partition_id,
                str(errbox[0]),
            )
        get_registry().counter("device_fetch.bytes").inc(loc.block.length)
        if isinstance(obj, dict):
            d = obj["d"]
            view = d.views[0] if d.views else memoryview(b"")
            return HostBlock(hb.shuffle_id, loc, view, "mapped", d.release)
        return HostBlock(
            hb.shuffle_id, loc, obj.view, "buffer",
            lambda o=obj: mgr.buffer_manager.put(o),
        )

    def verify_host_block(self, hb: HostBlock) -> HostBlock:
        """Decode-stage integrity gate (runs on a decode-pool worker):
        validate ``hb`` against its published checksum. A mismatch
        earns one synchronous same-source refetch, then
        FetchFailedError — the same ladder as the fused path, moved off
        the transport thread so refetches stall one group's decode, not
        every group's fetch. Returns the verified handle (possibly a
        fresh one; the failed one is released). The ``stage`` fault
        seam (``stage=decode``) fires here, modeling corruption that
        happens AFTER the wire delivered intact bytes."""
        mgr = self._manager
        my_id = mgr.executor_id
        if isinstance(hb, DevicePulledBlock):
            # device path: the checksum was verified at publish on the
            # same staged bytes and the pull is a DMA, not a socket —
            # trusted, no host bytes to gate (DESIGN.md §17)
            return hb
        plan = _faults.active()
        if plan is not None:
            plan.on_stage("decode", [hb.data])
        loc = hb.loc
        if _checksum.verify(hb.data, loc.block.checksum, loc.block.checksum_algo):
            return hb
        hb.release()
        reg_ = get_registry()
        reg_.counter("resilience.checksum_failures", role=my_id).inc()
        reg_.counter("resilience.retries", role=my_id).inc()
        fresh = self._refetch_host_block(hb)
        if _checksum.verify(
            fresh.data, loc.block.checksum, loc.block.checksum_algo
        ):
            mgr.health.record_success(loc.manager_id.executor_id)
            return fresh
        fresh.release()
        reg_.counter("resilience.checksum_failures", role=my_id).inc()
        mgr.health.record_failure(loc.manager_id.executor_id)
        raise FetchFailedError(
            loc.manager_id, hb.shuffle_id, -1, loc.partition_id,
            "checksum mismatch persisted across refetch",
        )

    def stage_host_block(self, hb: HostBlock, dtype=np.uint8) -> DeviceBuffer:
        """Host -> HBM half (runs on the staging thread): transfer a
        verified block into a pooled device slab and release the host
        resource. ``stage_view`` blocks until the device transfer
        completes, so releasing right after is safe. The ``stage``
        fault seam (``stage=stage``) fires before the transfer.

        A :class:`DevicePulledBlock` is already an HBM slab: ownership
        transfers to the caller with no transfer, no release, no fault
        seam (there are no host bytes to corrupt)."""
        if isinstance(hb, DevicePulledBlock):
            return hb.take()
        plan = _faults.active()
        if plan is not None:
            plan.on_stage("stage", [hb.data])
        ts = time.perf_counter()
        try:
            dev = self._dev.stage_view(hb.view, hb.length, dtype)
        finally:
            hb.release()
            t = time.perf_counter() - ts
            with self._lock:
                self._fetch_stats["fetch_stage_s"] += t
            get_registry().histogram("device_fetch.stage_ms").observe(t * 1e3)
        return dev

    # ------------------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Manager counters + the device (HBM) pool's: allocation per
        size class, live budget, and host-tier spill count."""
        snap = self._manager.metrics_snapshot()
        snap["hbm_pool_allocs_by_class"] = {
            str(k): v for k, v in self._dev.stats().items()
        }
        snap["hbm_in_use_bytes"] = self._dev.in_use_bytes
        snap["hbm_spill_count"] = self._dev.spill_count
        snap["hbm_disk_spill_count"] = self._dev.disk_spill_count
        with self._lock:
            snap.update(
                {k: round(v, 3) if isinstance(v, float) else v
                 for k, v in self._fetch_stats.items()}
            )
        return snap

    def unpublish(self, shuffle_id: int) -> None:
        """Release the registered buffers serving a shuffle's blocks,
        and the arena copies the device plane advertised. A puller
        racing this free sees the handle gone (or the slab recycled)
        at its residency re-check and degrades to host fetch — which
        then also finds the host buffer gone only if the whole shuffle
        is being torn down, the pre-existing contract."""
        with self._lock:
            staged = self._published.pop(shuffle_id, [])
            arena = self._arena_published.pop(shuffle_id, [])
        for buf in staged:
            self._manager.buffer_manager.put(buf)
        for abuf in arena:
            abuf.free()

    def stop(self) -> None:
        with self._lock:
            shuffles = set(self._published.keys()) | set(
                self._arena_published.keys()
            )
        for sid in shuffles:
            self.unpublish(sid)
        unregister_arena(self._manager.executor_id, self._dev)
        self._dev.stop()
