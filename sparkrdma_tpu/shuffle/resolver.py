"""TpuShuffleBlockResolver — per-executor shuffle storage registry.

Analogue of RdmaShuffleBlockResolver.scala (reference: /root/reference/
src/main/scala/org/apache/spark/shuffle/rdma/
RdmaShuffleBlockResolver.scala). Semantics preserved:

- maps shuffle_id → ShuffleData, created writer-method-specifically
  (:49-66),
- executor-wide in-memory budget accounting
  ``reserve_inmemory_bytes``/``release_inmemory_bytes`` against
  ``shuffle_write_max_inmemory_per_executor`` (:38-47),
- routes ``write_index_file_and_commit``/``remove_data_by_map``
  (:77-87),
- serves local partitions as input streams (:95-100).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import BinaryIO, Dict, List, Optional

from sparkrdma_tpu.engine.serializer import CompressionCodec
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle
from sparkrdma_tpu.shuffle.writer import ShuffleData
from sparkrdma_tpu.utils.config import ShuffleWriterMethod, TpuShuffleConf


class TpuShuffleBlockResolver:
    def __init__(self, manager):
        self._manager = manager
        self.conf: TpuShuffleConf = manager.conf
        self.codec = CompressionCodec(enabled=True)
        self._data: Dict[int, ShuffleData] = {}
        self._lock = threading.Lock()
        self._inmemory_used = 0
        self._budget = self.conf.shuffle_write_max_inmemory_per_executor
        self._local_dir = tempfile.mkdtemp(prefix=f"tpu-shuffle-{manager.executor_id}-")

    @property
    def pd(self):
        return self._manager.node.pd

    # -- in-memory budget (:38-47) ----------------------------------------
    def reserve_inmemory_bytes(self, n: int) -> bool:
        with self._lock:
            if self._inmemory_used + n > self._budget:
                return False
            self._inmemory_used += n
            return True

    def release_inmemory_bytes(self, n: int) -> None:
        with self._lock:
            self._inmemory_used = max(0, self._inmemory_used - n)

    @property
    def inmemory_used(self) -> int:
        with self._lock:
            return self._inmemory_used

    # -- paths -------------------------------------------------------------
    def data_file_path(self, shuffle_id: int, map_id: int) -> str:
        return os.path.join(self._local_dir, f"shuffle_{shuffle_id}_{map_id}.data")

    def data_tmp_path(self, shuffle_id: int, map_id: int) -> str:
        return os.path.join(self._local_dir, f"shuffle_{shuffle_id}_{map_id}.data.tmp")

    def scratch_path(self, name: str) -> str:
        return os.path.join(self._local_dir, name)

    # -- shuffle data lifecycle (:49-66) -----------------------------------
    def get_or_create_shuffle_data(self, handle: BaseShuffleHandle) -> ShuffleData:
        from sparkrdma_tpu.shuffle.writer.chunked_agg import ChunkedAggShuffleData
        from sparkrdma_tpu.shuffle.writer.wrapper import WrapperShuffleData

        with self._lock:
            data = self._data.get(handle.shuffle_id)
            if data is None:
                if self.conf.shuffle_writer_method == ShuffleWriterMethod.WRAPPER:
                    data = WrapperShuffleData(self, handle.shuffle_id, handle.num_partitions)
                else:
                    data = ChunkedAggShuffleData(
                        self,
                        handle.shuffle_id,
                        handle.num_partitions,
                        num_maps=handle.num_maps,
                    )
                self._data[handle.shuffle_id] = data
            return data

    def get_shuffle_data(self, shuffle_id: int) -> Optional[ShuffleData]:
        with self._lock:
            return self._data.get(shuffle_id)

    def shuffle_ids(self) -> List[int]:
        """Snapshot of the shuffles with live local data (elastic
        layer: the handoff path walks these to build its manifest)."""
        with self._lock:
            return sorted(self._data)

    def get_local_partition_streams(self, shuffle_id: int, partition_id: int) -> List[BinaryIO]:
        data = self.get_shuffle_data(shuffle_id)
        return data.get_input_streams(partition_id) if data is not None else []

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            data = self._data.pop(shuffle_id, None)
        if data is not None:
            data.dispose()

    def stop(self) -> None:
        with self._lock:
            datas = list(self._data.values())
            self._data.clear()
        for d in datas:
            d.dispose()
        shutil.rmtree(self._local_dir, ignore_errors=True)
