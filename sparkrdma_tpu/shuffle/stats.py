"""Opt-in per-remote-endpoint fetch-latency histograms.

Analogue of RdmaShuffleReaderStats.scala (reference: /root/reference/
src/main/scala/org/apache/spark/shuffle/rdma/
RdmaShuffleReaderStats.scala): fixed buckets of
``fetch_time_num_buckets × fetch_time_bucket_size_ms``, printed at
manager stop (:48-75; RdmaShuffleManager.scala:333-335).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List

from sparkrdma_tpu.locations import ShuffleManagerId
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


class RemoteFetchHistogram:
    """Fixed-bucket latency histogram (reference :25-46)."""

    def __init__(self, num_buckets: int, bucket_size_ms: int):
        # clamp degenerate shapes instead of deferring the blow-up to
        # add(): bucket_size_ms <= 0 was a ZeroDivisionError there
        self.num_buckets = max(1, int(num_buckets))
        self.bucket_size_ms = max(1, int(bucket_size_ms))
        self._buckets = [0] * (self.num_buckets + 1)  # +1 overflow bucket
        self._lock = threading.Lock()

    def add(self, latency_ms: float) -> None:
        # negative latencies (clock skew between timers) floor-divide to
        # a negative index — i.e. silently count in the overflow bucket
        # via Python's negative indexing; clamp them into bucket 0
        if latency_ms < 0:
            latency_ms = 0.0
        idx = min(int(latency_ms // self.bucket_size_ms), self.num_buckets)
        with self._lock:
            self._buckets[idx] += 1

    def snapshot(self) -> List[int]:
        with self._lock:
            return list(self._buckets)

    def format(self) -> str:
        parts = []
        buckets = self.snapshot()
        for i, count in enumerate(buckets[:-1]):
            lo = i * self.bucket_size_ms
            hi = (i + 1) * self.bucket_size_ms
            parts.append(f"[{lo}-{hi}ms: {count}]")
        parts.append(f"[>{self.num_buckets * self.bucket_size_ms}ms: {buckets[-1]}]")
        return " ".join(parts)


class ShuffleReaderStats:
    def __init__(self, conf: TpuShuffleConf):
        self._num_buckets = conf.fetch_time_num_buckets
        self._bucket_size_ms = conf.fetch_time_bucket_size_ms
        self._per_remote: Dict[ShuffleManagerId, RemoteFetchHistogram] = {}
        self._lock = threading.Lock()

    def update_remote_fetch_histogram(
        self, remote: ShuffleManagerId, latency_ms: float
    ) -> None:
        with self._lock:
            hist = self._per_remote.get(remote)
            if hist is None:
                hist = RemoteFetchHistogram(self._num_buckets, self._bucket_size_ms)
                self._per_remote[remote] = hist
        hist.add(latency_ms)
        # mirror into the unified registry so snapshots see the same
        # distribution without opting into reader_stats
        get_registry().histogram(
            "reader.remote_fetch_ms", peer=remote.executor_id
        ).observe(latency_ms)

    def snapshot(self) -> Dict[str, List[int]]:
        """Live queryable form of what ``print_stats`` logs at stop:
        remote endpoint -> bucket counts (last bucket = overflow)."""
        with self._lock:
            items = list(self._per_remote.items())
        return {
            f"{mid.executor_id}@{mid.host}:{mid.port}": hist.snapshot()
            for mid, hist in items
        }

    def print_stats(self) -> None:
        with self._lock:
            items = list(self._per_remote.items())
        for remote, hist in items:
            logger.info(
                "fetch latency from %s:%d (%s): %s",
                remote.host,
                remote.port,
                remote.executor_id,
                hist.format(),
            )
