"""TpuShuffleFetcherIterator — the read-path engine.

Analogue of RdmaShuffleFetcherIterator.scala (reference: /root/
reference/src/main/scala/org/apache/spark/shuffle/rdma/
RdmaShuffleFetcherIterator.scala). Semantics preserved:

- async location fetch from the driver for ``[start, end)`` with a
  timeout wrapper (:108-122, 220-320),
- local partitions short-circuit to streams, never looping through the
  network (:328-339; SURVEY.md §5.1 #2),
- remote blocks are grouped **per source manager** into
  ``AggregatedPartitionGroup``s capped at ``shuffle_read_block_size``
  (:252-275),
- one one-sided READ per group pulls all its blocks into one pooled
  registered buffer, sliced per block (:132-218),
- ``max_bytes_in_flight`` throttle with a pending-fetch queue drained
  as results are consumed (:279-284, 369-379),
- the blocking results queue carries Success/Failure/FailureMetadata
  and a sentinel "+1 block" protocol keeps ``has_next`` truthful until
  all fetches are enqueued (:47-50, 124-130, 288, 434-448),
- failures walk the resilience retry ladder BEFORE surfacing
  (docs/RESILIENCE.md): retry the same source with backoff, re-resolve
  locations from the driver (stale mkeys / respawned writers), split
  the aggregated group into per-block fetches — and only after
  exhaustion (or an open circuit breaker, or a blown deadline) raise
  FetchFailedError / MetadataFetchFailedError for stage recompute
  (:203, 381-391 — the reference's ONLY move, now the last resort),
- delivered blocks are validated against their published checksum; a
  mismatch is a retryable fault like any other READ failure,
- streams release their registered buffer slice on close
  (BufferReleasingInputStream, :399-429),
- per-fetch latency histogram hook (:186-189).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, List, Optional, Tuple

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.tenancy import quota as _tquota
from sparkrdma_tpu.locations import BlockLocation, PartitionLocation, ShuffleManagerId
from sparkrdma_tpu.memory.registered_buffer import RegisteredBuffer
from sparkrdma_tpu.memory.streams import MemoryviewInputStream
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs import now as obs_now
from sparkrdma_tpu.resilience import CircuitOpenError, RetryPolicy
from sparkrdma_tpu.shuffle import merge as _merge
from sparkrdma_tpu.shuffle.errors import (
    ChecksumError,
    FetchFailedError,
    MetadataFetchFailedError,
)
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.transport import FnListener, mapped_delivery_enabled
from sparkrdma_tpu.utils import checksum as _checksum

logger = logging.getLogger(__name__)


@dataclass
class ShuffleMetrics:
    """TaskMetrics stand-in (reference Spark metrics integration)."""

    local_blocks: int = 0
    remote_blocks: int = 0
    local_bytes: int = 0
    remote_bytes: int = 0
    fetch_wait_ms: float = 0.0
    records_read: int = 0
    sort_spills: int = 0  # external-sorter runs spilled to scratch
    merged_blocks: int = 0  # merged segments read in place of originals


@dataclass
class AggregatedPartitionGroup:
    """Blocks from one source manager read in one one-sided READ (:71-74).

    ``fallbacks`` rides only on groups carrying a MERGED segment
    (shuffle/merge.py): the partition's suppressed original locations,
    re-issued by ``_fallback_refetch`` if the merged read fails."""

    total_length: int = 0
    blocks: List[Tuple[int, BlockLocation]] = field(default_factory=list)  # (pid, loc)
    fallbacks: Dict[int, List[PartitionLocation]] = field(default_factory=dict)


@dataclass
class _Success:
    streams: List[Tuple[int, BinaryIO]]  # (partition_id, stream)
    in_flight: int = 0


@dataclass
class _Failure:
    manager_id: Optional[ShuffleManagerId]
    partition_id: int
    error: Exception
    in_flight: int = 0


class _Dummy:
    in_flight = 0


@dataclass
class _PendingFetch:
    """One group READ plus its position on the retry ladder.

    ``attempt`` is the next attempt number to issue (0 = initial);
    ``deadline`` is the group's wall budget across ALL its retries
    (monotonic seconds; +inf when resilience.fetchDeadlineMs is 0).
    """

    manager_id: ShuffleManagerId
    group: AggregatedPartitionGroup
    attempt: int = 0
    deadline: float = float("inf")


class TpuShuffleFetcherIterator:
    """Iterator of (partition_id, stream) over local + remote blocks."""

    def __init__(self, manager, handle, start_partition: int, end_partition: int):
        self._manager = manager
        self._handle = handle
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.metrics = ShuffleMetrics()

        # registry mirrors of ShuffleMetrics, pre-resolved per iterator
        role = manager.executor_id
        reg = get_registry()
        self._m_local_blocks = reg.counter("reader.local_blocks", role=role)
        self._m_local_bytes = reg.counter("reader.local_bytes", role=role)
        self._m_remote_blocks = reg.counter("reader.remote_blocks", role=role)
        self._m_remote_bytes = reg.counter("reader.remote_bytes", role=role)
        self._m_fetch_wait_ms = reg.counter("reader.fetch_wait_ms", role=role)
        self._h_fetch_ms = reg.histogram("reader.fetch_ms", role=role)

        # resilience: retry policy, per-peer circuit breakers (shared
        # with the manager), and the resilience.* counter family
        self._retry_policy = RetryPolicy.from_conf(manager.conf)
        self._health = manager.health
        # captured once: breaker calls and retries land on completion
        # and timer threads that carry no tenant scope of their own
        self._tenant = tenancy.current_tenant()
        self._m_retries = reg.counter("resilience.retries", role=role)
        self._m_checksum_failures = reg.counter(
            "resilience.checksum_failures", role=role
        )
        self._m_failovers = reg.counter("resilience.failovers", role=role)
        self._m_splits = reg.counter("resilience.splits", role=role)
        self._m_fail_fast = reg.counter("resilience.circuit_fail_fast", role=role)
        # push/merge plane: merged segments chosen over originals, and
        # merged reads that degraded back to the originals
        self._m_merged_reads = reg.counter("reader.merged_reads", role=role)
        self._m_merged_fallbacks = reg.counter("push.fallbacks", role=role)

        self._results: "queue.Queue" = queue.Queue()
        # hot: in-flight accounting and pending-queue bookkeeping only
        self._lock = named_lock("fetcher.state", hot=True)
        # sentinel "+1": keeps has_next true until enumeration completes
        self._total_results = 1
        self._processed_results = 0
        self._bytes_in_flight = 0
        self._pending: List[_PendingFetch] = []
        self._buffered: List[Tuple[int, BinaryIO]] = []
        self._closed = False

        self._start()

    # ------------------------------------------------------------------
    def _start(self) -> None:
        # the resolver thread allocates destination buffers and posts
        # the initial READs: run it under the owning tenant's scope so
        # quota charges and fault/breaker attribution stay correct
        threading.Thread(
            target=tenancy.scoped(self._tenant, self._resolve_and_fetch),
            name="fetcher-locations",
            daemon=True,
        ).start()

    def _resolve_and_fetch(self) -> None:
        """Async location resolution + group construction (:220-320)."""
        t0 = time.monotonic()
        future = self._manager.fetch_remote_partition_locations(
            self._handle.shuffle_id, self.start_partition, self.end_partition
        )
        try:
            locations: List[PartitionLocation] = future.result(
                timeout=self._manager.conf.fetch_location_timeout_ms / 1000.0
            )
        except Exception as e:
            self._results.put(
                _Failure(
                    None,
                    self.start_partition,
                    MetadataFetchFailedError(
                        self._handle.shuffle_id, self.start_partition, str(e)
                    ),
                )
            )
            return
        logger.debug(
            "fetched %d locations in %.1f ms",
            len(locations),
            (time.monotonic() - t0) * 1e3,
        )

        # merged-else-original (shuffle/merge.py): a partition whose
        # merged segment covers ALL its originals is read as ONE
        # sequential block; its originals stay attached as fallbacks
        my_id = self._manager.executor_id
        locations, merged_fallbacks = _merge.plan_reads(locations)
        if merged_fallbacks:
            self._m_merged_reads.inc(len(merged_fallbacks))
            self.metrics.merged_blocks += len(merged_fallbacks)
        merged_local = [
            loc
            for loc in locations
            if loc.block.merged_cover and loc.manager_id.executor_id == my_id
        ]
        if merged_local:
            locations = [loc for loc in locations if loc not in merged_local]
        for loc in merged_local:
            streams = self._read_local_merged(loc)
            if streams is None:
                # local merged segment unusable: restore the originals
                # into the ordinary plan (locals short-circuit below)
                locations.extend(merged_fallbacks.pop(loc.partition_id, ()))
                continue
            self.metrics.local_blocks += 1
            self.metrics.local_bytes += loc.block.length
            self._m_local_blocks.inc()
            self._m_local_bytes.inc(loc.block.length)
            with self._lock:
                self._total_results += 1
            self._put_success(streams, 0)

        # Local partitions short-circuit to streams (:328-339) — served
        # HERE, after the driver's barrier-gated reply, not at iterator
        # construction: a snapshot taken earlier would race local map
        # tasks that finish after the reader starts and silently drop
        # their records. The reply is complete by construction, so the
        # resolver now holds every local block the reply names.
        #
        # Replica blocks this executor HOLDS (promoted by the driver
        # after their source died) are excluded from the pid set: the
        # resolver's local streams cover only this executor's own
        # committed map outputs, while replica bytes live in the
        # ReplicaStore's registered segment — they are served by direct
        # resolve below, never by the stream short-circuit.
        my_id = self._manager.executor_id
        resolver = self._manager.resolver
        local_pids = sorted(
            {
                loc.partition_id
                for loc in locations
                if loc.manager_id.executor_id == my_id
                and not loc.block.replica_of
            }
        )
        local_streams: List[Tuple[int, BinaryIO]] = []
        for pid in local_pids:
            for stream in resolver.get_local_partition_streams(
                self._handle.shuffle_id, pid
            ):
                local_streams.append((pid, stream))
                self.metrics.local_blocks += 1
        # local bytes from the published block lengths (the streams
        # themselves are opaque); mirrors remote_bytes accounting
        local_bytes = sum(
            loc.block.length
            for loc in locations
            if loc.manager_id.executor_id == my_id
            and not loc.block.replica_of
        )
        unreadable_replicas: List[PartitionLocation] = []
        for loc in locations:
            if loc.manager_id.executor_id != my_id or not loc.block.replica_of:
                continue
            streams = self._read_local_replica(loc)
            if streams is None:
                # segment gone (store teardown race): let the remote
                # ladder re-resolve and fail over to another holder
                unreadable_replicas.append(loc)
                continue
            local_streams.extend(streams)
            local_bytes += loc.block.length
            self.metrics.local_blocks += 1
        self.metrics.local_bytes += local_bytes
        self._m_local_blocks.inc(len(local_streams))
        self._m_local_bytes.inc(local_bytes)
        if local_streams:
            with self._lock:
                self._total_results += 1
            # via _put_success: a close() racing this thread must sweep
            # (or be handed) these streams, never strand them
            self._put_success(local_streams, 0)

        by_manager: Dict[ShuffleManagerId, List[Tuple[int, BlockLocation]]] = {}
        for loc in locations:
            if loc.manager_id.executor_id == my_id:
                if loc not in unreadable_replicas:
                    continue  # served locally above
            by_manager.setdefault(loc.manager_id, []).append((loc.partition_id, loc.block))

        # pack per-manager groups ≤ read_block_size (:252-275)
        read_block_size = self._manager.conf.shuffle_read_block_size
        deadline = time.monotonic() + self._retry_policy.deadline_s()
        fetches: List[_PendingFetch] = []
        for mid, blocks in by_manager.items():
            group = AggregatedPartitionGroup()
            for pid, block in blocks:
                if group.blocks and group.total_length + block.length > read_block_size:
                    fetches.append(_PendingFetch(mid, group, deadline=deadline))
                    group = AggregatedPartitionGroup()
                group.blocks.append((pid, block))
                group.total_length += block.length
                if block.merged_cover and pid in merged_fallbacks:
                    group.fallbacks[pid] = merged_fallbacks[pid]
            if group.blocks:
                fetches.append(_PendingFetch(mid, group, deadline=deadline))

        max_in_flight = self._manager.conf.max_bytes_in_flight
        start_now: List[_PendingFetch] = []
        with self._lock:
            self._total_results += len(fetches)
            if self._closed:
                # closed while resolving: never launch READs for a
                # dead task (accounting is moot — has_next is False)
                fetches = []
            for fetch in fetches:
                if self._bytes_in_flight < max_in_flight:
                    self._bytes_in_flight += fetch.group.total_length
                    start_now.append(fetch)
                else:
                    self._pending.append(fetch)
        # resolve the sentinel now that enumeration is complete (:124-130)
        self._results.put(_Dummy())
        for fetch in start_now:
            self._fetch_blocks(fetch)

    def _group_failure(self, fetch: _PendingFetch, cleanup=None):
        """Once-only failure handler for one group READ attempt
        (on_failure may legally fire more than once; ``cleanup``
        releases the attempt's destination resources, if any). The
        failure enters the retry ladder instead of surfacing directly."""
        failed_once = threading.Event()

        def on_failure(e: Exception) -> None:
            if failed_once.is_set():
                return
            failed_once.set()
            if cleanup is not None:
                cleanup()
            self._retry_or_fail(fetch, e)

        return on_failure

    # ------------------------------------------------------------------
    # resilience: the retry ladder (docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def _surface_failure(self, fetch: _PendingFetch, error: Exception) -> None:
        self._results.put(
            _Failure(
                fetch.manager_id,
                fetch.group.blocks[0][0],
                error,
                in_flight=fetch.group.total_length,
            )
        )

    def _retry_or_fail(self, fetch: _PendingFetch, error: Exception) -> None:
        """One attempt failed: schedule the next ladder rung, or give up.

        Gives up — surfacing _Failure for FetchFailedError / stage
        recompute — when the policy's attempts are exhausted, the
        group's wall deadline has passed, the error is non-retryable
        (an open circuit IS the fail-fast decision), or the iterator
        closed. Otherwise the retry is scheduled on a timer after the
        policy's deterministic backoff; no completion thread sleeps.
        """
        if fetch.group.fallbacks:
            # a merged-segment group: never walk the ladder — the
            # merged-else-original contract's else branch re-issues the
            # partition's original locations immediately
            self._fallback_refetch(fetch, error)
            return
        mid, group = fetch.manager_id, fetch.group
        failed_attempt = fetch.attempt
        retryable = not isinstance(error, CircuitOpenError)
        if retryable:
            self._health.record_failure(mid.executor_id, tenant=self._tenant)
        with self._lock:
            closed = self._closed
        if (
            not retryable
            or closed
            or not self._retry_policy.allows(failed_attempt + 1)
            or time.monotonic() >= fetch.deadline
        ):
            self._surface_failure(fetch, error)
            return
        fetch.attempt = failed_attempt + 1
        self._m_retries.inc()
        delay = self._retry_policy.backoff_s(
            failed_attempt,
            self._handle.shuffle_id,
            mid.executor_id,
            group.blocks[0][0],
        )
        logger.info(
            "fetch group from %s failed (attempt %d: %s); retrying in %.0f ms",
            mid.executor_id,
            failed_attempt,
            error,
            delay * 1e3,
        )
        t = threading.Timer(delay, self._retry_fetch, args=(fetch,))
        t.daemon = True
        t.start()

    def _retry_fetch(self, fetch: _PendingFetch) -> None:
        """Issue the next rung: 1 = same source, 2 = re-resolve and
        failover, 3+ = split the group into per-block fetches.

        Runs on a bare timer thread: re-enter the owning tenant's
        scope so re-issued IO (fault plans, quota charges, downstream
        allocations) stays attributed to the tenant that started it."""
        with self._lock:
            if self._closed:
                return  # dead task; the attempt holds no resources
        with tenancy.tenant_scope(self._tenant):
            if fetch.attempt >= 3 and len(fetch.group.blocks) > 1:
                self._split_and_refetch(fetch)
            elif fetch.attempt >= 2:
                self._failover_refetch(fetch)
            else:
                self._fetch_blocks(fetch)

    def _failover_refetch(self, fetch: _PendingFetch) -> None:
        """Re-resolve locations from the driver and re-aim the group.

        Handles stale mkeys and respawned writers: a re-published block
        of the same (partition, length) on the same executor identity
        replaces the stale handle, and the fresh ShuffleManagerId
        carries the respawned endpoint's host:port. Blocks never
        migrate across executor identities without a stage recompute,
        so matching stays within ``mid.executor_id`` — the one sanctioned
        exception is a location whose ``replica_of`` IS that identity
        (elastic replication / service handoff): that block is a
        byte-identical copy of the same map output published under a
        surviving holder, so failing over to it is still an
        identity-preserving retarget. Primaries outrank replicas when
        both are live. Runs on a retry timer thread, so blocking on the
        location future is fine."""
        mid, group = fetch.manager_id, fetch.group
        try:
            future = self._manager.fetch_remote_partition_locations(
                self._handle.shuffle_id, self.start_partition, self.end_partition
            )
            fresh: List[PartitionLocation] = future.result(
                timeout=self._manager.conf.fetch_location_timeout_ms / 1000.0
            )
        except Exception as e:
            logger.warning(
                "failover re-resolve failed (%s); retrying stale locations", e
            )
            self._fetch_blocks(fetch)
            return
        self._m_failovers.inc()
        pool: Dict[Tuple[int, int], List[PartitionLocation]] = {}
        replicas: List[PartitionLocation] = []
        for loc in fresh:
            if loc.manager_id.executor_id != mid.executor_id:
                if loc.block.replica_of == mid.executor_id:
                    replicas.append(loc)
                continue
            pool.setdefault((loc.partition_id, loc.block.length), []).append(loc)
        for loc in replicas:  # appended after ALL primaries: lower rank
            pool.setdefault((loc.partition_id, loc.block.length), []).append(loc)
        new_mid = mid
        new_blocks: List[Tuple[int, BlockLocation]] = []
        for pid, block in group.blocks:
            cands = pool.get((pid, block.length), [])
            # prefer the exact published handle (unchanged block); else
            # any re-published sibling of the same length
            pick = next((loc for loc in cands if loc.block == block), None)
            if pick is None and cands:
                pick = cands[0]
            if pick is not None:
                cands.remove(pick)
                block = pick.block
                new_mid = pick.manager_id
            new_blocks.append((pid, block))
        fetch.manager_id = new_mid
        fetch.group = AggregatedPartitionGroup(
            total_length=group.total_length, blocks=new_blocks
        )
        self._fetch_blocks(fetch)

    def _split_and_refetch(self, fetch: _PendingFetch) -> None:
        """Break the aggregated group into single-block fetches so one
        poisoned block no longer fails its groupmates. Each sub-fetch
        keeps the parent's attempt number and deadline; the result
        accounting grows by k-1 (each sub-result carries its own
        in_flight share, summing to the parent's)."""
        mid, group = fetch.manager_id, fetch.group
        subs = [
            _PendingFetch(
                mid,
                AggregatedPartitionGroup(
                    total_length=block.length, blocks=[(pid, block)]
                ),
                attempt=fetch.attempt,
                deadline=fetch.deadline,
            )
            for pid, block in group.blocks
        ]
        with self._lock:
            if self._closed:
                return
            self._total_results += len(subs) - 1
        self._m_splits.inc()
        logger.info(
            "splitting %d-block group from %s for per-block retry",
            len(subs),
            mid.executor_id,
        )
        for sub in subs:
            self._fetch_blocks(sub)

    def _read_local_merged(self, loc: PartitionLocation):
        """Serve a merged segment sealed on THIS executor: resolve the
        registered bytes directly — and verify the publish-time
        checksum HERE, because the local path bypasses the remote
        READ's checksum gate and a corrupted merged segment must fall
        back to the originals, never reach the deserializer. Returns
        the (pid, stream) list, or None to fall back."""
        block = loc.block
        try:
            view = self._manager.node.pd.resolve(
                block.mkey, block.address, block.length
            )
            if not _checksum.verify(view, block.checksum, block.checksum_algo):
                raise ChecksumError(
                    self._handle.shuffle_id,
                    loc.partition_id,
                    f"merged segment of {block.length} bytes (local)",
                )
        except Exception as e:
            self._m_checksum_failures.inc()
            self._m_merged_fallbacks.inc()
            logger.warning(
                "local merged segment for pid %d unusable (%s); "
                "falling back to originals",
                loc.partition_id,
                e,
            )
            return None
        return [(loc.partition_id, MemoryviewInputStream(view))]

    def _read_local_replica(self, loc: PartitionLocation):
        """Serve a promoted replica block held by THIS executor. Its
        bytes sit in the local ReplicaStore's registered segment, which
        the resolver's local-stream path (own map outputs only) cannot
        see — resolve the registered memory directly, with the same
        local checksum gate as ``_read_local_merged``. Returns the
        (pid, stream) list, or None to route through the remote ladder."""
        block = loc.block
        try:
            view = self._manager.node.pd.resolve(
                block.mkey, block.address, block.length
            )
            if not _checksum.verify(view, block.checksum, block.checksum_algo):
                raise ChecksumError(
                    self._handle.shuffle_id,
                    loc.partition_id,
                    f"replica block of {block.length} bytes (local)",
                )
        except Exception as e:
            self._m_checksum_failures.inc()
            logger.warning(
                "local replica block for pid %d unusable (%s); "
                "routing through remote refetch",
                loc.partition_id,
                e,
            )
            return None
        return [(loc.partition_id, MemoryviewInputStream(view))]

    def _fallback_refetch(self, fetch: _PendingFetch, error: Exception) -> None:
        """A merged-segment read failed (checksum mismatch, dead peer,
        dropped buffer): re-issue the partitions' ORIGINAL per-map
        locations, kept attached as the group's fallbacks — the
        merged-else-original contract's else branch. Accounting mirrors
        ``_split_and_refetch``: the parent result slot is replaced by
        the replacements' and their in_flight shares sum to the
        parent's total (a merged segment's length equals the sum of
        its originals')."""
        group = fetch.group
        self._m_merged_fallbacks.inc()
        logger.info(
            "merged read from %s failed (%s); falling back to originals "
            "for %d partition(s)",
            fetch.manager_id.executor_id,
            error,
            len(group.fallbacks),
        )
        my_id = self._manager.executor_id
        resolver = self._manager.resolver
        local_streams: List[Tuple[int, BinaryIO]] = []
        served_local = set()
        by_manager: Dict[ShuffleManagerId, List[Tuple[int, BlockLocation]]] = {}
        for pid, block in group.blocks:
            originals = group.fallbacks.get(pid) if block.merged_cover else None
            if originals is None:
                # non-merged groupmate: re-fetch as-is from the source
                by_manager.setdefault(fetch.manager_id, []).append((pid, block))
                continue
            for loc in originals:
                if loc.manager_id.executor_id == my_id:
                    if pid not in served_local:
                        served_local.add(pid)
                        for stream in resolver.get_local_partition_streams(
                            self._handle.shuffle_id, pid
                        ):
                            local_streams.append((pid, stream))
                else:
                    by_manager.setdefault(loc.manager_id, []).append(
                        (pid, loc.block)
                    )
        read_block_size = self._manager.conf.shuffle_read_block_size
        subs: List[_PendingFetch] = []
        for mid, blocks in by_manager.items():
            g = AggregatedPartitionGroup()
            for pid, block in blocks:
                if g.blocks and g.total_length + block.length > read_block_size:
                    subs.append(_PendingFetch(mid, g, deadline=fetch.deadline))
                    g = AggregatedPartitionGroup()
                g.blocks.append((pid, block))
                g.total_length += block.length
            if g.blocks:
                subs.append(_PendingFetch(mid, g, deadline=fetch.deadline))
        remote_sum = sum(s.group.total_length for s in subs)
        local_share = max(0, group.total_length - remote_sum)
        put_local = bool(local_streams) or local_share > 0 or not subs
        n_new = len(subs) + (1 if put_local else 0)
        with self._lock:
            closed = self._closed
            if not closed:
                self._total_results += n_new - 1
        if closed:
            for _pid, stream in local_streams:
                try:
                    stream.close()
                except Exception:
                    logger.exception("closing fallback stream failed")
            return
        if put_local:
            self.metrics.local_blocks += len(local_streams)
            self.metrics.local_bytes += local_share
            self._m_local_blocks.inc(len(local_streams))
            self._m_local_bytes.inc(local_share)
            self._put_success(local_streams, local_share)
        for sub in subs:
            self._fetch_blocks(sub)

    def _bad_block(self, group: AggregatedPartitionGroup, views) -> Optional[int]:
        """Index of the first checksum-mismatched block, else None."""
        plan = _faults.active()
        if plan is not None:
            # block-format seam: the plan may flip a byte inside a landed
            # columnar frame's header span — BEFORE the verify loop below
            plan.on_block(views)
        for i, ((_pid, block), view) in enumerate(zip(group.blocks, views)):
            if not _checksum.verify(view, block.checksum, block.checksum_algo):
                return i
        return None

    def _deliver_group(self, mid, group, streams, t0) -> None:
        """Shared success epilogue: histogram, metrics, closed-aware
        enqueue — ONE definition for both delivery flavors."""
        t1 = obs_now()
        latency_ms = (t1 - t0) * 1e3
        stats = self._manager.reader_stats
        if stats is not None:
            stats.update_remote_fetch_histogram(mid, latency_ms)
        self.metrics.remote_blocks += len(streams)
        self.metrics.remote_bytes += group.total_length
        self._m_remote_blocks.inc(len(streams))
        self._m_remote_bytes.inc(group.total_length)
        self._h_fetch_ms.observe(latency_ms)
        # fetch span: the trace id arrived with the location reply, so
        # the binding is resolvable by now; it causally follows the
        # driver resolve span whose reply named these locations
        fsp = self._manager.tracer.record(
            "shuffle.fetch",
            t0,
            t1,
            shuffle_id=self._handle.shuffle_id,
            follows=self._manager.resolve_origin(
                self._handle.shuffle_id, self.start_partition
            ),
            peer=mid.executor_id,
            bytes=group.total_length,
            blocks=len(streams),
        )
        # native submission plane: drain the node's read-completion
        # timestamp ring into transport.native_read spans, so the
        # submit→complete interval inside this fetch window is traced
        # (host-read attribution, obs/attr.py)
        drain = getattr(getattr(self._manager, "node", None),
                        "drain_read_ring", None)
        if drain is not None:
            for rt0, rt1, nbytes in drain():
                self._manager.tracer.record(
                    "transport.native_read",
                    rt0,
                    rt1,
                    shuffle_id=self._handle.shuffle_id,
                    follows=fsp,
                    bytes=nbytes,
                )
        self._put_success(streams, group.total_length)

    def _fetch_blocks(self, fetch: _PendingFetch) -> None:
        """Issue one one-sided READ attempt for a group (:132-218)."""
        mid, group = fetch.manager_id, fetch.group
        if not self._health.allow(mid.executor_id, tenant=self._tenant):
            # open circuit: no READ, no retry ladder — the breaker IS
            # the fail-fast decision for a peer presumed dead, so this
            # surfaces immediately as a FetchFailedError / recompute
            self._m_fail_fast.inc()
            err = CircuitOpenError(
                f"circuit to {mid.executor_id} is open (peer unhealthy)"
            )
            if group.fallbacks:
                # merged segment behind an open circuit: its originals
                # (on other, possibly healthy peers) are the answer
                self._fallback_refetch(fetch, err)
                return
            self._surface_failure(fetch, err)
            return
        t0 = obs_now()
        try:
            # bulk READ payloads ride the data-flavor channel so an 8 MiB
            # in-flight group never head-of-line blocks a location fetch
            # on the rpc channel (RdmaChannel.java:110-154)
            channel = self._manager.get_channel_to(mid, purpose="data")
            if mapped_delivery_enabled(self._manager.conf, channel):
                self._fetch_blocks_mapped(fetch, channel, t0)
                return
            reg = RegisteredBuffer(self._manager.buffer_manager, group.total_length)
            # each slice holds one refcount; buffer returns to the pool
            # when the last stream closes (:399-429)
            slices = [reg.slice(block.length) for _, block in group.blocks]
        except Exception as e:
            # connect/allocation failures walk the same ladder as READ
            # completions: a refused connection to a restarting peer is
            # exactly what same-source retry + failover exist for
            self._retry_or_fail(fetch, e)
            return

        fail = self._group_failure(
            fetch, cleanup=lambda: [sl.release() for sl in slices]
        )

        def on_success(_) -> None:
            bad = self._bad_block(group, [sl.view for sl in slices])
            if bad is not None:
                pid, block = group.blocks[bad]
                self._m_checksum_failures.inc()
                fail(
                    ChecksumError(
                        self._handle.shuffle_id,
                        pid,
                        f"block of {block.length} bytes from {mid.executor_id}",
                    )
                )
                return
            self._health.record_success(mid.executor_id, tenant=self._tenant)
            streams: List[Tuple[int, BinaryIO]] = [
                (pid, MemoryviewInputStream(sl.view, on_close=sl.release))
                for (pid, _block), sl in zip(group.blocks, slices)
            ]
            self._deliver_group(mid, group, streams, t0)

        channel.read_in_queue(
            FnListener(on_success, fail),
            [sl.view for sl in slices],
            [(block.mkey, block.address, block.length) for _, block in group.blocks],
        )

    def _fetch_blocks_mapped(self, fetch: _PendingFetch, channel, t0) -> None:
        """Mapped-delivery flavor of the group READ (native transport):
        no pooled destination buffer — same-host blocks stream straight
        from page-cache mappings, remote ones from one malloc'd blob.
        The delivery releases when the LAST of its block streams
        closes, exactly like the registered buffer's refcounted
        slices (:399-429).

        Mapped bytes never touch the mempool, so the tenant's quota
        ledger would be blind to them: the group's length is charged
        against the ``pagecache`` broker through the submission plane's
        single charge seam (``tenancy.quota.charge_pagecache``,
        DESIGN.md §24) for exactly the life of the delivery (released
        once — on failure cleanup or when the last stream closes)."""
        mid, group = fetch.manager_id, fetch.group
        release_charge = _tquota.charge_pagecache(
            self._tenant, group.total_length
        )

        fail = self._group_failure(fetch, cleanup=release_charge)

        def on_success(delivery) -> None:
            bad = self._bad_block(group, delivery.views)
            if bad is not None:
                pid, block = group.blocks[bad]
                self._m_checksum_failures.inc()
                delivery.release()
                fail(
                    ChecksumError(
                        self._handle.shuffle_id,
                        pid,
                        f"block of {block.length} bytes from {mid.executor_id}",
                    )
                )
                return
            self._health.record_success(mid.executor_id, tenant=self._tenant)
            remaining = [len(delivery.views)]
            lock = named_lock("fetcher.mapped_release", allow_self_nest=True)

            def release_one() -> None:
                with lock:
                    remaining[0] -= 1
                    last = remaining[0] == 0
                if last:
                    delivery.release()
                    release_charge()

            streams: List[Tuple[int, BinaryIO]] = [
                (pid, MemoryviewInputStream(view, on_close=release_one))
                for (pid, _block), view in zip(group.blocks, delivery.views)
            ]
            self._deliver_group(mid, group, streams, t0)

        channel.read_mapped_in_queue(
            FnListener(on_success, fail),
            [(block.mkey, block.address, block.length)
             for _, block in group.blocks],
        )

    # ------------------------------------------------------------------
    def _put_success(self, streams, in_flight: int) -> None:
        """Enqueue delivered streams — unless the iterator has been
        closed, in which case the delivery's resources (registered
        slices or mapped page-cache windows) are released RIGHT HERE:
        a late arrival must never wait for the garbage collector."""
        with self._lock:
            # the put happens INSIDE the closed-flag lock: a put racing
            # close() must either land before the drain (swept there)
            # or observe _closed and release here — never fall between
            if not self._closed:
                self._results.put(_Success(streams, in_flight=in_flight))
                return
        for _pid, stream in streams:
            try:
                stream.close()
            except Exception:
                logger.exception("closing late-delivered stream failed")

    def close(self) -> None:
        """Release every delivered-but-unconsumed stream: buffered ones
        and results still queued; in-flight deliveries release on
        arrival via `_put_success`. The reference runs the same sweep
        as a task-completion callback
        (RdmaShuffleFetcherIterator.scala:90-106). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._pending.clear()  # never launch new READs for a dead task
        leftovers = list(self._buffered)
        self._buffered.clear()
        while True:
            try:
                r = self._results.get_nowait()
            except queue.Empty:
                break
            if isinstance(r, _Success):
                leftovers.extend(r.streams)
        for _pid, stream in leftovers:
            try:
                stream.close()
            except Exception:
                logger.exception("closing unconsumed stream failed")
        # wake a next() blocked on the results queue (the pipelined
        # reader's fetch thread waits there while ANOTHER thread closes;
        # the serial path always closed from the consuming thread): the
        # dummy makes it re-check has_next, now False. Posted AFTER the
        # sweep so the sweep can't consume it; if nothing is waiting it
        # sits in the dead queue — later next() calls see has_next
        # False before ever blocking.
        self._results.put(_Dummy())

    def _drain_pending(self) -> None:
        """Start queued fetches now under the in-flight cap (:369-379)."""
        max_in_flight = self._manager.conf.max_bytes_in_flight
        start_now: List[_PendingFetch] = []
        with self._lock:
            while self._pending and self._bytes_in_flight < max_in_flight:
                fetch = self._pending.pop(0)
                self._bytes_in_flight += fetch.group.total_length
                start_now.append(fetch)
        # runs on a completion-callback thread with no scope of its own
        with tenancy.tenant_scope(self._tenant):
            for fetch in start_now:
                self._fetch_blocks(fetch)

    def has_next(self) -> bool:
        if self._buffered:
            return True
        with self._lock:
            # a closed iterator is exhausted: pending fetches were
            # dropped and late deliveries release without enqueueing,
            # so waiting on the result count would hang forever
            if self._closed:
                return False
            return self._processed_results < self._total_results

    def next(self) -> Tuple[int, BinaryIO]:
        while not self._buffered:
            if not self.has_next():
                raise StopIteration
            t0 = time.monotonic()
            result = self._results.get()
            waited_ms = (time.monotonic() - t0) * 1e3
            self.metrics.fetch_wait_ms += waited_ms
            self._m_fetch_wait_ms.inc(waited_ms)
            with self._lock:
                self._processed_results += 1
                self._bytes_in_flight -= result.in_flight
            if isinstance(result, _Failure):
                # the task will abandon this iterator: sweep every
                # already-delivered stream (and drop queued pending
                # fetches — launching fresh READs for a dead task,
                # which the pre-close drain did, is pure waste) before
                # surfacing the error
                self.close()
                err = result.error
                if isinstance(err, (FetchFailedError, MetadataFetchFailedError)):
                    raise err
                raise FetchFailedError(
                    result.manager_id,
                    self._handle.shuffle_id,
                    -1,
                    result.partition_id,
                    str(err),
                )
            # only successful progress starts the next queued fetches
            self._drain_pending()
            if isinstance(result, _Success):
                self._buffered.extend(result.streams)
        return self._buffered.pop(0)

    def __iter__(self):
        return self

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()
