"""TpuShuffleFetcherIterator — the read-path engine.

Analogue of RdmaShuffleFetcherIterator.scala (reference: /root/
reference/src/main/scala/org/apache/spark/shuffle/rdma/
RdmaShuffleFetcherIterator.scala). Semantics preserved:

- async location fetch from the driver for ``[start, end)`` with a
  timeout wrapper (:108-122, 220-320),
- local partitions short-circuit to streams, never looping through the
  network (:328-339; SURVEY.md §5.1 #2),
- remote blocks are grouped **per source manager** into
  ``AggregatedPartitionGroup``s capped at ``shuffle_read_block_size``
  (:252-275),
- one one-sided READ per group pulls all its blocks into one pooled
  registered buffer, sliced per block (:132-218),
- ``max_bytes_in_flight`` throttle with a pending-fetch queue drained
  as results are consumed (:279-284, 369-379),
- the blocking results queue carries Success/Failure/FailureMetadata
  and a sentinel "+1 block" protocol keeps ``has_next`` truthful until
  all fetches are enqueued (:47-50, 124-130, 288, 434-448),
- failures surface as FetchFailedError / MetadataFetchFailedError so
  the scheduler can recompute; one failed block fails the whole reduce
  task by design (:203, 381-391),
- streams release their registered buffer slice on close
  (BufferReleasingInputStream, :399-429),
- per-fetch latency histogram hook (:186-189).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, List, Optional, Tuple

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation, ShuffleManagerId
from sparkrdma_tpu.memory.registered_buffer import RegisteredBuffer
from sparkrdma_tpu.memory.streams import MemoryviewInputStream
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs import now as obs_now
from sparkrdma_tpu.shuffle.errors import FetchFailedError, MetadataFetchFailedError
from sparkrdma_tpu.transport import FnListener, mapped_delivery_enabled

logger = logging.getLogger(__name__)


@dataclass
class ShuffleMetrics:
    """TaskMetrics stand-in (reference Spark metrics integration)."""

    local_blocks: int = 0
    remote_blocks: int = 0
    local_bytes: int = 0
    remote_bytes: int = 0
    fetch_wait_ms: float = 0.0
    records_read: int = 0
    sort_spills: int = 0  # external-sorter runs spilled to scratch


@dataclass
class AggregatedPartitionGroup:
    """Blocks from one source manager read in one one-sided READ (:71-74)."""

    total_length: int = 0
    blocks: List[Tuple[int, BlockLocation]] = field(default_factory=list)  # (pid, loc)


@dataclass
class _Success:
    streams: List[Tuple[int, BinaryIO]]  # (partition_id, stream)
    in_flight: int = 0


@dataclass
class _Failure:
    manager_id: Optional[ShuffleManagerId]
    partition_id: int
    error: Exception
    in_flight: int = 0


class _Dummy:
    in_flight = 0


@dataclass
class _PendingFetch:
    manager_id: ShuffleManagerId
    group: AggregatedPartitionGroup


class TpuShuffleFetcherIterator:
    """Iterator of (partition_id, stream) over local + remote blocks."""

    def __init__(self, manager, handle, start_partition: int, end_partition: int):
        self._manager = manager
        self._handle = handle
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.metrics = ShuffleMetrics()

        # registry mirrors of ShuffleMetrics, pre-resolved per iterator
        role = manager.executor_id
        reg = get_registry()
        self._m_local_blocks = reg.counter("reader.local_blocks", role=role)
        self._m_local_bytes = reg.counter("reader.local_bytes", role=role)
        self._m_remote_blocks = reg.counter("reader.remote_blocks", role=role)
        self._m_remote_bytes = reg.counter("reader.remote_bytes", role=role)
        self._m_fetch_wait_ms = reg.counter("reader.fetch_wait_ms", role=role)
        self._h_fetch_ms = reg.histogram("reader.fetch_ms", role=role)

        self._results: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        # sentinel "+1": keeps has_next true until enumeration completes
        self._total_results = 1
        self._processed_results = 0
        self._bytes_in_flight = 0
        self._pending: List[_PendingFetch] = []
        self._buffered: List[Tuple[int, BinaryIO]] = []
        self._closed = False

        self._start()

    # ------------------------------------------------------------------
    def _start(self) -> None:
        threading.Thread(
            target=self._resolve_and_fetch, name="fetcher-locations", daemon=True
        ).start()

    def _resolve_and_fetch(self) -> None:
        """Async location resolution + group construction (:220-320)."""
        t0 = time.monotonic()
        future = self._manager.fetch_remote_partition_locations(
            self._handle.shuffle_id, self.start_partition, self.end_partition
        )
        try:
            locations: List[PartitionLocation] = future.result(
                timeout=self._manager.conf.fetch_location_timeout_ms / 1000.0
            )
        except Exception as e:
            self._results.put(
                _Failure(
                    None,
                    self.start_partition,
                    MetadataFetchFailedError(
                        self._handle.shuffle_id, self.start_partition, str(e)
                    ),
                )
            )
            return
        logger.debug(
            "fetched %d locations in %.1f ms",
            len(locations),
            (time.monotonic() - t0) * 1e3,
        )

        # Local partitions short-circuit to streams (:328-339) — served
        # HERE, after the driver's barrier-gated reply, not at iterator
        # construction: a snapshot taken earlier would race local map
        # tasks that finish after the reader starts and silently drop
        # their records. The reply is complete by construction, so the
        # resolver now holds every local block the reply names.
        my_id = self._manager.executor_id
        resolver = self._manager.resolver
        local_pids = sorted(
            {
                loc.partition_id
                for loc in locations
                if loc.manager_id.executor_id == my_id
            }
        )
        local_streams: List[Tuple[int, BinaryIO]] = []
        for pid in local_pids:
            for stream in resolver.get_local_partition_streams(
                self._handle.shuffle_id, pid
            ):
                local_streams.append((pid, stream))
                self.metrics.local_blocks += 1
        # local bytes from the published block lengths (the streams
        # themselves are opaque); mirrors remote_bytes accounting
        local_bytes = sum(
            loc.block.length
            for loc in locations
            if loc.manager_id.executor_id == my_id
        )
        self.metrics.local_bytes += local_bytes
        self._m_local_blocks.inc(len(local_streams))
        self._m_local_bytes.inc(local_bytes)
        if local_streams:
            with self._lock:
                self._total_results += 1
            # via _put_success: a close() racing this thread must sweep
            # (or be handed) these streams, never strand them
            self._put_success(local_streams, 0)

        by_manager: Dict[ShuffleManagerId, List[Tuple[int, BlockLocation]]] = {}
        for loc in locations:
            if loc.manager_id.executor_id == my_id:
                continue  # served locally above
            by_manager.setdefault(loc.manager_id, []).append((loc.partition_id, loc.block))

        # pack per-manager groups ≤ read_block_size (:252-275)
        read_block_size = self._manager.conf.shuffle_read_block_size
        fetches: List[_PendingFetch] = []
        for mid, blocks in by_manager.items():
            group = AggregatedPartitionGroup()
            for pid, block in blocks:
                if group.blocks and group.total_length + block.length > read_block_size:
                    fetches.append(_PendingFetch(mid, group))
                    group = AggregatedPartitionGroup()
                group.blocks.append((pid, block))
                group.total_length += block.length
            if group.blocks:
                fetches.append(_PendingFetch(mid, group))

        max_in_flight = self._manager.conf.max_bytes_in_flight
        start_now: List[_PendingFetch] = []
        with self._lock:
            self._total_results += len(fetches)
            if self._closed:
                # closed while resolving: never launch READs for a
                # dead task (accounting is moot — has_next is False)
                fetches = []
            for fetch in fetches:
                if self._bytes_in_flight < max_in_flight:
                    self._bytes_in_flight += fetch.group.total_length
                    start_now.append(fetch)
                else:
                    self._pending.append(fetch)
        # resolve the sentinel now that enumeration is complete (:124-130)
        self._results.put(_Dummy())
        for fetch in start_now:
            self._fetch_blocks(fetch)

    def _group_failure(self, mid, group, cleanup=None):
        """Once-only failure handler for one group READ (on_failure may
        legally fire more than once; ``cleanup`` releases the group's
        destination resources, if any, before the error is queued)."""
        failed_once = threading.Event()

        def on_failure(e: Exception) -> None:
            if failed_once.is_set():
                return
            failed_once.set()
            if cleanup is not None:
                cleanup()
            self._results.put(
                _Failure(mid, group.blocks[0][0], e, in_flight=group.total_length)
            )

        return on_failure

    def _deliver_group(self, mid, group, streams, t0) -> None:
        """Shared success epilogue: histogram, metrics, closed-aware
        enqueue — ONE definition for both delivery flavors."""
        t1 = obs_now()
        latency_ms = (t1 - t0) * 1e3
        stats = self._manager.reader_stats
        if stats is not None:
            stats.update_remote_fetch_histogram(mid, latency_ms)
        self.metrics.remote_blocks += len(streams)
        self.metrics.remote_bytes += group.total_length
        self._m_remote_blocks.inc(len(streams))
        self._m_remote_bytes.inc(group.total_length)
        self._h_fetch_ms.observe(latency_ms)
        # fetch span: the trace id arrived with the location reply, so
        # the binding is resolvable by now
        self._manager.tracer.record(
            "shuffle.fetch",
            t0,
            t1,
            shuffle_id=self._handle.shuffle_id,
            peer=mid.executor_id,
            bytes=group.total_length,
            blocks=len(streams),
        )
        self._put_success(streams, group.total_length)

    def _fetch_blocks(self, fetch: _PendingFetch) -> None:
        """Issue one one-sided READ for a whole group (:132-218)."""
        mid, group = fetch.manager_id, fetch.group
        t0 = obs_now()
        try:
            # bulk READ payloads ride the data-flavor channel so an 8 MiB
            # in-flight group never head-of-line blocks a location fetch
            # on the rpc channel (RdmaChannel.java:110-154)
            channel = self._manager.get_channel_to(mid, purpose="data")
            if mapped_delivery_enabled(self._manager.conf, channel):
                self._fetch_blocks_mapped(fetch, channel, t0)
                return
            reg = RegisteredBuffer(self._manager.buffer_manager, group.total_length)
            # each slice holds one refcount; buffer returns to the pool
            # when the last stream closes (:399-429)
            slices = [reg.slice(block.length) for _, block in group.blocks]
        except Exception as e:
            self._results.put(
                _Failure(mid, group.blocks[0][0], e, in_flight=group.total_length)
            )
            return

        def on_success(_) -> None:
            streams: List[Tuple[int, BinaryIO]] = [
                (pid, MemoryviewInputStream(sl.view, on_close=sl.release))
                for (pid, _block), sl in zip(group.blocks, slices)
            ]
            self._deliver_group(mid, group, streams, t0)

        channel.read_in_queue(
            FnListener(
                on_success,
                self._group_failure(
                    mid, group,
                    cleanup=lambda: [sl.release() for sl in slices],
                ),
            ),
            [sl.view for sl in slices],
            [(block.mkey, block.address, block.length) for _, block in group.blocks],
        )

    def _fetch_blocks_mapped(self, fetch: _PendingFetch, channel, t0) -> None:
        """Mapped-delivery flavor of the group READ (native transport):
        no pooled destination buffer — same-host blocks stream straight
        from page-cache mappings, remote ones from one malloc'd blob.
        The delivery releases when the LAST of its block streams
        closes, exactly like the registered buffer's refcounted
        slices (:399-429)."""
        mid, group = fetch.manager_id, fetch.group

        def on_success(delivery) -> None:
            remaining = [len(delivery.views)]
            lock = threading.Lock()

            def release_one() -> None:
                with lock:
                    remaining[0] -= 1
                    last = remaining[0] == 0
                if last:
                    delivery.release()

            streams: List[Tuple[int, BinaryIO]] = [
                (pid, MemoryviewInputStream(view, on_close=release_one))
                for (pid, _block), view in zip(group.blocks, delivery.views)
            ]
            self._deliver_group(mid, group, streams, t0)

        channel.read_mapped_in_queue(
            FnListener(on_success, self._group_failure(mid, group)),
            [(block.mkey, block.address, block.length)
             for _, block in group.blocks],
        )

    # ------------------------------------------------------------------
    def _put_success(self, streams, in_flight: int) -> None:
        """Enqueue delivered streams — unless the iterator has been
        closed, in which case the delivery's resources (registered
        slices or mapped page-cache windows) are released RIGHT HERE:
        a late arrival must never wait for the garbage collector."""
        with self._lock:
            # the put happens INSIDE the closed-flag lock: a put racing
            # close() must either land before the drain (swept there)
            # or observe _closed and release here — never fall between
            if not self._closed:
                self._results.put(_Success(streams, in_flight=in_flight))
                return
        for _pid, stream in streams:
            try:
                stream.close()
            except Exception:
                logger.exception("closing late-delivered stream failed")

    def close(self) -> None:
        """Release every delivered-but-unconsumed stream: buffered ones
        and results still queued; in-flight deliveries release on
        arrival via `_put_success`. The reference runs the same sweep
        as a task-completion callback
        (RdmaShuffleFetcherIterator.scala:90-106). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._pending.clear()  # never launch new READs for a dead task
        leftovers = list(self._buffered)
        self._buffered.clear()
        while True:
            try:
                r = self._results.get_nowait()
            except queue.Empty:
                break
            if isinstance(r, _Success):
                leftovers.extend(r.streams)
        for _pid, stream in leftovers:
            try:
                stream.close()
            except Exception:
                logger.exception("closing unconsumed stream failed")

    def _drain_pending(self) -> None:
        """Start queued fetches now under the in-flight cap (:369-379)."""
        max_in_flight = self._manager.conf.max_bytes_in_flight
        start_now: List[_PendingFetch] = []
        with self._lock:
            while self._pending and self._bytes_in_flight < max_in_flight:
                fetch = self._pending.pop(0)
                self._bytes_in_flight += fetch.group.total_length
                start_now.append(fetch)
        for fetch in start_now:
            self._fetch_blocks(fetch)

    def has_next(self) -> bool:
        if self._buffered:
            return True
        with self._lock:
            # a closed iterator is exhausted: pending fetches were
            # dropped and late deliveries release without enqueueing,
            # so waiting on the result count would hang forever
            if self._closed:
                return False
            return self._processed_results < self._total_results

    def next(self) -> Tuple[int, BinaryIO]:
        while not self._buffered:
            if not self.has_next():
                raise StopIteration
            t0 = time.monotonic()
            result = self._results.get()
            waited_ms = (time.monotonic() - t0) * 1e3
            self.metrics.fetch_wait_ms += waited_ms
            self._m_fetch_wait_ms.inc(waited_ms)
            with self._lock:
                self._processed_results += 1
                self._bytes_in_flight -= result.in_flight
            if isinstance(result, _Failure):
                # the task will abandon this iterator: sweep every
                # already-delivered stream (and drop queued pending
                # fetches — launching fresh READs for a dead task,
                # which the pre-close drain did, is pure waste) before
                # surfacing the error
                self.close()
                err = result.error
                if isinstance(err, (FetchFailedError, MetadataFetchFailedError)):
                    raise err
                raise FetchFailedError(
                    result.manager_id,
                    self._handle.shuffle_id,
                    -1,
                    result.partition_id,
                    str(err),
                )
            # only successful progress starts the next queued fetches
            self._drain_pending()
            if isinstance(result, _Success):
                self._buffered.extend(result.streams)
        return self._buffered.pop(0)

    def __iter__(self):
        return self

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()
