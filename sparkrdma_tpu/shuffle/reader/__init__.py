"""TpuShuffleReader — records out of fetched partition streams.

Analogue of RdmaShuffleReader.scala (reference: /root/reference/src/
main/scala/org/apache/spark/shuffle/rdma/RdmaShuffleReader.scala):
wraps the fetcher iterator's streams with the symmetric decompression +
deserialization (:52-67), merges metrics, applies the aggregator
(map-side-combine aware, :81-96) and optional key ordering (:99-112 —
the ExternalSorter role).

Two structural upgrades over the reference's serial loop
(DESIGN.md §16):

- decode runs on the :class:`ReduceTaskPipeline` (reader/pipeline.py):
  a pool of ``reduce.parallelism`` workers decompresses + deserializes
  fetched streams OFF the fetch thread while further group READs are
  in flight, with delivery re-sequenced to fetch order so any
  parallelism yields the exact serial sequence;
- the consume path is zero-copy end to end: compressed frames slice
  out of the fetched stream via ``read_view`` (no intermediate bytes),
  and records deserialize straight from the decompressed buffer via
  ``load_buffer`` (no ``BytesIO(block)`` copy per block).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from sparkrdma_tpu.engine.serializer import PickleSerializer, iter_compressed_blocks
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle import columnar
from sparkrdma_tpu.shuffle.fetcher import TpuShuffleFetcherIterator
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, combine_by_key
from sparkrdma_tpu.shuffle.reader.pipeline import ReduceTaskPipeline


class TpuShuffleReader:
    def __init__(
        self,
        manager,
        handle: BaseShuffleHandle,
        start_partition: int,
        end_partition: int,
    ):
        self._manager = manager
        self._handle = handle
        self._fetcher = TpuShuffleFetcherIterator(
            manager, handle, start_partition, end_partition
        )
        self._serializer = PickleSerializer()
        self._pipe: Optional[ReduceTaskPipeline] = None

    @property
    def metrics(self):
        return self._fetcher.metrics

    def _decode_stream(self, item, _fetched) -> List[Tuple]:
        """Decode one fetched (pid, stream) fully: checksum-verified
        bytes -> decompressed block views -> record tuples. Runs on a
        decode-pool worker; the stream's registered slice / mapped
        window releases as soon as its last record materializes, so
        zero-copy views never outlive their backing buffer.

        Columnar frames (per-block magic sniff, shuffle/columnar.py)
        skip deserialization entirely: decode is header validation +
        ``np.frombuffer`` column views over the landed bytes, rows
        materialize straight off the aliased columns — the split-phase
        decode stage degenerated to view construction (DESIGN.md §25)."""
        _pid, stream = item
        codec = self._manager.resolver.codec
        records: List[Tuple] = []
        view_decodes = 0
        try:
            for block in iter_compressed_blocks(stream, codec):
                if columnar.is_columnar(block):
                    records.extend(columnar.iter_records(block))
                    view_decodes += 1
                else:
                    records.extend(self._serializer.load_buffer(block))
        finally:
            stream.close()
        if view_decodes:
            get_registry().counter(
                "block.view_decodes", role=self._manager.executor_id
            ).inc(view_decodes)
        return records

    @staticmethod
    def _discard(stage: str, item, value) -> None:
        """Abort-drain hook: an undecoded stream still owns its
        registered slice / mapped window — close it. Decoded record
        lists hold no resources."""
        if stage == "fetch" and item is not None:
            _pid, stream = item
            try:
                stream.close()
            except Exception:
                pass

    def _record_iter(self) -> Iterator[Tuple]:
        conf = self._manager.conf
        metrics = self._fetcher.metrics
        self._pipe = ReduceTaskPipeline(
            None,  # the fetcher iterator IS the fetch stage
            self._decode_stream,
            None,
            None,
            parallelism=conf.reduce_parallelism,
            depth=conf.reduce_pipeline_depth,
            double_buffer=False,  # no staging stage on the record plane
            role=self._manager.executor_id,
            discard_fn=self._discard,
        )
        stream = self._pipe.stream(self._fetcher)
        try:
            for records in stream:
                for rec in records:
                    metrics.records_read += 1
                    yield rec
        finally:
            # completion OR abandonment (generator finalization): abort
            # the pipeline, unblock its fetch thread by closing the
            # fetcher (sweeping unconsumed streams — the reference's
            # task-completion cleanup, RdmaShuffleFetcherIterator.scala:
            # 90-106), then drain the pipeline so every in-flight
            # stream's registered slice / mapped window releases
            self._pipe.abort()
            self._fetcher.close()
            stream.close()

    def close(self) -> None:
        """Release unconsumed fetched streams NOW (the reference's
        task-completion cleanup, RdmaShuffleFetcherIterator.scala:
        90-106). Generator finalization alone cannot cover a consumer
        that abandons `read()` without ever starting iteration — task
        runners call this from a finally. Idempotent."""
        if self._pipe is not None:
            self._pipe.abort()
        self._fetcher.close()

    def read(self) -> Iterator[Tuple]:
        """Iterator of (key, value) with aggregation/ordering applied."""
        records = self._record_iter()
        agg = self._handle.aggregator
        if agg is not None:
            # with map-side combine the incoming values are combiners (:87-90)
            combined = combine_by_key(
                records, agg, values_are_combiners=self._handle.map_side_combine
            )
            records = iter(combined.items())
        if self._handle.key_ordering:
            # spillable ordering (the ExternalSorter role, :99-112)
            from sparkrdma_tpu.utils.external_sorter import ExternalSorter

            sorter = ExternalSorter(
                spill_threshold=self._manager.conf.sort_spill_threshold
            )
            records = sorter.sort(records)
            self._fetcher.metrics.sort_spills = sorter.spill_count
        return records
