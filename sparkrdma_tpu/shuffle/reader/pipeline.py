"""ReduceTaskPipeline — the pipelined reduce plane.

BENCH_r05/WORKLOADS_r05 pinned the reduce-side loss: raw one-sided READ
sustains 4.02 GB/s but the *consumed* rate is 1.46 GB/s against a
2.41 GB/s roofline, and the TeraSort e2e reduce wall saved only 0.83 s
of fetch/merge overlap — fetch, checksum/decode, host→HBM staging and
device merge ran strictly in sequence, the exact shape the map plane's
``MapTaskPipeline`` (shuffle/writer/pipeline.py) already eliminated.
This is its reduce-side mirror:

    fetch (group READs in flight)        group k+2   (wire / fetcher)
      -> decode pool                     group k+1   (checksum +
                                                      decompress +
                                                      deserialize)
        -> stage                         group k     (host -> HBM)
          -> merge / deliver             group k-1   (device compute /
                                                      the consumer)

Stage concurrency:

- the *fetch* stage is one thread pulling the source iterator — for the
  record plane that iterator is :class:`TpuShuffleFetcherIterator`,
  which already issues group READs ahead under ``maxBytesInFlight``;
  the thread's blocking wait on arrivals IS the measured fetch time,
- ``parallelism`` decode workers (conf ``reduce.parallelism``) take
  checksum verify + decompress + deserialize OFF the fetch thread,
- a sequencer re-orders decode-pool output back to source order before
  the stage body runs, so **delivery order is invariant under
  parallelism** — ``parallelism=1`` and ``parallelism=N`` deliver the
  exact same sequence the serial loop did,
- the stage and merge bodies run on separate threads when
  ``double_buffer`` is on (conf ``reduce.doubleBufferStaging``): the
  host→HBM transfer of group k+1 rides under the device merge of
  group k — classic double-buffered staging. Off, one thread runs
  stage+merge back to back (strictly serialized staging).

Abort semantics mirror the map plane: the first error latches,
everything in flight drains WITHOUT delivering (``discard_fn`` releases
each undelivered item's resources — streams, host blocks, device
buffers), and the error re-raises to the consumer. An early-closing
consumer (generator finalization, ``close()``) takes the same path, so
registered slices and mapped windows always release deterministically.

Observability (docs/OBSERVABILITY.md): per-item latency histograms
``reader.pipeline.stage_ms{stage=fetch|decode|stage|merge}``, the live
``reader.pipeline.inflight`` gauge, and ``reader.pipeline.overlap_ms``
— per-run sum-of-stage-busy minus wall, the time the overlap SAVED.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.obs import get_registry, get_tracer
from sparkrdma_tpu.shuffle.writer.pipeline import PipelineReport, _STAGE_BOUNDS

STAGES = ("fetch", "decode", "stage", "merge")

_CLOSE = object()  # queue sentinel: upstream is done
_SKIP = object()  # sequencer marker: item discarded (abort/error)


class ReduceTaskPipeline:
    """Bounded four-stage reduce pipeline over fetched items.

    ``fetch_fn(item)``, ``decode_fn(item, fetched)``, ``stage_fn(item,
    decoded)``, ``merge_fn(item, staged)`` are the stage bodies; any may
    be None to pass its input through. ``run(source)`` collects a
    :class:`PipelineReport`; ``stream(source)`` yields merged outputs
    lazily IN SOURCE ORDER (the record plane's consumption mode) and
    records the report on :attr:`last_report` once exhausted.

    ``discard_fn(stage, item, value)`` releases an undelivered item's
    resources during an abort drain; ``stage`` names the pipeline stage
    whose OUTPUT ``value`` is (``"fetch"`` = fetched-but-undecoded,
    ``"decode"`` = decoded, ``"stage"`` = staged).
    """

    def __init__(
        self,
        fetch_fn: Optional[Callable[[Any], Any]],
        decode_fn: Optional[Callable[[Any, Any], Any]],
        stage_fn: Optional[Callable[[Any, Any], Any]],
        merge_fn: Optional[Callable[[Any, Any], Any]] = None,
        *,
        parallelism: int = 2,
        depth: int = 2,
        double_buffer: bool = True,
        role: str = "reader",
        discard_fn: Optional[Callable[[str, Any, Any], None]] = None,
    ):
        self._fetch_fn = fetch_fn
        self._decode_fn = decode_fn
        self._stage_fn = stage_fn
        self._merge_fn = merge_fn
        self._parallelism = max(1, int(parallelism))
        self._depth = max(1, int(depth))
        self._double_buffer = bool(double_buffer)
        self._role = role
        self._discard_fn = discard_fn
        self.last_report: Optional[PipelineReport] = None
        # live-run state, set while stream() is active so close() can
        # abort a pipeline its consumer abandoned
        self._abort: Optional[threading.Event] = None

    # ------------------------------------------------------------------
    def abort(self) -> None:
        """Latch the abort flag of a live ``stream``; in-flight items
        drain without delivering. No-op when nothing is running."""
        ev = self._abort
        if ev is not None:
            ev.set()

    def run(self, source: Iterable[Any]) -> PipelineReport:
        """Drive the pipeline to completion, collecting ordered results."""
        results = list(self.stream(source))
        report = self.last_report
        report.results = results
        return report

    # ------------------------------------------------------------------
    def stream(self, source: Iterable[Any]) -> Iterator[Any]:
        # fetch/decode/stage/merge run on bare threads: inherit the
        # consuming task's tenant for buffer charges and breaker keys
        tenant = tenancy.current_tenant()
        reg = get_registry()
        inflight = reg.gauge("reader.pipeline.inflight", role=self._role)
        hists = {
            s: reg.histogram(
                "reader.pipeline.stage_ms",
                bounds=_STAGE_BOUNDS,
                role=self._role,
                stage=s,
            )
            for s in STAGES
        }
        busy = {s: 0.0 for s in STAGES}
        busy_lock = threading.Lock()
        abort = threading.Event()
        self._abort = abort
        errbox: List[BaseException] = []
        err_lock = threading.Lock()

        def fail(e: BaseException) -> None:
            with err_lock:
                if not errbox:
                    errbox.append(e)
            abort.set()

        tracer = get_tracer(self._role)

        def timed(stage: str, follows, fn: Callable, *args):
            """Run one stage body inside a ``reader.pipeline.<stage>``
            span that causally follows the item's previous stage span
            (the queue hand-off edge). Returns (result, span)."""
            t0 = time.perf_counter()
            try:
                with tracer.span(
                    "reader.pipeline." + stage, follows=follows
                ) as sp:
                    return fn(*args), sp
            finally:
                dt = time.perf_counter() - t0
                hists[stage].observe(dt * 1e3)
                with busy_lock:
                    busy[stage] += dt

        def discard(stage: str, item: Any, value: Any) -> None:
            # _SKIP marks an item a previous stage already discarded —
            # its resources are gone and its inflight slot is freed
            if value is _SKIP:
                return
            try:
                if self._discard_fn is not None:
                    self._discard_fn(stage, item, value)
            except Exception as e:  # noqa: BLE001 — drain must finish
                fail(e)
            finally:
                inflight.add(-1)

        # fetch -> decode handoff: bounded, so decode backpressures the
        # fetch thread instead of decoding the whole shuffle ahead of a
        # slow consumer
        decode_q: "queue.Queue" = queue.Queue(self._depth)
        # decode -> sequencer reorder buffer: decode-pool completions
        # land keyed by source index; the sequencer releases them in
        # order. Bounded implicitly: at most parallelism + depth items
        # are past the fetch stage at once.
        seq_lock = threading.Lock()
        seq_ready = threading.Condition(seq_lock)
        seq_buf: dict = {}
        total_box = {"n": None}  # set when the source is exhausted
        # stage -> merge double buffer (only when split across threads)
        merge_q: "queue.Queue" = queue.Queue(1)
        # merge -> consumer handoff
        out_q: "queue.Queue" = queue.Queue(self._depth)

        def fetch_main() -> None:
            it = iter(source)
            idx = 0
            try:
                while not abort.is_set():
                    t0 = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    finally:
                        dt = time.perf_counter() - t0
                        with busy_lock:
                            busy["fetch"] += dt
                    inflight.add(1)
                    try:
                        fetched, sp = (
                            timed("fetch", None, self._fetch_fn, item)
                            if self._fetch_fn is not None
                            else (item, None)
                        )
                    except BaseException as e:  # noqa: BLE001
                        fail(e)
                        inflight.add(-1)
                        break
                    schedule_point("queue", "reader.decode_q.put")
                    decode_q.put((idx, item, fetched, sp))
                    idx += 1
            except BaseException as e:  # noqa: BLE001
                fail(e)
            finally:
                with seq_ready:
                    total_box["n"] = idx
                    seq_ready.notify_all()
                decode_q.put(_CLOSE)

        def decode_main() -> None:
            while True:
                schedule_point("queue", "reader.decode_q.get")
                got = decode_q.get()
                if got is _CLOSE:
                    decode_q.put(_CLOSE)  # release sibling workers
                    return
                idx, item, fetched, prev = got
                if abort.is_set():
                    discard("fetch", item, fetched)
                    decoded, sp = _SKIP, None
                else:
                    try:
                        decoded, sp = (
                            timed("decode", prev, self._decode_fn, item, fetched)
                            if self._decode_fn is not None
                            else (fetched, prev)
                        )
                    except BaseException as e:  # noqa: BLE001
                        fail(e)
                        discard("fetch", item, fetched)
                        decoded, sp = _SKIP, None
                with seq_ready:
                    seq_buf[idx] = (item, decoded, sp)
                    seq_ready.notify_all()

        def next_in_order():
            """Sequencer: block for the next source-order item. Returns
            (idx, item, decoded) or None when the run is complete —
            ordering is enforced HERE, so any decode parallelism
            delivers the exact sequence the serial loop would."""
            want = next_in_order.want
            with seq_ready:
                while True:
                    if want in seq_buf:
                        item, decoded, sp = seq_buf.pop(want)
                        next_in_order.want = want + 1
                        return want, item, decoded, sp
                    n = total_box["n"]
                    if n is not None and want >= n:
                        return None
                    seq_ready.wait()

        next_in_order.want = 0

        def stage_one(idx, item, decoded, prev):
            if decoded is _SKIP or abort.is_set():
                discard("decode", item, decoded)
                return None, None, False
            try:
                staged, sp = (
                    timed("stage", prev, self._stage_fn, item, decoded)
                    if self._stage_fn is not None
                    else (decoded, prev)
                )
                return staged, sp, True
            except BaseException as e:  # noqa: BLE001
                fail(e)
                discard("decode", item, decoded)
                return None, None, False

        def merge_one(idx, item, staged, prev) -> None:
            if abort.is_set():
                discard("stage", item, staged)
                return
            try:
                out, _sp = (
                    timed("merge", prev, self._merge_fn, item, staged)
                    if self._merge_fn is not None
                    else (staged, prev)
                )
            except BaseException as e:  # noqa: BLE001
                fail(e)
                discard("stage", item, staged)
                return
            schedule_point("queue", "reader.out_q.put")
            out_q.put((idx, out))

        def stage_main() -> None:
            while True:
                nxt = next_in_order()
                if nxt is None:
                    if self._double_buffer:
                        merge_q.put(_CLOSE)
                    return
                idx, item, decoded, prev = nxt
                staged, sp, ok = stage_one(idx, item, decoded, prev)
                if not ok:
                    continue
                if self._double_buffer:
                    # hand off: the NEXT item's host->HBM stage fills
                    # its buffer while the merge thread drains this one
                    schedule_point("queue", "reader.merge_q.put")
                    merge_q.put((idx, item, staged, sp))
                else:
                    merge_one(idx, item, staged, sp)

        def merge_main() -> None:
            while True:
                schedule_point("queue", "reader.merge_q.get")
                got = merge_q.get()
                if got is _CLOSE:
                    return
                merge_one(*got)

        threads = [
            threading.Thread(
                target=tenancy.scoped(tenant, fetch_main),
                name="reduce-pipeline-fetch",
                daemon=True,
            ),
            threading.Thread(
                target=tenancy.scoped(tenant, stage_main),
                name="reduce-pipeline-stage",
                daemon=True,
            ),
        ]
        threads += [
            threading.Thread(
                target=tenancy.scoped(tenant, decode_main),
                name=f"reduce-pipeline-decode-{i}",
                daemon=True,
            )
            for i in range(self._parallelism)
        ]
        if self._double_buffer:
            threads.append(
                threading.Thread(
                    target=tenancy.scoped(tenant, merge_main),
                    name="reduce-pipeline-merge",
                    daemon=True,
                )
            )
        t_wall0 = time.perf_counter()
        for t in threads:
            t.start()

        done = threading.Event()

        def joiner() -> None:
            for t in threads:
                t.join()
            done.set()
            out_q.put(_CLOSE)

        # analysis: ignore[tenant-scope]: joins scoped workers and posts a sentinel, no tenant work
        threading.Thread(
            target=joiner, name="reduce-pipeline-join", daemon=True
        ).start()

        closing = False
        try:
            while True:
                got = out_q.get()
                if got is _CLOSE:
                    break
                idx, out = got
                # a consumer that stops here (abandons the generator)
                # unwinds through the finally below: abort + drain
                inflight.add(-1)
                yield out
        except GeneratorExit:
            closing = True
            raise
        finally:
            abort.set()
            # drain the consumer handoff so stage/merge never block on
            # a full out_q while the joiner waits on them; keep going
            # until the sentinel (or an empty queue with all workers
            # gone) so no delivered-but-unconsumed item evades discard
            while True:
                try:
                    got = out_q.get(timeout=0.05)
                except queue.Empty:
                    if done.is_set():
                        break
                    continue
                if got is _CLOSE:
                    break
                _idx, out = got
                discard("merge", None, out)
            wall = time.perf_counter() - t_wall0
            self._abort = None
            overlap = max(0.0, sum(busy.values()) - wall)
            reg.histogram(
                "reader.pipeline.overlap_ms",
                bounds=_STAGE_BOUNDS,
                role=self._role,
            ).observe(overlap * 1e3)
            self.last_report = PipelineReport(
                wall_s=wall,
                stage_busy_s=dict(busy),
                overlap_s=overlap,
                results=[],
            )
            # an early-closing consumer is an abort, not an error: the
            # latched exception (if any) must not replace GeneratorExit
            if errbox and not closing:
                raise errbox[0]
