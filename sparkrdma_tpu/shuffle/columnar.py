"""Fixed-width columnar block encoding — the zero-copy record plane.

An Arrow-style record-batch layout negotiated per shuffle alongside the
pickle stream format (DESIGN.md §25): a batch of same-arity tuples of
fixed-width numpy scalars serializes into one contiguous typed region
per column, prefixed by a fixed header carrying the dtype codes, row
count, and column offsets. The payload rides the existing block frame
(``serializer.frame_columnar``) UNCOMPRESSED, so on the reduce side

- decode degenerates to header validation + ``np.frombuffer`` view
  construction: every column ALIASES the fetched buffer (registered
  slice, mapped page-cache window, or HBM-pulled slab) — no per-block
  ``bytes()`` materialization anywhere between transport landing and
  consume (the PR 4 ``read_view`` contract extended to the record
  plane), and
- device staging is a raw byte copy — the on-device sorter/planner
  (``models/terasort.py``, ``ops/sort.py``) consume columns straight
  through ``np.frombuffer`` + ``device_put``.

Layout (all integers big-endian, column data little-endian):

    magic(2)=0xA7C1 version(1) flags(1) rows(4) cols(2)
    cols x [dtype_code(1) offset(4) nbytes(4)]
    ...8-aligned column regions...
    tail padding

Offsets are relative to the payload start and 8-aligned. The payload is
padded so ``(4 + len(payload)) % 8 == 0``: framed columnar blocks — and
therefore whole columnar partitions — have lengths divisible by 8, which
is exactly what ``ShuffleScheduleCompiler``'s elem-alignment eligibility
check needs. Ragged pickle partitions fail ``length % itemsize`` for
4/8-byte dtypes and drop to the host passthrough; columnar partitions
ride the DMA waves (ROADMAP item 3's collective-coverage lever).

Magic collision safety inside a mixed frame stream: zlib frames start
0x78; an uncompressed pickle frame starts with a 4-byte record length,
so a 0xA7 first byte would claim a ~2.8 GiB record — blocks flush at
256 KiB. The first payload byte is therefore unambiguous.

Pickle remains the universal fallback: ``encode_batch`` returns ``None``
for any batch this layout cannot carry (non-tuple records, ragged
arity, non-numpy or non-fixed-width values, mixed dtypes per position)
and the writer frames that batch as a pickle stream instead — the two
frame kinds interleave freely within one partition block.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = 0xA7C1
MAGIC_BYTES = b"\xa7\xc1"
VERSION = 1

_HDR = struct.Struct(">HBBIH")  # magic, version, flags, rows, cols
_COL = struct.Struct(">BII")  # dtype_code, offset, nbytes

# fixed-width scalar dtypes the layout carries; column data is stored
# little-endian so the wire bytes are host-independent (numpy scalars
# are native-order — identical on every rig this runs on, but the
# explicit tag keeps the format self-describing)
_CODE_TO_DTYPE = {
    1: np.dtype("u1"),
    2: np.dtype("<u2"),
    3: np.dtype("<u4"),
    4: np.dtype("<u8"),
    5: np.dtype("i1"),
    6: np.dtype("<i2"),
    7: np.dtype("<i4"),
    8: np.dtype("<i8"),
    9: np.dtype("<f4"),
    10: np.dtype("<f8"),
    11: np.dtype("?"),
}
# kind/itemsize identifies a dtype independent of byte order
_KIND_TO_CODE = {
    (dt.kind, dt.itemsize): code for code, dt in _CODE_TO_DTYPE.items()
}


def _code_for(dtype: np.dtype) -> Optional[int]:
    return _KIND_TO_CODE.get((dtype.kind, dtype.itemsize))


def _align8(n: int) -> int:
    return (n + 7) & ~7


def is_columnar(buf) -> bool:
    """True when ``buf`` starts with the columnar frame magic."""
    if len(buf) < _HDR.size:
        return False
    view = buf if isinstance(buf, (bytes, bytearray)) else memoryview(buf)
    return bytes(view[:2]) == MAGIC_BYTES


def header_span(buf) -> int:
    """Byte length of the header + column descriptor table (the region
    the ``block:corrupt_header`` fault seam is allowed to flip in)."""
    _magic, _ver, _flags, _rows, ncols = _HDR.unpack_from(
        buf if isinstance(buf, (bytes, bytearray, memoryview)) else memoryview(buf), 0
    )
    return _HDR.size + ncols * _COL.size


# ----------------------------------------------------------------------
# encode
# ----------------------------------------------------------------------
def encode_columns(cols: Sequence[np.ndarray]) -> bytes:
    """Serialize 1-D column arrays (equal lengths) into one payload."""
    if not cols:
        raise ValueError("columnar payload needs at least one column")
    rows = len(cols[0])
    descs: List[Tuple[int, int, int]] = []
    off = _align8(_HDR.size + len(cols) * _COL.size)
    for col in cols:
        if col.ndim != 1 or len(col) != rows:
            raise ValueError("columns must be 1-D and equal-length")
        code = _code_for(col.dtype)
        if code is None:
            raise ValueError(f"dtype {col.dtype} not columnar-encodable")
        descs.append((code, off, col.nbytes))
        off = _align8(off + col.nbytes)
    # +4 keeps the FRAMED length (4-byte prefix + payload) a multiple
    # of 8 — the collective eligibility invariant (module docstring)
    total = off + 4
    out = bytearray(total)
    _HDR.pack_into(out, 0, MAGIC, VERSION, 0, rows, len(cols))
    pos = _HDR.size
    for (code, coff, nbytes), col in zip(descs, cols):
        _COL.pack_into(out, pos, code, coff, nbytes)
        pos += _COL.size
        le = col.astype(col.dtype.newbyteorder("<"), copy=False)
        out[coff : coff + nbytes] = le.tobytes()
    return bytes(out)


def encode_batch(records: Sequence[Tuple]) -> Optional[bytes]:
    """Encode a record batch, or ``None`` when it does not conform.

    Conformance: every record a tuple of the same nonzero arity, every
    value a numpy fixed-width scalar, and each position's dtype uniform
    across the batch. Anything else pickles (the universal fallback).
    """
    if not records:
        return None
    first = records[0]
    if type(first) is not tuple or not first:
        return None
    arity = len(first)
    codes: List[int] = []
    for v in first:
        if not isinstance(v, np.generic):
            return None
        code = _code_for(v.dtype)
        if code is None:
            return None
        codes.append(code)
    for rec in records:
        if type(rec) is not tuple or len(rec) != arity:
            return None
        for v, code in zip(rec, codes):
            if not isinstance(v, np.generic) or _code_for(v.dtype) != code:
                return None
    cols = [
        np.array([rec[j] for rec in records], dtype=_CODE_TO_DTYPE[codes[j]])
        for j in range(arity)
    ]
    return encode_columns(cols)


# ----------------------------------------------------------------------
# decode — views over the landed buffer, never copies
# ----------------------------------------------------------------------
def decode_columns(buf) -> List[np.ndarray]:
    """Header validation + view construction: each returned array
    ALIASES ``buf`` (``np.frombuffer`` at the column offset). Views are
    valid only while the backing buffer (registered slice / mapped
    window / pulled slab) stays open — same lifetime contract as
    ``read_view`` blocks."""
    view = buf if isinstance(buf, memoryview) else memoryview(buf)
    if len(view) < _HDR.size:
        raise ValueError("columnar block shorter than its header")
    magic, version, _flags, rows, ncols = _HDR.unpack_from(view, 0)
    if magic != MAGIC:
        raise ValueError(f"bad columnar magic 0x{magic:04X}")
    if version != VERSION:
        raise ValueError(f"unsupported columnar version {version}")
    if ncols == 0:
        raise ValueError("columnar block with zero columns")
    end = len(view)
    if _HDR.size + ncols * _COL.size > end:
        raise ValueError("columnar descriptor table out of bounds")
    cols: List[np.ndarray] = []
    pos = _HDR.size
    for _ in range(ncols):
        code, off, nbytes = _COL.unpack_from(view, pos)
        pos += _COL.size
        dt = _CODE_TO_DTYPE.get(code)
        if dt is None:
            raise ValueError(f"unknown columnar dtype code {code}")
        if nbytes != rows * dt.itemsize or off + nbytes > end:
            raise ValueError("columnar column extent out of bounds")
        cols.append(np.frombuffer(view, dtype=dt, count=rows, offset=off))
    return cols


def iter_records(buf) -> Iterator[Tuple]:
    """Row iterator over a columnar payload: tuples of numpy scalars,
    byte-identical in value and dtype to the pickle path's records."""
    cols = decode_columns(buf)
    return zip(*cols)
