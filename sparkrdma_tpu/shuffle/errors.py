"""Failure types surfaced to the scheduler for recompute.

Analogues of Spark's FetchFailedException / MetadataFetchFailedException
as the reference raises them (RdmaShuffleFetcherIterator.scala:381-391,
226-237): failures never hang the iterator — they surface so the
scheduler can re-run the producing stage (SURVEY.md §5.1 #9).
"""

from __future__ import annotations

from typing import Optional

from sparkrdma_tpu.locations import ShuffleManagerId


class ShuffleError(Exception):
    pass


class FetchFailedError(ShuffleError):
    def __init__(
        self,
        manager_id: Optional[ShuffleManagerId],
        shuffle_id: int,
        map_id: int,
        partition_id: int,
        message: str,
    ):
        self.manager_id = manager_id
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.partition_id = partition_id
        super().__init__(
            f"fetch failed: shuffle {shuffle_id} partition {partition_id} "
            f"from {manager_id}: {message}"
        )


class MetadataFetchFailedError(ShuffleError):
    def __init__(self, shuffle_id: int, partition_id: int, message: str):
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        super().__init__(
            f"metadata fetch failed: shuffle {shuffle_id} partition {partition_id}: {message}"
        )


class ChecksumError(IOError):
    """A fetched block's bytes do not match the published checksum.

    Deliberately an IOError, not a ShuffleError: inside the fetcher it
    is a *retryable* transport-grade fault (the retry ladder re-reads
    the block); only retry exhaustion promotes it into the
    FetchFailedError that triggers stage recompute."""

    def __init__(self, shuffle_id: int, partition_id: int, message: str):
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        super().__init__(
            f"checksum mismatch: shuffle {shuffle_id} partition {partition_id}: {message}"
        )
