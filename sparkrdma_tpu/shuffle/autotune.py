"""Attribution-driven wave self-tuning for the schedule compiler.

The pipelined wave engine (shuffle/collective.py) has one load-bearing
sizing choice: the effective ``collective.waveBytes``, which decides
how many waves a stage cuts into. Too coarse and the stage runs as one
monolithic wave — nothing for the pipeline to overlap; too fine and
per-wave dispatch dominates. The right cut depends on the stage shape
and the rig, so this module closes the loop from the system's own
observability planes instead of asking the operator to guess:

- ``collective.*`` wave stats from the stage that just ran (wave
  count, dispatch vs in-flight wall, overlap actually achieved),
- the job's critical-path :class:`~sparkrdma_tpu.obs.attr.TimeBreakdown`
  (PR 14) — if ``dma-wave`` is a sliver of the job's wall, re-cutting
  waves cannot move the job and the tuner holds still,
- the sampling profiler's gap frames (PR 15) — transfer-plane frames
  (``device_put`` / ``block_until_ready``) dominating untraced gaps
  confirm the mover is worth re-cutting toward overlap.

Choices persist per (shuffle, stage-shape) signature in the compiler's
tuner instance, so the SECOND identical stage of a job already runs
with the adjusted cut — the first knob the system tunes from its own
attribution data. The tuned budget never drops below the stage's
largest partition group: fusion requires a partition's rows to share
one wave, and a tuner must never change result shapes.

Stdlib + numpy only (the compiler imports this on every platform).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit
from sparkrdma_tpu.ops.exchange import round_bucket

logger = logging.getLogger(__name__)

# fraction of job wall the dma-wave category must carry before the
# tuner will re-cut a stage on breakdown evidence; below this the
# shuffle is not the job's problem and re-cutting is churn
MIN_DMA_WAVE_FRACTION = 0.05


def stage_signature(schedule: str, lanes: int, rows_class: int,
                    bucket_class: int, dtype_name: str) -> Tuple:
    """Stable identity of a stage SHAPE: two stages with the same
    signature would compile to the same wave program classes, so a
    cut learned on one transfers to the other."""
    return (schedule, lanes, rows_class, bucket_class, dtype_name)


class WaveReport:
    """One executed stage's wave stats, fed back by ``execute()``."""

    __slots__ = ("stage_bytes", "min_group_bytes", "waves", "depth",
                 "dispatch_ms", "wave_ms", "overlap_ms")

    def __init__(self, stage_bytes: int, min_group_bytes: int, waves: int,
                 depth: int, dispatch_ms: float, wave_ms: float,
                 overlap_ms: float):
        self.stage_bytes = stage_bytes
        # largest single partition group (bucketed) — the fusion floor
        self.min_group_bytes = min_group_bytes
        self.waves = waves
        self.depth = depth
        self.dispatch_ms = dispatch_ms
        self.wave_ms = wave_ms
        self.overlap_ms = overlap_ms


class WaveAutoTuner:
    """Per-compiler controller: observe a stage, choose the next cut.

    Deterministic and convergent by construction: the chosen budget is
    a pure function of (stage bytes, depth, fusion floor), so the
    second observation of the same signature computes the same choice
    and the controller goes quiet (no oscillation)."""

    def __init__(self, conf, executor_id: str):
        self._conf = conf
        self._executor_id = executor_id
        self._lock = threading.Lock()
        self._choices: Dict[Tuple, int] = {}
        reg = get_registry()
        self._m_adjust = reg.counter(
            "collective.autotune_adjustments", role=executor_id
        )
        self._m_tuned = reg.gauge(
            "collective.tuned_wave_bytes", role=executor_id
        )

    # ------------------------------------------------------------------
    def wave_bytes_for(self, sig: Tuple) -> Optional[int]:
        """The remembered cut for this stage shape, or None for the
        configured default. Called by ``plan()`` before wave
        formation — this is how the second identical stage runs
        tuned."""
        if not self._conf.collective_auto_tune:
            return None
        with self._lock:
            return self._choices.get(sig)

    # ------------------------------------------------------------------
    def observe(self, sig: Tuple, report: WaveReport) -> None:
        """Fold one executed stage into the per-signature choice."""
        if not self._conf.collective_auto_tune:
            return
        if report.stage_bytes <= 0 or report.waves <= 0:
            return
        if not self._breakdown_allows():
            return
        target = self._target_budget(report)
        if target is None:
            return
        with self._lock:
            prev = self._choices.get(sig)
            if prev == target:
                return  # converged for this shape
            self._choices[sig] = target
        self._m_adjust.inc()
        self._m_tuned.set(target)
        journal_emit(
            "autotune.adjust", role=self._executor_id,
            prev=prev or 0, wave_bytes=target, waves=report.waves,
        )
        logger.debug(
            "autotune: stage %r waveBytes %s -> %d (waves=%d depth=%d "
            "dispatch=%.2fms wall=%.2fms overlap=%.2fms)",
            sig, prev, target, report.waves, report.depth,
            report.dispatch_ms, report.wave_ms, report.overlap_ms,
        )

    # ------------------------------------------------------------------
    def _target_budget(self, report: WaveReport) -> Optional[int]:
        """The cut the NEXT run of this shape should use.

        Aim for ~2 waves per pipeline slot: enough waves that issue
        and consume genuinely overlap, few enough that dispatch stays
        amortized. When the stage already runs dispatch-bound (issue
        wall dominating the in-flight wall across many waves), coarsen
        instead — the same rule, approached from the other side."""
        depth = max(1, report.depth)
        target_waves = 2 * depth
        configured = self._conf.collective_wave_bytes
        dispatch_frac = (
            report.dispatch_ms / report.wave_ms
            if report.wave_ms > 1e-6 else 0.0
        )
        if report.waves > target_waves * 2 and dispatch_frac > 0.5:
            # dispatch-bound: coarsen toward the target count
            ideal = -(-report.stage_bytes // target_waves)
        elif report.waves < target_waves:
            # monolithic (or near): re-cut so the pipeline has waves
            # to keep in flight
            ideal = -(-report.stage_bytes // target_waves)
        else:
            return None  # already in band — hold
        budget = round_bucket(max(1, ideal))
        # never cut below the fusion floor (a partition's rows must
        # share one wave) nor above the operator's configured cap
        budget = max(budget, report.min_group_bytes)
        budget = min(budget, configured)
        # and never below the smallest legal knob value
        budget = max(budget, 1 << 16)
        return budget

    # ------------------------------------------------------------------
    def _breakdown_allows(self) -> bool:
        """Attribution gate: when the last job's TimeBreakdown says the
        wall went elsewhere (and its gap frames don't implicate the
        transfer plane), hold still. No breakdown (critpath off, first
        job) means no veto — wave stats alone are enough to act."""
        try:
            from sparkrdma_tpu.obs.attr import dma_wave_signal, last_breakdown

            bd = last_breakdown()
            if bd is None:
                return True
            fraction, transfer_gaps = dma_wave_signal(bd)
            return fraction >= MIN_DMA_WAVE_FRACTION or transfer_gaps
        except Exception:
            logger.exception("autotune breakdown gate failed; allowing")
            return True
