"""TpuShuffleReader — records out of fetched partition streams.

Analogue of RdmaShuffleReader.scala (reference: /root/reference/src/
main/scala/org/apache/spark/shuffle/rdma/RdmaShuffleReader.scala):
wraps the fetcher iterator's streams with the symmetric decompression +
deserialization (:52-67), merges metrics, applies the aggregator
(map-side-combine aware, :81-96) and optional key ordering (:99-112 —
the ExternalSorter role).
"""

from __future__ import annotations

from io import BytesIO
from typing import Iterator, Tuple

from sparkrdma_tpu.engine.serializer import PickleSerializer, iter_compressed_blocks
from sparkrdma_tpu.shuffle.fetcher import TpuShuffleFetcherIterator
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, combine_by_key


class TpuShuffleReader:
    def __init__(
        self,
        manager,
        handle: BaseShuffleHandle,
        start_partition: int,
        end_partition: int,
    ):
        self._manager = manager
        self._handle = handle
        self._fetcher = TpuShuffleFetcherIterator(
            manager, handle, start_partition, end_partition
        )
        self._serializer = PickleSerializer()

    @property
    def metrics(self):
        return self._fetcher.metrics

    def _record_iter(self) -> Iterator[Tuple]:
        codec = self._manager.resolver.codec
        metrics = self._fetcher.metrics
        try:
            for _pid, stream in self._fetcher:
                try:
                    for block in iter_compressed_blocks(stream, codec):
                        for rec in self._serializer.load_stream(BytesIO(block)):
                            metrics.records_read += 1
                            yield rec
                finally:
                    stream.close()
        finally:
            # completion OR abandonment (generator finalization): sweep
            # unconsumed streams so registered slices / mapped windows
            # release deterministically (the reference's task-completion
            # cleanup, RdmaShuffleFetcherIterator.scala:90-106)
            self._fetcher.close()

    def close(self) -> None:
        """Release unconsumed fetched streams NOW (the reference's
        task-completion cleanup, RdmaShuffleFetcherIterator.scala:
        90-106). Generator finalization alone cannot cover a consumer
        that abandons `read()` without ever starting iteration — task
        runners call this from a finally. Idempotent."""
        self._fetcher.close()

    def read(self) -> Iterator[Tuple]:
        """Iterator of (key, value) with aggregation/ordering applied."""
        records = self._record_iter()
        agg = self._handle.aggregator
        if agg is not None:
            # with map-side combine the incoming values are combiners (:87-90)
            combined = combine_by_key(
                records, agg, values_are_combiners=self._handle.map_side_combine
            )
            records = iter(combined.items())
        if self._handle.key_ordering:
            # spillable ordering (the ExternalSorter role, :99-112)
            from sparkrdma_tpu.utils.external_sorter import ExternalSorter

            sorter = ExternalSorter(
                spill_threshold=self._manager.conf.sort_spill_threshold
            )
            records = sorter.sort(records)
            self._fetcher.metrics.sort_spills = sorter.spill_count
        return records
