"""Device fetch plane — per-block host-vs-device transport planning.

The reduce-side half of the device-native one-sided fetch path
(DESIGN.md §17): map tasks that stage a shard in the HBM arena publish
its ``(device_coords, arena_handle, arena_offset)`` next to the host
``(address, length, mkey)`` triple (locations.py / rpc.py trailing
extension), and the planner here decides per block whether the bytes
can move HBM→HBM — a Pallas/transfer-engine pull with no host CPU in
the data path (ops/remote_copy.py) — or must take the host socket
path. The host triple is ALWAYS valid; every planner outcome other
than a completed pull is a silent fallback, never an error, so an
arena that spilled (or freed) the shard mid-job degrades to exactly
the pre-existing behavior.

Mesh visibility: a destination can pull a source arena it can reach
over the device fabric. On a real multi-chip mesh that is the ICI/DCN
domain; in this process-model reproduction (and under
``JAX_PLATFORMS=cpu``) the visible set is the arenas registered by
DeviceShuffleIO endpoints living in this process — the emulated
topology the cluster tests run on.

Planner decision table (see DESIGN.md §17):

| condition                                   | outcome        |
|---------------------------------------------|----------------|
| ``deviceFetch.enabled`` off                  | host (silent)  |
| location has no device extension             | host (silent)  |
| block < ``deviceFetch.minBlockBytes``        | host, fallback++|
| source arena not mesh-visible                | host, fallback++|
| arena slab freed / spilled / being spilled   | host, fallback++|
| staged dtype ≠ requested dtype               | host, fallback++|
| pull itself fails                            | host, fallback++|
| otherwise                                    | device pull    |

Checksums are verified at publish time on the host copy; the device
copy is the same staged bytes, so device pulls trust them (the host
path keeps its per-block verify gate).

Relationship to the whole-stage schedule compiler (DESIGN.md §22,
shuffle/collective.py): when a reduce stage carries enough
device-resident blocks, the compiler claims them up front and moves
them in batched DMA waves; THIS planner then only sees the compiler's
passthrough set (non-device blocks, sub-minimum blocks, stages below
``collective.minBlocks``) plus any wave rows that degraded mid-stage —
for those the decision table above applies unchanged. The plane's
``pulls``/``bytes``/``fallbacks`` counters stay the single source of
truth across both paths: the compiler feeds them for its landed and
degraded rows.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import numpy as np

from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.ops import remote_copy
from sparkrdma_tpu.ops.hbm_arena import DeviceBuffer, DeviceBufferManager

logger = logging.getLogger(__name__)

# mesh-visible arena registry: executor_id -> that endpoint's
# DeviceBufferManager. Registered by DeviceShuffleIO on construction,
# dropped on stop. Process-local by design (see module docstring).
_arenas: Dict[str, DeviceBufferManager] = {}
_arenas_lock = threading.Lock()


def register_arena(executor_id: str, dev: DeviceBufferManager) -> None:
    with _arenas_lock:
        _arenas[executor_id] = dev


def unregister_arena(executor_id: str, dev: DeviceBufferManager) -> None:
    """Drop the registration iff it is still ``dev`` (a newer endpoint
    under the same executor id wins; its registration must survive the
    old one's stop)."""
    with _arenas_lock:
        if _arenas.get(executor_id) is dev:
            del _arenas[executor_id]


def visible_arena(executor_id: str) -> Optional[DeviceBufferManager]:
    with _arenas_lock:
        return _arenas.get(executor_id)


class DevicePulledBlock:
    """A block that arrived HBM→HBM — the device plane's stand-in for
    a :class:`~sparkrdma_tpu.shuffle.device_io.HostBlock` in the reduce
    pipeline's hand-off. It is already staged (the pull landed in a
    local arena slab), already integrity-covered (checksum verified at
    publish), so verify passes it through and stage just unwraps it;
    ordering, abort-drain (``release`` frees the slab) and
    circuit-breaker bookkeeping flow through the same pipeline seams
    the host path uses."""

    kind = "device"

    __slots__ = ("shuffle_id", "loc", "length", "dev", "_released")

    def __init__(self, shuffle_id: int, loc: PartitionLocation, dev: DeviceBuffer):
        self.shuffle_id = shuffle_id
        self.loc = loc
        self.length = loc.block.length
        self.dev = dev
        self._released = False

    def release(self) -> None:
        """Abort-drain path: discard the pulled slab."""
        if self._released:
            return
        self._released = True
        self.dev.free()

    def take(self) -> DeviceBuffer:
        """Ownership transfer to the staging stage (release becomes a
        no-op; the consumer frees the slab)."""
        self._released = True
        return self.dev


class DeviceFetchPlane:
    """Per-endpoint planner + mover for device pulls."""

    def __init__(self, conf, dev: DeviceBufferManager, executor_id: str):
        self._conf = conf
        self._dev = dev
        self._executor_id = executor_id
        reg = get_registry()
        self._m_pulls = reg.counter("device_fetch.plane.pulls", role=executor_id)
        self._m_bytes = reg.counter("device_fetch.plane.bytes", role=executor_id)
        self._m_fallbacks = reg.counter(
            "device_fetch.plane.fallbacks", role=executor_id
        )
        self._m_plan_ms = reg.histogram(
            "device_fetch.plane.plan_ms", role=executor_id
        )

    def _fallback(self, reason: str) -> None:
        self._m_fallbacks.inc()
        logger.debug("device pull fallback: %s", reason)

    def try_pull(self, loc: PartitionLocation, dtype=np.uint8) -> Optional[DeviceBuffer]:
        """Plan + execute one block pull; None means 'use the host path'.

        Never raises: any surprise inside the mover is swallowed into a
        fallback (the acceptance bar — an eviction/spill race degrades,
        it does not error)."""
        t0 = time.perf_counter()
        try:
            return self._try_pull(loc, dtype)
        except Exception:
            logger.exception("device pull errored; using host path")
            self._fallback("unexpected error")
            return None
        finally:
            self._m_plan_ms.observe((time.perf_counter() - t0) * 1e3)

    def _try_pull(self, loc: PartitionLocation, dtype) -> Optional[DeviceBuffer]:
        block = loc.block
        if not self._conf.device_fetch_enabled or not block.has_device:
            return None  # silent: the publisher never offered a device copy
        if block.length < self._conf.device_fetch_min_block_bytes:
            self._fallback("below minBlockBytes")
            return None
        src_arena = visible_arena(loc.manager_id.executor_id)
        if src_arena is None:
            self._fallback("source arena not mesh-visible")
            return None
        with src_arena.pinned_if_resident(block.arena_handle) as src:
            if src is None:
                # freed, spilled, or mid-spill: the eviction race
                self._fallback("arena slab not device-resident")
                return None
            if block.arena_offset + block.length > src.capacity:
                self._fallback("stale arena coordinates")
                return None
            if np.dtype(src.array.dtype) != np.dtype(dtype):
                # the consumer asked for differently-typed slabs than
                # the publisher staged; host stage_view retypes for
                # free, a device-side cast would compile per shape
                self._fallback("staged dtype mismatch")
                return None
            pulled = remote_copy.pull_block(src.array, self._dev.device)
            if pulled is None:
                self._fallback("mover failed")
                return None
            # adopt into the local arena: source and destination size
            # classes match (same power-of-two classing both sides), so
            # the pulled slab-capacity array fits exactly
            local = self._dev.get(block.length)
            try:
                local = local.put_array(pulled)
            except Exception:
                local.free()
                raise
            local.length = block.length
        self._m_pulls.inc()
        self._m_bytes.inc(block.length)
        return local
