"""Adaptive partition planner — telemetry-driven reduce-side ranges.

Static reduce plans split the partition id space uniformly across
workers: worker ``w`` owns ``[w*P//n, (w+1)*P//n)``. Under skew that is
the wrong cut — the worker that drew the hot partition also drew its
neighbors, and the stage tail stretches to the sum. Spark's AQE solves
this with runtime statistics (coalesce small post-shuffle partitions,
split skewed ones); the reference framework exposes the same lever
through its block-size metadata. Here the map stage already publishes
per-partition byte totals into the driver TelemetryHub
(``TpuShuffleManager._handle_publish`` ->
``TelemetryHub.record_partition_bytes``), so the driver can re-plan the
reduce ranges from REAL sizes before launching a single reduce task.

Two rules keep the plan safe:

- **Contiguity.** Ranges are contiguous ``(lo, hi)`` partition-id
  spans covering ``[0, P)`` exactly, in order. Orderings that depend on
  range-partitioned keys (TeraSort) stay correct: concatenating range
  outputs in range order is still globally sorted.
- **Conservatism.** If the static uniform plan is already balanced
  (its max byte load <= hot_factor * ideal), the planner returns the
  static bounds unchanged — no churn on uniform workloads, and
  existing jobs see byte-identical plans.

``plan_edges`` is the device-side twin: quantile key edges from a
sample, for the SPMD TeraSort's all-to-all routing
(models/terasort.py). A zipf-skewed key space under static top-bits
radix overflows one shard's receive capacity and forces
capacity-doubling recompiles; sampled quantile edges balance the
receive counts instead.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Sequence, Tuple

from sparkrdma_tpu.obs.metrics import get_registry
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


def static_bounds(num_partitions: int, num_reducers: int) -> List[Tuple[int, int]]:
    """The uniform id-space split reduce plans use when no sizes exist."""
    return [
        (w * num_partitions // num_reducers,
         (w + 1) * num_partitions // num_reducers)
        for w in range(num_reducers)
    ]


class AdaptivePartitioner:
    """Byte-balanced contiguous reduce ranges from published sizes."""

    def __init__(self, conf: TpuShuffleConf = None):
        self.conf = conf or TpuShuffleConf()
        self.hot_factor = max(1.0, float(self.conf.planner_hot_factor))
        reg = get_registry()
        self._m_splits = reg.counter("planner.splits", role="driver")
        self._m_coalesces = reg.counter("planner.coalesces", role="driver")
        self._m_plan_ms = reg.histogram("planner.plan_ms", role="driver")

    # ------------------------------------------------------------------
    def plan(
        self, sizes: Sequence[int], num_reducers: int,
        lane_sizes: Dict[str, Sequence[int]] = None,
    ) -> List[Tuple[int, int]]:
        """Contiguous ``(lo, hi)`` ranges covering ``[0, P)``, at most
        ``num_reducers`` of them, byte-balanced against ``sizes``.

        Greedy boundary placement with a recomputed target
        (remaining_bytes / remaining_ranges) so early over-full ranges
        don't starve the tail, plus hot-partition isolation: a
        partition whose size is >= hot_factor * ideal gets its own
        range when possible (cut before it and after it).

        ``lane_sizes`` (source executor -> per-partition bytes) switches
        the cost function from byte totals to DMA-LANE cost: the
        collective schedule's wave wall is set by its hottest source
        lane, not the byte sum, so a partition fed overwhelmingly by one
        source costs ``num_lanes * max_lane_bytes`` even when its total
        looks benign. Cuts then balance lane occupancy across reducers
        (the whole-stage schedule compiler's wave planner, DESIGN.md
        §22)."""
        t0 = time.perf_counter()
        if lane_sizes:
            sizes = self._lane_costs(sizes, lane_sizes)
        p = len(sizes)
        n = max(1, int(num_reducers))
        if p == 0:
            return []
        uniform = static_bounds(p, n)
        total = sum(sizes)
        if total <= 0 or n == 1:
            return uniform if n > 1 else [(0, p)]
        ideal = total / n
        hot = self.hot_factor * ideal
        # conservatism: keep the static plan when it is already balanced
        static_max = max(sum(sizes[lo:hi]) for lo, hi in uniform)
        if static_max <= hot:
            self._m_plan_ms.observe((time.perf_counter() - t0) * 1000.0)
            return uniform

        ranges: List[Tuple[int, int]] = []
        lo = 0
        acc = 0
        remaining = total
        for pid in range(p):
            ranges_left = n - len(ranges)
            if ranges_left <= 1:
                break  # last range takes everything left
            target = remaining / ranges_left
            s = sizes[pid]
            # cut BEFORE a hot partition so it starts its own range
            if s >= hot and acc > 0:
                ranges.append((lo, pid))
                remaining -= acc
                lo, acc = pid, 0
                ranges_left = n - len(ranges)
                if ranges_left <= 1:
                    break
                target = remaining / ranges_left
            acc += s
            # cut AFTER a range reaching target (or after a hot pid)
            if acc >= target or s >= hot:
                ranges.append((lo, pid + 1))
                remaining -= acc
                lo, acc = pid + 1, 0
        if lo < p:
            ranges.append((lo, p))
        elif not ranges or ranges[-1][1] < p:
            # defensive: never under-cover the id space
            start = ranges[-1][1] if ranges else 0
            ranges.append((start, p))

        # metrics: splits = hot partitions isolated into 1-wide ranges;
        # coalesces = ranges wider than the uniform width (tiny
        # neighbors folded together)
        uniform_width = -(-p // n)  # ceil
        splits = sum(
            1 for (a, b) in ranges if b - a == 1 and sizes[a] >= hot
        )
        coalesces = sum(1 for (a, b) in ranges if b - a > uniform_width)
        if splits:
            self._m_splits.inc(splits)
        if coalesces:
            self._m_coalesces.inc(coalesces)
        self._m_plan_ms.observe((time.perf_counter() - t0) * 1000.0)
        logger.debug(
            "adaptive plan: %d ranges over %d partitions "
            "(%d splits, %d coalesces, max load %.2fx ideal)",
            len(ranges), p, splits, coalesces,
            max(sum(sizes[a:b]) for a, b in ranges) / ideal if ideal else 0.0,
        )
        return ranges

    # ------------------------------------------------------------------
    def _lane_costs(
        self, sizes: Sequence[int], lane_sizes: Dict[str, Sequence[int]]
    ) -> List[int]:
        """Per-partition DMA-lane cost: ``max(total, L * hottest_lane)``.

        A ring-scheduled wave moves one source lane at a time, so a
        partition's fetch wall is its hottest lane times the lane
        count when one source dominates — and never better than its
        byte total when sources are balanced (then the two coincide)."""
        lanes = [list(v) for v in lane_sizes.values() if v]
        if not lanes:
            return list(sizes)
        n_lanes = len(lanes)
        costs: List[int] = []
        for pid in range(len(sizes)):
            hottest = max(
                (lane[pid] if pid < len(lane) else 0) for lane in lanes
            )
            costs.append(max(sizes[pid], n_lanes * hottest))
        get_registry().counter("collective.lane_plans", role="driver").inc()
        return costs

    # ------------------------------------------------------------------
    def plan_weights(self, sizes: Dict[int, int]) -> List[int]:
        """Partition ids heaviest-first — the scheduling order signal
        (TpuContext.run_job submits hot partitions first)."""
        return sorted(sizes, key=lambda pid: -sizes[pid])


# ----------------------------------------------------------------------
# device-side twin: quantile edges for the SPMD TeraSort all-to-all
# ----------------------------------------------------------------------
def plan_edges(sample, num_shards: int):
    """Ascending quantile key edges (len ``num_shards - 1``) from a
    host-side key sample: shard ``i`` owns keys in
    ``[edges[i-1], edges[i])``. Balanced receive counts under ANY key
    distribution, where static top-bits ranges balance only uniform
    keys."""
    import numpy as np

    arr = np.asarray(sample, dtype=np.uint32)
    if num_shards <= 1 or arr.size == 0:
        return np.zeros((max(0, num_shards - 1),), dtype=np.uint32)
    qs = np.arange(1, num_shards) / num_shards
    # quantile over sorted sample; uint32 keys sort correctly as uint
    edges = np.quantile(arr.astype(np.float64), qs)
    return np.minimum(edges, float(np.iinfo(np.uint32).max)).astype(np.uint32)


def capacity_from_sample(sample, num_shards: int, n_local: int,
                         edges=None, slack: float = 1.25) -> int:
    """Receive-capacity estimate from a sample: the max shard share
    observed in the sample, scaled to ``n_local`` keys per shard with
    ``slack`` headroom. With quantile ``edges`` the shares are near
    uniform and this lands close to ``n_local / num_shards``; without
    edges it measures the static top-bits skew directly."""
    import numpy as np

    arr = np.asarray(sample, dtype=np.uint32)
    if arr.size == 0 or num_shards <= 1:
        return max(8, n_local)
    if edges is None:
        shift = 32 - (num_shards.bit_length() - 1)
        dest = (arr >> np.uint32(shift)).astype(np.int64)
    else:
        dest = np.searchsorted(np.asarray(edges, dtype=np.uint32), arr,
                               side="right").astype(np.int64)
    counts = np.bincount(dest, minlength=num_shards)
    max_share = counts.max() / arr.size
    # every shard contributes up to n_local keys to the hottest receiver
    est = int(max_share * n_local * slack) + 8
    return max(8, est)
