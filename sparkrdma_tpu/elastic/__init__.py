"""Elastic cluster layer: executor-loss survival, speculative task
cloning, and the detachable shuffle-service daemon.

Three pillars, all behind the existing resolver/locations API
(docs/DESIGN.md §21):

- **Map-output durability** (:mod:`~sparkrdma_tpu.elastic.replication`):
  every committed map output is best-effort copied to
  ``tpu.shuffle.elastic.replicas`` peer executors. Replica locations
  publish with a lineage tag (``BlockLocation.replica_of`` /
  ``source_map``) and divert into a driver-side replica registry —
  invisible to reducers until the primary's executor is lost, at which
  point ``TpuShuffleManager._on_peer_lost`` promotes them and the
  completeness barrier only drops by the maps no replica covers.
  ``engine/cluster.py`` recomputes exactly that uncovered remainder.

- **Speculative execution** (:mod:`~sparkrdma_tpu.elastic.speculation`):
  the cluster driver consumes ``TelemetryHub.straggler_report()`` and
  clones a flagged executor's in-flight tasks onto a healthy peer.
  First finisher wins (the driver's first-finisher publish dedup makes
  map clones safe); the loser drains through the reader pipeline's
  existing abort latch via a ``cancel_reduce`` task request.

- **Shuffle-service daemon** (:mod:`~sparkrdma_tpu.elastic.service`):
  ``python -m sparkrdma_tpu.elastic.service`` runs a detachable
  process that adopts an executor's committed map outputs by file path
  — hard-link + mmap re-registration, no byte copy — and publishes
  them as replicas of that executor. Registered in the locations
  registry as a first-class source, served by the same transport, and
  covered by the circuit breakers like any peer.
"""

from sparkrdma_tpu.elastic.replication import (
    ReplicaClient,
    ReplicaStore,
    register_store,
    store_for,
    unregister_store,
)

__all__ = [
    "ReplicaClient",
    "ReplicaStore",
    "register_store",
    "store_for",
    "unregister_store",
]
