"""Speculative reduce execution — first finisher wins (docs/DESIGN.md §21).

The telemetry hub's straggler detector produces *advisory* verdicts
(``TelemetryHub.straggler_report`` → ``SourceHealthRegistry`` suspect
keys); this module is their first actuator. While a stage's reduce
ranges are in flight, :class:`SpeculativeReducePhase` polls those
verdicts and clones any range whose only attempt sits on a flagged
executor onto a healthy peer. Both attempts race:

- the first to finish settles the range (a clone win counts under
  ``elastic.speculation_wins``),
- every other attempt is drained through the worker's ``cancel_reduce``
  request, which closes the in-flight reader and fires the reduce
  pipeline's abort latch (``elastic.clone_cancels``) — the loser
  unwinds instead of burning its executor to the end.

Reduce tasks are safe to run twice by construction: they only *read*
published map outputs and the winner's result is taken whole, so the
race needs no output commit protocol. The phase also serves as the
cluster driver's failure collector — ranges whose every attempt failed
come back in the ``failures`` map for the executor-loss recovery path
(engine/cluster.py) rather than raising mid-phase.

Everything here runs on the driver: the monitor loop borrows the
calling thread, attempts ride the cluster's task pool.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit

logger = logging.getLogger(__name__)

# (range_index, (start_partition, end_partition), WorkerHandle)
Assignment = Tuple[int, Tuple[int, int], object]


def suspect_executors(driver) -> Set[str]:
    """Executor ids currently flagged by the advisory plane: the health
    registry's suspects (keys may be tenant-scoped ``<tenant>:<eid>`` —
    the verdict applies to the executor either way here, since a slow
    process is slow for every tenant's clone decision) plus a fresh
    straggler report when a telemetry hub is live."""
    out: Set[str] = set()
    health = getattr(driver, "health", None)
    if health is not None:
        for key in health.suspects():
            out.add(key.rsplit(":", 1)[-1])
    hub = getattr(driver, "telemetry", None)
    if hub is not None:
        try:
            out.update(hub.straggler_report().get("stragglers") or ())
        except Exception:
            logger.debug("straggler report failed", exc_info=True)
    return out


class SpeculativeReducePhase:
    """One stage's reduce fan-out with straggler cloning.

    ``live_workers`` is a callable (not a snapshot) so clone targets
    are chosen among executors still alive at decision time."""

    def __init__(
        self,
        driver,
        pool,
        conf,
        live_workers: Callable[[], List],
        handle,
        reduce_fn,
        tenant: Optional[str],
    ):
        self._driver = driver
        self._pool = pool
        self._conf = conf
        self._live_workers = live_workers
        self._handle = handle
        self._reduce_fn = reduce_fn
        self._tenant = tenant
        reg = get_registry()
        role = driver.executor_id
        self._m_specs = reg.counter("elastic.speculations", role=role)
        self._m_wins = reg.counter("elastic.speculation_wins", role=role)
        self._m_cancels = reg.counter("elastic.clone_cancels", role=role)

    # -- one attempt ----------------------------------------------------
    def _reduce_once(self, worker, rng: Tuple[int, int]):
        return worker.request(
            {
                "kind": "reduce",
                "handle": self._handle,
                "start": rng[0],
                "end": rng[1],
                "reduce_fn": self._reduce_fn,
                "tenant": self._tenant,
            }
        )

    def _cancel(self, worker, rng: Tuple[int, int]) -> None:
        try:
            hit = worker.request(
                {
                    "kind": "cancel_reduce",
                    "shuffle_id": self._handle.shuffle_id,
                    "start": rng[0],
                    "end": rng[1],
                },
                timeout_s=10.0,
            )
        except Exception:
            return  # loser already finished or died; nothing to drain
        if hit:
            self._m_cancels.inc()

    def _already_settled(
        self, idx: int, done: Dict[int, object], failures: Dict[int, Exception]
    ) -> bool:
        """Late-loser guard (caller holds the phase lock): once a range
        settled, every other attempt crossing the line is discarded —
        the first finisher's result must never be overwritten. Named so
        the modelcheck mutation gate can disarm exactly this guard."""
        return idx in done or idx in failures

    def _pick_peer(self, suspects: Set[str], tried: Set[str]):
        for w in self._live_workers():
            if w.executor_id in suspects or w.executor_id in tried:
                continue
            return w
        return None

    # -- the race -------------------------------------------------------
    def run(
        self, assignments: Sequence[Assignment]
    ) -> Tuple[Dict[int, object], Dict[int, Exception]]:
        """Run every assignment to first-finisher resolution. Returns
        ``(results, failures)`` keyed by range index; a range fails only
        when ALL of its attempts failed."""
        rngs = {idx: rng for idx, rng, _ in assignments}
        done: Dict[int, object] = {}
        failures: Dict[int, Exception] = {}
        # idx -> {executor_id: worker} still racing / ever tried
        inflight: Dict[int, Dict[str, object]] = {}
        tried: Dict[int, Set[str]] = {}
        lock = threading.Lock()
        wake = threading.Event()

        def issue(idx: int, worker, clone: bool) -> None:
            schedule_point("proto", "spec.issue")
            with lock:
                inflight.setdefault(idx, {})[worker.executor_id] = worker
                tried.setdefault(idx, set()).add(worker.executor_id)
            fut = self._pool.submit(self._reduce_once, worker, rngs[idx])
            fut.add_done_callback(
                lambda f: settle(idx, worker, f, clone)
            )

        def settle(idx: int, worker, fut, clone: bool) -> None:
            schedule_point("proto", "spec.settle")
            losers: List = []
            with lock:
                flight = inflight.get(idx, {})
                flight.pop(worker.executor_id, None)
                if self._already_settled(idx, done, failures):
                    wake.set()
                    return  # a loser crossing the line late
                err = fut.exception()
                if err is None:
                    done[idx] = fut.result()
                    if clone:
                        self._m_wins.inc()
                        journal_emit(
                            "elastic.spec_win",
                            role=self._driver.executor_id,
                            executor=worker.executor_id,
                            shuffle_id=self._handle.shuffle_id,
                            range=list(rngs[idx]),
                        )
                    losers = list(flight.values())
                    flight.clear()
                elif not flight:
                    # every attempt for this range has now failed
                    failures[idx] = err
                else:
                    logger.warning(
                        "reduce range %s failed on %s (%s); racing attempt "
                        "still in flight", rngs[idx], worker.executor_id, err,
                    )
            for w in losers:
                self._cancel(w, rngs[idx])
            wake.set()

        for idx, _rng, worker in assignments:
            issue(idx, worker, clone=False)

        speculate = self._conf.elastic_speculation
        check_s = self._conf.elastic_speculation_check_ms / 1000.0
        while True:
            with lock:
                if len(done) + len(failures) == len(assignments):
                    break
            wake.wait(timeout=check_s if speculate else 1.0)
            wake.clear()
            if not speculate:
                continue
            suspects = suspect_executors(self._driver)
            if not suspects:
                continue
            clones: List[Tuple[int, object]] = []
            with lock:
                for idx in rngs:
                    if idx in done or idx in failures:
                        continue
                    flight = inflight.get(idx, {})
                    # clone only a range with exactly one attempt, and
                    # only when that attempt sits on a suspect
                    if len(flight) != 1:
                        continue
                    (eid,) = flight
                    if eid not in suspects:
                        continue
                    peer = self._pick_peer(suspects, tried.get(idx, set()))
                    if peer is not None:
                        clones.append((idx, peer))
            for idx, worker in clones:
                self._m_specs.inc()
                journal_emit(
                    "elastic.spec", role=self._driver.executor_id,
                    executor=worker.executor_id,
                    tenant=self._tenant or "",
                    shuffle_id=self._handle.shuffle_id,
                    range=list(rngs[idx]),
                )
                logger.warning(
                    "speculating reduce range %s: cloning off flagged "
                    "executor onto %s", rngs[idx], worker.executor_id,
                )
                issue(idx, worker, clone=True)
        return dict(done), dict(failures)
