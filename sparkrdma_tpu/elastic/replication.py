"""Map-output replication: best-effort copies on peer executors.

The durability pillar of the elastic layer (docs/DESIGN.md §21). After
a wrapper writer commits a map output, its executor's
:class:`ReplicaClient` ships the non-empty partition payloads to the
next ``tpu.shuffle.elastic.replicas`` peers in ring order — in-process
by direct call (the merge plane's endpoint-registry idiom), across
processes over the engine task protocol (``replicate_blocks``, routed
like pushes). The receiving :class:`ReplicaStore` copies the bytes
into ONE registered segment and publishes the locations with the
lineage tag set (``replica_of`` = source executor, ``source_map`` =
map id, ``num_map_outputs`` = 0): the driver diverts such publishes
into its replica registry, so a replica can never double-serve a
partition while its primary is alive. Everything here is best-effort
by contract — a failed or skipped replication costs durability, never
a write failure.
"""

from __future__ import annotations

import logging
import re
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.writer.blocks import MemoryWriterBlock

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")


def _natural(executor_id: str):
    """Sort key treating digit runs numerically (exec-10 after exec-2)."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", executor_id)]


# ----------------------------------------------------------------------
# process-local store registry (the merge plane's endpoint idiom): in-
# process clusters replicate by direct call; keyed by (driver_port,
# executor_id) so two live contexts in one process never cross wires.
# ----------------------------------------------------------------------
_stores: Dict[Tuple[int, str], "ReplicaStore"] = {}
_stores_lock = threading.Lock()


def register_store(store: "ReplicaStore") -> None:
    with _stores_lock:
        _stores[store.key] = store


def unregister_store(store: "ReplicaStore") -> None:
    with _stores_lock:
        if _stores.get(store.key) is store:
            del _stores[store.key]


def store_for(driver_port: int, executor_id: str) -> Optional["ReplicaStore"]:
    with _stores_lock:
        return _stores.get((driver_port, executor_id))


def local_store_ids(driver_port: int) -> List[str]:
    """Executor ids with an in-process store for this driver port."""
    with _stores_lock:
        return [eid for (port, eid) in _stores if port == driver_port]


def ring_targets(
    self_id: str, candidates: Sequence[str], n: int
) -> List[str]:
    """The ``n`` peers after ``self_id`` in natural ring order."""
    ordered = sorted(set(candidates) | {self_id}, key=_natural)
    i = ordered.index(self_id)
    ring = [p for p in ordered[i + 1 :] + ordered[:i] if p != self_id]
    return ring[: max(0, n)]


class ReplicaStore:
    """Per-executor receiver of replicated map outputs."""

    def __init__(self, manager):
        self._manager = manager
        self.key = (manager.conf.driver_port, manager.executor_id)
        self._lock = named_lock("elastic.store")
        # shuffle_id -> [(registered segment, reserved bytes)]
        self._segments: Dict[int, List[Tuple[MemoryWriterBlock, int]]] = {}
        # shuffle_id -> replica locations published from this store:
        # the re-adoption ladder re-publishes these (lineage tags
        # intact) after a hub wipe, so a pre-crash executor death still
        # promotes instead of recomputing (sparkrdma_tpu/metastore)
        self._published: Dict[int, List[PartitionLocation]] = {}
        self._stopped = False
        reg = get_registry()
        role = manager.executor_id
        self._m_accepts = reg.counter("elastic.replica_accepts", role=role)
        self._m_drops = reg.counter("elastic.replica_drops", role=role)

    def accept(
        self,
        shuffle_id: int,
        source: str,
        map_id: int,
        blocks: Sequence[Tuple[int, bytes]],
    ) -> int:
        """Copy one map's partition payloads into registered memory and
        publish them as replicas of ``source``. Returns the number of
        locations published (0 = dropped: empty, over budget, or the
        store is stopping)."""
        manager = self._manager
        blocks = [(pid, payload) for pid, payload in blocks if len(payload)]
        total = sum(len(p) for _, p in blocks)
        if total == 0:
            return 0
        # replicas ride the same in-memory staging budget as merged
        # segments: durability must not OOM the executor
        if not manager.resolver.reserve_inmemory_bytes(total):
            self._m_drops.inc()
            return 0
        try:
            manager.start_node_if_missing()
            seg = MemoryWriterBlock(manager.node.pd, total)
            offsets: List[Tuple[int, int, int]] = []
            off = 0
            for pid, payload in blocks:
                seg.append(payload)
                offsets.append((pid, off, len(payload)))
                off += len(payload)
            mkey = seg.location().mkey
        except Exception:
            logger.exception("staging replica of %s map %d failed", source, map_id)
            manager.resolver.release_inmemory_bytes(total)
            self._m_drops.inc()
            return 0
        keep = False
        with self._lock:
            if not self._stopped:
                self._segments.setdefault(shuffle_id, []).append((seg, total))
                keep = True
        if not keep:
            seg.dispose()
            manager.resolver.release_inmemory_bytes(total)
            self._m_drops.inc()
            return 0
        locs = [
            PartitionLocation(
                manager.local_manager_id,
                pid,
                BlockLocation(
                    addr,
                    length,
                    mkey,
                    replica_of=source,
                    source_map=map_id,
                ),
            )
            for pid, addr, length in offsets
        ]
        with self._lock:
            if not self._stopped:
                self._published.setdefault(shuffle_id, []).extend(locs)
        manager.publish_partition_locations(shuffle_id, -1, locs, num_map_outputs=0)
        self._m_accepts.inc()
        return len(locs)

    def republish(self, meta_epoch: int = 0) -> int:
        """Re-publish every parked replica location (lineage tags
        intact) toward a wiped hub — the replica half of the
        re-adoption sweep. The segments themselves never moved; only
        the registry forgot them. Returns locations re-published."""
        with self._lock:
            parked = {sid: list(locs) for sid, locs in self._published.items()}
        count = 0
        for shuffle_id, locs in sorted(parked.items()):
            if not locs:
                continue
            self._manager.publish_partition_locations(
                shuffle_id, -1, locs, num_map_outputs=0, meta_epoch=meta_epoch
            )
            count += len(locs)
        return count

    def drop_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            segments = self._segments.pop(shuffle_id, [])
            self._published.pop(shuffle_id, None)
        for seg, reserved in segments:
            seg.dispose()
            self._manager.resolver.release_inmemory_bytes(reserved)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            shuffle_ids = list(self._segments)
        for sid in shuffle_ids:
            self.drop_shuffle(sid)


class ReplicaClient:
    """Map-side replication sender (one per executor manager)."""

    def __init__(self, manager):
        self._manager = manager
        self.routes: Dict[str, Tuple[str, int]] = {}
        reg = get_registry()
        role = manager.executor_id
        self._m_maps = reg.counter("elastic.replicated_maps", role=role)
        self._m_bytes = reg.counter("elastic.replicated_bytes", role=role)
        self._m_errors = reg.counter("elastic.replica_errors", role=role)

    def set_routes(self, routes: Optional[Dict[str, Tuple[str, int]]]) -> None:
        """{executor_id: (host, task_port)} — where replicate_blocks
        requests reach peer task servers (shipped by the driver in
        ``map_batch``, exactly like push routes)."""
        self.routes = {k: tuple(v) for k, v in (routes or {}).items()}

    def _peers(self) -> List[str]:
        # routes (shipped by the cluster driver in map_batch) name the
        # cross-process peers; the process-local store registry names
        # the in-process ones — it is populated at manager construction
        # and therefore complete before the first map commits, unlike
        # announced membership, which races early map tasks
        ids = set(self.routes)
        ids.update(local_store_ids(self._manager.conf.driver_port))
        if not ids:
            ids = set(self._manager.known_executor_ids())
        ids.discard(self._manager.executor_id)
        return sorted(ids, key=_natural)

    def replicate_map(self, shuffle_id: int, map_id: int, mapped_file) -> int:
        """Ship one committed map output to the configured number of
        ring peers. Returns how many peers accepted."""
        n = self._manager.conf.elastic_replicas
        if n <= 0:
            return 0
        targets = ring_targets(self._manager.executor_id, self._peers(), n)
        if not targets:
            return 0
        blocks = [
            (pid, bytes(mapped_file.get_partition_view(pid)))
            for pid in range(mapped_file.partition_count())
            if mapped_file.get_partition_location(pid).length > 0
        ]
        total = sum(len(p) for _, p in blocks)
        if total == 0:
            return 0
        payload = {
            "shuffle_id": shuffle_id,
            "source": self._manager.executor_id,
            "map_id": map_id,
            "blocks": blocks,
        }
        sent = 0
        # cluster mode: replica BYTES ride the data plane. The blocks
        # are registered once in this node's ProtectionDomain and every
        # socket target gets only (pid, mkey, length) descriptors over
        # the task protocol, pulling the bytes with a one-sided READ
        # before accepting (transport/staging.py); the synchronous task
        # replies are the release signal for the registrations
        staged = None
        try:
            for dest in targets:
                store = store_for(self._manager.conf.driver_port, dest)
                try:
                    if store is not None:
                        store.accept(
                            shuffle_id, payload["source"], map_id, blocks
                        )
                    elif dest in self.routes:
                        if staged is None and self._manager.node is not None:
                            from sparkrdma_tpu.transport.staging import (
                                stage_payloads,
                            )

                            data_addr, descs, release = stage_payloads(
                                self._manager.node, [p for _, p in blocks]
                            )
                            staged = (
                                dict(
                                    payload,
                                    blocks=[],
                                    blocks_rd=[
                                        (pid, mkey, length)
                                        for (pid, _), (mkey, length) in zip(
                                            blocks, descs
                                        )
                                    ],
                                    data_addr=data_addr,
                                ),
                                release,
                            )
                        self._send_socket(
                            self.routes[dest],
                            staged[0] if staged is not None else payload,
                        )
                    else:
                        continue
                    sent += 1
                except Exception:
                    # best-effort by contract: a failed replica is a
                    # silent durability miss, never a write failure
                    logger.debug(
                        "replicating to %s failed", dest, exc_info=True
                    )
                    self._m_errors.inc()
        finally:
            if staged is not None:
                staged[1]()
        if sent:
            self._m_maps.inc()
            self._m_bytes.inc(total * sent)
        return sent

    @staticmethod
    def _send_socket(addr: Tuple[str, int], payload: dict) -> None:
        import cloudpickle

        data = cloudpickle.dumps(dict(payload, kind="replicate_blocks"))
        with socket.create_connection(addr, timeout=10.0) as s:
            s.settimeout(10.0)
            s.sendall(_LEN.pack(len(data)) + data)
            # wait for the reply: the store publishes its replica
            # locations before answering, so by the time the map task
            # reports success its replicas are already registered
            hdr = b""
            while len(hdr) < 4:
                chunk = s.recv(4 - len(hdr))
                if not chunk:
                    raise ConnectionError("replica peer closed")
                hdr += chunk
            (nbytes,) = _LEN.unpack(hdr)
            got = 0
            while got < nbytes:
                chunk = s.recv(min(1 << 20, nbytes - got))
                if not chunk:
                    raise ConnectionError("replica peer closed")
                got += len(chunk)
