"""Detachable shuffle-service daemon — ``python -m
sparkrdma_tpu.elastic.service``.

The third pillar of the elastic layer (docs/DESIGN.md §21): a process
that outlives executors and takes ownership of their committed map
outputs, so an executor can restart (rolling upgrade, preemption)
without losing shuffle state. The handoff is metadata only — file
paths plus per-partition lengths (``WrapperShuffleData
.handoff_manifest``). The daemon hard-links each data file into its
own directory (same inode — zero byte copy; a cross-device fallback
copies), mmaps + registers the bytes in its OWN protection domain, and
publishes the locations as *replicas* of the source executor
(``replica_of`` set, ``num_map_outputs`` 0).

That replica tagging is what makes the daemon safe AND first-class:
while the executor lives, its own locations serve every fetch and the
daemon's stay parked in the driver's replica registry; the moment the
executor is lost, ``TpuShuffleManager._on_peer_lost`` promotes the
daemon's locations into the primary registry and reducers pull from
the daemon over the exact same transport, circuit breakers and all —
no duplication window, no special read path.

Control protocol (length-prefixed cloudpickle, one request per
connection, the engine task-protocol idiom): ``{"kind": "ping" |
"adopt" | "stop"}``; the daemon announces ``SERVICE_PORT <n>`` on
stdout. Executors trigger the handoff via their own ``{"kind":
"handoff", "service": (host, port)}`` task request (engine/worker.py).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import socket
import struct
import tempfile
import threading
import traceback
from typing import Dict, List, Tuple

import cloudpickle

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.memory.mapped_file import MappedFile
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")


def _recv_obj(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return cloudpickle.loads(bytes(buf))


def _send_obj(sock: socket.socket, obj) -> None:
    data = cloudpickle.dumps(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def send_adopt(
    addr: Tuple[str, int], source: str, manifests: Dict[int, List[dict]]
) -> int:
    """Client half of the handoff: ship ``{shuffle_id: [{map_id, path,
    partition_lengths}]}`` to a running daemon. Returns the number of
    map outputs adopted."""
    with socket.create_connection(addr, timeout=30.0) as s:
        s.settimeout(30.0)
        _send_obj(s, {"kind": "adopt", "source": source, "manifests": manifests})
        resp = _recv_obj(s)
    if not resp.get("ok"):
        raise RuntimeError(f"handoff to shuffle service failed: {resp.get('error')}")
    return resp.get("result", 0)


class ShuffleService:
    """One daemon: a full shuffle manager endpoint + the adopt logic.

    Usable in-process (tests construct it directly and call
    :meth:`adopt`) or as the detached ``__main__`` process."""

    def __init__(self, conf: TpuShuffleConf, service_id: str = "shuffle-svc-0"):
        # deliberately a plain executor-role manager: the daemon IS a
        # first-class location source — hello/announce membership, the
        # same transport node serving one-sided reads, the same breaker
        # keys on the fetcher side
        from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

        self.manager = TpuShuffleManager(conf, is_driver=False, executor_id=service_id)
        self.manager.start_node_if_missing()
        self._dir = tempfile.mkdtemp(prefix=f"tpu-shuffle-svc-{service_id}-")
        # (shuffle_id, source, map_id) -> MappedFile, so a repeated
        # handoff of the same map (executor retried it) is idempotent
        self._adopted: Dict[Tuple[int, str, int], MappedFile] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._m_maps = get_registry().counter(
            "elastic.handoff_maps", role=service_id
        )

    @property
    def executor_id(self) -> str:
        return self.manager.executor_id

    def adopt(self, source: str, manifests: Dict[int, List[dict]]) -> int:
        """Take ownership of ``source``'s map outputs. Returns how many
        map outputs were adopted this call."""
        adopted = 0
        for shuffle_id, maps in sorted(manifests.items()):
            for entry in maps:
                if self._adopt_one(int(shuffle_id), source, entry):
                    adopted += 1
        if adopted:
            self._m_maps.inc(adopted)
        return adopted

    def _adopt_one(self, shuffle_id: int, source: str, entry: dict) -> bool:
        map_id = int(entry["map_id"])
        key = (shuffle_id, source, map_id)
        with self._lock:
            if key in self._adopted:
                return False
        src_path = entry["path"]
        lengths = [int(n) for n in entry["partition_lengths"]]
        own_path = os.path.join(
            self._dir, f"shuffle_{shuffle_id}_{source}_{map_id}.data"
        )
        try:
            # hard link = shared inode, zero copy; the executor's later
            # dispose() unlinks only its own directory entry
            try:
                os.link(src_path, own_path)
            except OSError:
                shutil.copy(src_path, own_path)  # cross-device fallback
            mf = MappedFile(
                own_path,
                self.manager.node.pd,
                self.manager.conf.shuffle_write_block_size,
                lengths,
            )
        except Exception:
            logger.exception(
                "adopting %s map %d of shuffle %d failed", source, map_id, shuffle_id
            )
            return False
        with self._lock:
            if self._stop.is_set():
                mf.dispose()
                return False
            self._adopted[key] = mf
        locs = [
            PartitionLocation(
                self.manager.local_manager_id,
                pid,
                BlockLocation(
                    block.address,
                    block.length,
                    block.mkey,
                    replica_of=source,
                    source_map=map_id,
                ),
            )
            for pid in range(mf.partition_count())
            for block in (mf.get_partition_location(pid),)
            if block.length > 0
        ]
        if locs:
            self.manager.publish_partition_locations(
                shuffle_id, -1, locs, num_map_outputs=0
            )
        return True

    def handle(self, req: dict) -> dict:
        kind = req.get("kind")
        if kind == "ping":
            return {"ok": True, "result": "pong"}
        if kind == "adopt":
            n = self.adopt(req["source"], req.get("manifests") or {})
            return {"ok": True, "result": n}
        if kind == "stop":
            self._stop.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown service request {kind!r}"}

    def serve(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(16)
        srv.settimeout(0.2)
        print(f"SERVICE_PORT {srv.getsockname()[1]}", flush=True)

        def one(conn):
            try:
                req = _recv_obj(conn)
                try:
                    resp = self.handle(req)
                except Exception as e:
                    resp = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "traceback": traceback.format_exc(),
                    }
                _send_obj(conn, resp)
            except Exception:
                pass
            finally:
                conn.close()

        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=one, args=(conn,), daemon=True).start()
        srv.close()
        self.close()

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            adopted = list(self._adopted.values())
            self._adopted.clear()
        for mf in adopted:
            mf.dispose()
        shutil.rmtree(self._dir, ignore_errors=True)
        self.manager.stop()


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Detachable shuffle-service daemon (docs/DESIGN.md §21)"
    )
    ap.add_argument("--service-id", default="shuffle-svc-0")
    ap.add_argument("--conf", required=True, help="JSON dict of tpu.shuffle.* keys")
    args = ap.parse_args()
    conf = TpuShuffleConf(json.loads(args.conf))
    ShuffleService(conf, args.service_id).serve()


if __name__ == "__main__":
    main()
