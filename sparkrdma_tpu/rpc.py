"""Control-plane RPC protocol: 4 message types, segmented framing.

TPU-native analogue of RdmaRpcMsg.scala (reference: /root/reference/src/
main/scala/org/apache/spark/shuffle/rdma/RdmaRpcMsg.scala).

Framing (reference :42-64): a message serializes into one or more
*segments*, each at most ``recv_wr_size`` bytes, each prefixed with a
4-byte segment length and 4-byte message type so a receiver with fixed
preposted receive buffers can parse every segment independently. Large
messages (PublishPartitionLocations, AnnounceManagers) are split with a
per-segment ``is_last`` flag; receivers accumulate until the last
segment arrives (reference :91-161).

Message types (reference RdmaRpcMsgType, :30-34):
  - PublishPartitionLocations — writer→driver and driver→reducer pushes
    of ``PartitionLocation`` lists.
  - FetchPartitionLocations — reducer→driver request for one shuffle
    partition range.
  - ManagerHello — executor→driver introduction carrying its identity.
  - AnnounceManagers — driver→all broadcast of full membership.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field, replace
from io import BytesIO
from typing import List

from sparkrdma_tpu.locations import (
    PartitionLocation,
    ShuffleManagerId,
)

SEG_HEADER = struct.Struct(">iI")  # msg_type(4) payload_len(4)


class RpcMsgType(enum.IntEnum):
    PUBLISH_PARTITION_LOCATIONS = 0
    FETCH_PARTITION_LOCATIONS = 1
    MANAGER_HELLO = 2
    ANNOUNCE_MANAGERS = 3


class RpcMsg:
    """Base: a message knows how to cut itself into ≤seg_size segments."""

    msg_type: RpcMsgType

    def to_segments(self, seg_size: int) -> List[bytes]:
        raise NotImplementedError

    @staticmethod
    def frame(msg_type: RpcMsgType, payload: bytes) -> bytes:
        return SEG_HEADER.pack(int(msg_type), len(payload)) + payload

    @staticmethod
    def parse_segment(segment: bytes) -> "RpcMsg":
        """Parse one framed segment into its message object.

        Multi-segment messages come back as partial objects; the caller
        accumulates via ``is_last`` (reference parse loop, :70-88).
        """
        msg_type, payload_len = SEG_HEADER.unpack_from(segment, 0)
        payload = segment[SEG_HEADER.size : SEG_HEADER.size + payload_len]
        t = RpcMsgType(msg_type)
        if t == RpcMsgType.PUBLISH_PARTITION_LOCATIONS:
            return PublishPartitionLocationsMsg.from_payload(payload)
        if t == RpcMsgType.FETCH_PARTITION_LOCATIONS:
            return FetchPartitionLocationsMsg.from_payload(payload)
        if t == RpcMsgType.MANAGER_HELLO:
            return ManagerHelloMsg.from_payload(payload)
        if t == RpcMsgType.ANNOUNCE_MANAGERS:
            return AnnounceManagersMsg.from_payload(payload)
        raise ValueError(f"unknown rpc message type {msg_type}")


@dataclass
class PublishPartitionLocationsMsg(RpcMsg):
    """Segmented list of partition locations for one shuffle.

    Reference :91-161. ``partition_id`` is the *request* partition this
    publish answers (driver→reducer); writers publishing their map output
    to the driver use the sentinel -1 and the driver re-keys each
    location by its own ``partition_id`` (reference quirk documented at
    SURVEY.md §5.1 — preserved deliberately because the driver-side
    re-keying makes it sound).
    """

    msg_type = RpcMsgType.PUBLISH_PARTITION_LOCATIONS

    shuffle_id: int
    partition_id: int  # -1 = writer publish; else the fetched partition
    locations: List[PartitionLocation] = field(default_factory=list)
    is_last: bool = True
    # writer→driver publishes carry how many map outputs this message
    # completes so the driver can act as the map-output tracker and
    # defer fetch replies until the shuffle is complete (the reference
    # relies on Spark's own MapOutputTracker for this barrier; here the
    # control plane owns it). 0 on driver→reducer replies.
    num_map_outputs: int = 0
    # observability: the shuffle's trace id (minted at register_shuffle,
    # obs/trace.py) rides the frame so spans correlate across roles.
    # 0 = unknown (e.g. writer publishes before learning the id). It is
    # appended as a trailing 8-byte extension AFTER the locations so
    # parsers of the original layout (examples/foreign_client.c) skip
    # it: a PartitionLocation is >= 28 bytes, so an 8-byte residue is
    # unambiguously the extension, never a truncated location.
    trace_id: int = 0
    # observability: span id of the sender-side span this message hands
    # off from (obs/trace.py SpanHandle; 0 = none). Carried in the
    # 0xFFFB follows extension so the receiver can add a causal
    # ``follows`` edge — the publish→record and resolve→fetch legs of
    # the cross-role critical path (docs/OBSERVABILITY.md).
    origin_span: int = 0
    # control-plane HA (sparkrdma_tpu/metastore): the metastore
    # generation this publish routed against. Nonzero only on
    # re-adoption sweeps after a driver crash — the receiving hub
    # fences sweeps started under an older takeover. Carried in the
    # 0xFFFA epoch extension; 0 emits no bytes (legacy frames stay
    # byte-identical).
    meta_epoch: int = 0

    # is_last(1) shuffle_id(4) partition_id(4) num_map_outputs(4)
    _HDR = struct.Struct(">Biii")
    _TRACE_EXT = struct.Struct(">Q")
    # ONE header shape for every trailing extension: marker(2) count(4).
    # The parser peeks exactly this many bytes to dispatch, so all
    # extensions MUST share it — encoder and parser both go through
    # _EXT_HDR (the wire-markers analysis pass enforces the pairing).
    _EXT_HDR = struct.Struct(">HI")
    # per-segment checksum extension (resilience layer): written AFTER
    # the locations, BEFORE the trace extension. The marker 0xFFFF is
    # impossible as a ShuffleManagerId host length (a 64 KiB hostname
    # cannot fit a 4 KiB segment), so a parser peeking two bytes
    # distinguishes "next location" from "checksum extension"
    # unambiguously; examples/foreign_client.c's bounds check
    # (``o + hl + 4 + 2 > n``) makes the marker terminate its parse
    # loop safely. Layout: _EXT_HDR, then per location
    # algo(1) crc(4) — algo-tagged so mixed publishers coexist
    # (utils/checksum.py).
    _CK_MARKER = 0xFFFF
    _CK_ITEM = struct.Struct(">BI")
    # per-segment device-location extension (device fetch plane):
    # written AFTER the checksum extension, BEFORE the trace extension.
    # Same marker trick with 0xFFFE — equally impossible as a host
    # length. Layout: _EXT_HDR, then per location
    # device_coords(i4) arena_handle(u4) arena_offset(u8); handle 0 =
    # that location has no device copy (arena handles start at 1).
    _DEV_MARKER = 0xFFFE
    _DEV_ITEM = struct.Struct(">iIQ")
    # per-segment merged-location extension (push-based merge plane,
    # shuffle/merge.py): written AFTER the device extension, BEFORE the
    # trace extension. Same impossible-host-length marker trick with
    # 0xFFFD. Layout: _EXT_HDR, then per location merged_cover(u4);
    # cover 0 = a plain per-map block. Publishes with no merged
    # location emit zero extension bytes — legacy frames stay
    # byte-identical.
    _MRG_MARKER = 0xFFFD
    _MRG_ITEM = struct.Struct(">I")
    # per-segment elastic lineage extension (sparkrdma_tpu/elastic/):
    # written AFTER the merged extension, BEFORE the trace extension.
    # Same impossible-host-length marker trick with 0xFFFC. Layout:
    # _EXT_HDR, then per location source_map(i4) replica_len(u2)
    # followed by replica_len utf-8 bytes naming the executor whose
    # primary copy the block duplicates (0 bytes = a primary block,
    # source_map -1 = unattributed). Publishes with no lineage tag emit
    # zero extension bytes — legacy frames stay byte-identical.
    _ELA_MARKER = 0xFFFC
    _ELA_ITEM = struct.Struct(">iH")
    # message-level follows extension (critical-path attribution):
    # written AFTER the elastic extension, BEFORE the trace extension.
    # Same impossible-host-length marker trick with 0xFFFB. Layout:
    # _EXT_HDR with count 1, then one origin_span(u8) — the sender-side
    # span id this message causally follows. Messages with no origin
    # span emit zero extension bytes — legacy frames stay byte-identical.
    _FLW_MARKER = 0xFFFB
    _FLW_ITEM = struct.Struct(">Q")
    # message-level metastore-epoch extension (control-plane HA,
    # sparkrdma_tpu/metastore): written AFTER the follows extension,
    # BEFORE the trace extension. Same impossible-host-length marker
    # trick with 0xFFFA. Layout: _EXT_HDR with count 1, then one
    # meta_epoch(u8) — the metastore generation a re-adoption publish
    # routed against, so a sweep started under an older takeover is
    # fenced whole at the hub. Messages with epoch 0 emit zero
    # extension bytes — legacy frames stay byte-identical.
    _EPO_MARKER = 0xFFFA
    _EPO_ITEM = struct.Struct(">Q")
    # per-segment block-format extension (columnar block format,
    # shuffle/columnar.py): written AFTER the elastic extension, BEFORE
    # the follows extension. Same impossible-host-length marker trick
    # with 0xFFF9. Layout: _EXT_HDR, then per location block_format(u1);
    # 0 = pickle frame stream (the default). Publishes where every
    # block is pickle emit zero extension bytes — legacy frames stay
    # byte-identical.
    _FMT_MARKER = 0xFFF9
    _FMT_ITEM = struct.Struct(">B")

    def to_segments(self, seg_size: int) -> List[bytes]:
        has_ck = any(loc.block.checksum_algo for loc in self.locations)
        ck_fixed = self._EXT_HDR.size if has_ck else 0
        ck_per_loc = self._CK_ITEM.size if has_ck else 0
        has_dev = any(loc.block.arena_handle for loc in self.locations)
        dev_fixed = self._EXT_HDR.size if has_dev else 0
        dev_per_loc = self._DEV_ITEM.size if has_dev else 0
        has_mrg = any(loc.block.merged_cover for loc in self.locations)
        mrg_fixed = self._EXT_HDR.size if has_mrg else 0
        mrg_per_loc = self._MRG_ITEM.size if has_mrg else 0
        has_ela = any(
            loc.block.replica_of or loc.block.source_map >= 0
            for loc in self.locations
        )
        ela_fixed = self._EXT_HDR.size if has_ela else 0
        has_fmt = any(loc.block.block_format for loc in self.locations)
        fmt_fixed = self._EXT_HDR.size if has_fmt else 0
        fmt_per_loc = self._FMT_ITEM.size if has_fmt else 0
        flw_fixed = (
            self._EXT_HDR.size + self._FLW_ITEM.size if self.origin_span else 0
        )
        epo_fixed = (
            self._EXT_HDR.size + self._EPO_ITEM.size if self.meta_epoch else 0
        )
        budget = (
            seg_size
            - SEG_HEADER.size
            - self._HDR.size
            - self._TRACE_EXT.size
            - ck_fixed
            - dev_fixed
            - mrg_fixed
            - ela_fixed
            - fmt_fixed
            - flw_fixed
            - epo_fixed
        )
        if budget <= 0:
            raise ValueError(f"segment size {seg_size} too small")
        groups: List[List[PartitionLocation]] = [[]]
        used = 0
        for loc in self.locations:
            sz = (
                loc.serialized_size()
                + ck_per_loc + dev_per_loc + mrg_per_loc + fmt_per_loc
            )
            if has_ela:
                # variable per-loc cost: fixed item + the replica id bytes
                sz += self._ELA_ITEM.size + len(loc.block.replica_of.encode())
            if sz > budget:
                raise ValueError(
                    f"partition location ({sz} bytes) exceeds segment budget {budget}"
                )
            if used + sz > budget and groups[-1]:
                groups.append([])
                used = 0
            groups[-1].append(loc)
            used += sz
        segments = []
        for i, group in enumerate(groups):
            is_last = i == len(groups) - 1
            buf = BytesIO()
            buf.write(
                self._HDR.pack(
                    1 if is_last else 0,
                    self.shuffle_id,
                    self.partition_id,
                    self.num_map_outputs,
                )
            )
            for loc in group:
                loc.write(buf)
            if has_ck and group:
                buf.write(self._EXT_HDR.pack(self._CK_MARKER, len(group)))
                for loc in group:
                    buf.write(
                        self._CK_ITEM.pack(
                            loc.block.checksum_algo & 0xFF,
                            loc.block.checksum & 0xFFFFFFFF,
                        )
                    )
            if has_dev and group:
                buf.write(self._EXT_HDR.pack(self._DEV_MARKER, len(group)))
                for loc in group:
                    buf.write(
                        self._DEV_ITEM.pack(
                            loc.block.device_coords,
                            loc.block.arena_handle & 0xFFFFFFFF,
                            loc.block.arena_offset,
                        )
                    )
            if has_mrg and group:
                buf.write(self._EXT_HDR.pack(self._MRG_MARKER, len(group)))
                for loc in group:
                    buf.write(
                        self._MRG_ITEM.pack(loc.block.merged_cover & 0xFFFFFFFF)
                    )
            if has_ela and group:
                buf.write(self._EXT_HDR.pack(self._ELA_MARKER, len(group)))
                for loc in group:
                    rep = loc.block.replica_of.encode("utf-8")
                    buf.write(self._ELA_ITEM.pack(loc.block.source_map, len(rep)))
                    buf.write(rep)
            if has_fmt and group:
                buf.write(self._EXT_HDR.pack(self._FMT_MARKER, len(group)))
                for loc in group:
                    buf.write(self._FMT_ITEM.pack(loc.block.block_format & 0xFF))
            if self.origin_span:
                buf.write(self._EXT_HDR.pack(self._FLW_MARKER, 1))
                buf.write(self._FLW_ITEM.pack(self.origin_span))
            if self.meta_epoch:
                buf.write(self._EXT_HDR.pack(self._EPO_MARKER, 1))
                buf.write(self._EPO_ITEM.pack(self.meta_epoch))
            buf.write(self._TRACE_EXT.pack(self.trace_id))
            segments.append(self.frame(self.msg_type, buf.getvalue()))
        return segments

    @classmethod
    def from_payload(cls, payload: bytes) -> "PublishPartitionLocationsMsg":
        inp = BytesIO(payload)
        is_last, shuffle_id, partition_id, num_maps = cls._HDR.unpack(
            inp.read(cls._HDR.size)
        )
        locs = []
        origin_span = 0
        meta_epoch = 0
        end = len(payload)
        # locations are each >= 28 bytes, so a residue of exactly 8 is
        # the trailing trace-id extension (absent from legacy senders);
        # a 0xFFFF two-byte peek is the checksum extension, a 0xFFFE
        # peek the device-location extension, a 0xFFFD peek the merged
        # extension — all sit between the locations and the trace id,
        # in any order
        while end - inp.tell() > cls._TRACE_EXT.size:
            pos = inp.tell()
            peek = inp.read(cls._EXT_HDR.size)
            if len(peek) == cls._EXT_HDR.size:
                marker, count = cls._EXT_HDR.unpack(peek)
                if marker == cls._CK_MARKER:
                    if count == len(locs):
                        for i in range(count):
                            algo, crc = cls._CK_ITEM.unpack(
                                inp.read(cls._CK_ITEM.size)
                            )
                            if algo:
                                locs[i] = replace(
                                    locs[i],
                                    block=replace(
                                        locs[i].block,
                                        checksum=crc,
                                        checksum_algo=algo,
                                    ),
                                )
                    else:
                        # count mismatch (corrupt/foreign ext): skip it
                        inp.read(count * cls._CK_ITEM.size)
                    continue
                if marker == cls._DEV_MARKER:
                    if count == len(locs):
                        for i in range(count):
                            coords, handle, offset = cls._DEV_ITEM.unpack(
                                inp.read(cls._DEV_ITEM.size)
                            )
                            if handle:
                                locs[i] = replace(
                                    locs[i],
                                    block=replace(
                                        locs[i].block,
                                        device_coords=coords,
                                        arena_handle=handle,
                                        arena_offset=offset,
                                    ),
                                )
                    else:
                        inp.read(count * cls._DEV_ITEM.size)
                    continue
                if marker == cls._MRG_MARKER:
                    if count == len(locs):
                        for i in range(count):
                            (cover,) = cls._MRG_ITEM.unpack(
                                inp.read(cls._MRG_ITEM.size)
                            )
                            if cover:
                                locs[i] = replace(
                                    locs[i],
                                    block=replace(
                                        locs[i].block, merged_cover=cover
                                    ),
                                )
                    else:
                        inp.read(count * cls._MRG_ITEM.size)
                    continue
                if marker == cls._ELA_MARKER:
                    # items are variable width (fixed header + replica id
                    # bytes), so even the count-mismatch skip must walk
                    # them item by item
                    for i in range(count):
                        source_map, rep_len = cls._ELA_ITEM.unpack(
                            inp.read(cls._ELA_ITEM.size)
                        )
                        rep = inp.read(rep_len).decode("utf-8")
                        if count != len(locs):
                            continue  # corrupt/foreign ext: discard
                        if rep or source_map >= 0:
                            locs[i] = replace(
                                locs[i],
                                block=replace(
                                    locs[i].block,
                                    replica_of=rep,
                                    source_map=source_map,
                                ),
                            )
                    continue
                if marker == cls._FMT_MARKER:
                    if count == len(locs):
                        for i in range(count):
                            (fmt,) = cls._FMT_ITEM.unpack(
                                inp.read(cls._FMT_ITEM.size)
                            )
                            if fmt:
                                locs[i] = replace(
                                    locs[i],
                                    block=replace(
                                        locs[i].block, block_format=fmt
                                    ),
                                )
                    else:
                        inp.read(count * cls._FMT_ITEM.size)
                    continue
                if marker == cls._FLW_MARKER:
                    for _ in range(count):
                        (span,) = cls._FLW_ITEM.unpack(
                            inp.read(cls._FLW_ITEM.size)
                        )
                        if span:
                            origin_span = span
                    continue
                if marker == cls._EPO_MARKER:
                    for _ in range(count):
                        (epoch,) = cls._EPO_ITEM.unpack(
                            inp.read(cls._EPO_ITEM.size)
                        )
                        if epoch:
                            meta_epoch = epoch
                    continue
            inp.seek(pos)
            locs.append(PartitionLocation.read(inp))
        trace_id = 0
        if end - inp.tell() == cls._TRACE_EXT.size:
            (trace_id,) = cls._TRACE_EXT.unpack(inp.read(cls._TRACE_EXT.size))
        return cls(shuffle_id, partition_id, locs, bool(is_last), num_maps,
                   trace_id, origin_span, meta_epoch)


@dataclass
class FetchPartitionLocationsMsg(RpcMsg):
    """Reducer→driver request for locations of partitions [start, end).

    Reference :163-215 fetches a single partitionId per message; the
    range form is a strict superset that collapses the reference's
    per-partition request loop (RdmaShuffleFetcherIterator.scala:220-320)
    into one message per reduce task.
    """

    msg_type = RpcMsgType.FETCH_PARTITION_LOCATIONS

    requester: ShuffleManagerId
    shuffle_id: int
    start_partition: int
    end_partition: int
    # observability: propagated shuffle trace id (0 = unknown). Sent as
    # a trailing 8-byte extension after the legacy 12-byte body; legacy
    # senders (examples/foreign_client.c) omit it and parse as trace 0.
    trace_id: int = 0
    # observability: span id of the reducer-side fetch-request span
    # (0 = none), a second trailing 8-byte extension after trace_id, so
    # the driver's resolve span can causally follow the request. Legacy
    # and trace-only senders omit it and parse as 0.
    origin_span: int = 0

    def to_segments(self, seg_size: int) -> List[bytes]:
        buf = BytesIO()
        self.requester.write(buf)
        buf.write(
            struct.pack(
                ">iiiQQ",
                self.shuffle_id,
                self.start_partition,
                self.end_partition,
                self.trace_id,
                self.origin_span,
            )
        )
        seg = self.frame(self.msg_type, buf.getvalue())
        if len(seg) > seg_size:
            raise ValueError("fetch message exceeds one segment")
        return [seg]

    @classmethod
    def from_payload(cls, payload: bytes) -> "FetchPartitionLocationsMsg":
        inp = BytesIO(payload)
        requester = ShuffleManagerId.read(inp)
        rest = inp.read()
        shuffle_id, start, end = struct.unpack_from(">iii", rest, 0)
        trace_id = struct.unpack_from(">Q", rest, 12)[0] if len(rest) >= 20 else 0
        origin = struct.unpack_from(">Q", rest, 20)[0] if len(rest) >= 28 else 0
        return cls(requester, shuffle_id, start, end, trace_id, origin)


@dataclass
class ManagerHelloMsg(RpcMsg):
    """Executor→driver introduction (reference :217-246)."""

    msg_type = RpcMsgType.MANAGER_HELLO

    manager_id: ShuffleManagerId

    def to_segments(self, seg_size: int) -> List[bytes]:
        seg = self.frame(self.msg_type, self.manager_id.to_bytes())
        if len(seg) > seg_size:
            raise ValueError("hello message exceeds one segment")
        return [seg]

    @classmethod
    def from_payload(cls, payload: bytes) -> "ManagerHelloMsg":
        return cls(ShuffleManagerId.from_bytes(payload))


@dataclass
class AnnounceManagersMsg(RpcMsg):
    """Driver→all broadcast of the full membership (reference :248-307)."""

    msg_type = RpcMsgType.ANNOUNCE_MANAGERS

    manager_ids: List[ShuffleManagerId] = field(default_factory=list)
    is_last: bool = True

    def to_segments(self, seg_size: int) -> List[bytes]:
        budget = seg_size - SEG_HEADER.size - 1
        if budget <= 0:
            raise ValueError(f"segment size {seg_size} too small")
        groups: List[List[ShuffleManagerId]] = [[]]
        used = 0
        for mid in self.manager_ids:
            sz = mid.serialized_size()
            if sz > budget:
                raise ValueError(
                    f"manager id ({sz} bytes) exceeds segment budget {budget}"
                )
            if used + sz > budget and groups[-1]:
                groups.append([])
                used = 0
            groups[-1].append(mid)
            used += sz
        segments = []
        for i, group in enumerate(groups):
            is_last = i == len(groups) - 1
            buf = BytesIO()
            buf.write(struct.pack(">B", 1 if is_last else 0))
            for mid in group:
                mid.write(buf)
            segments.append(self.frame(self.msg_type, buf.getvalue()))
        return segments

    @classmethod
    def from_payload(cls, payload: bytes) -> "AnnounceManagersMsg":
        inp = BytesIO(payload)
        (is_last,) = struct.unpack(">B", inp.read(1))
        mids = []
        end = len(payload)
        while inp.tell() < end:
            mids.append(ShuffleManagerId.read(inp))
        return cls(mids, bool(is_last))
