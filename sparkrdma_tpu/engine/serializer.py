"""Record serialization + stream compression, applied symmetrically.

The reference delegates both to Spark (serializerManager.wrapStream on
read, the serializer instance inside the sort writer) and applies them
symmetrically on write and read (SURVEY.md §5.1 #8; reflected
wrapStream at RdmaShuffleReader.scala:116-126). Here the same contract:
a :class:`Serializer` turns an iterator of (key, value) records into a
byte stream and back, and an optional zlib compression codec wraps both
sides.

Wire format per record: 4-byte length + pickled (k, v) tuple. A zero
length marks end-of-stream (so concatenated partition segments from
different map outputs can be framed independently and read back to
exhaustion of the underlying stream).
"""

from __future__ import annotations

import pickle
import struct
import zlib
from typing import BinaryIO, Iterator, Tuple

_LEN = struct.Struct(">I")


class Serializer:
    name = "base"

    def dump_stream(self, records: Iterator[Tuple], out: BinaryIO) -> None:
        raise NotImplementedError

    def load_stream(self, inp: BinaryIO) -> Iterator[Tuple]:
        raise NotImplementedError


class PickleSerializer(Serializer):
    name = "pickle"

    def dump_stream(self, records, out: BinaryIO) -> None:
        pack = _LEN.pack
        dumps = pickle.dumps
        for rec in records:
            data = dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            out.write(pack(len(data)))
            out.write(data)

    def load_stream(self, inp: BinaryIO):
        unpack = _LEN.unpack
        loads = pickle.loads
        read = inp.read
        while True:
            header = read(4)
            if len(header) < 4:
                return
            (n,) = unpack(header)
            if n == 0:
                return
            data = read(n)
            if len(data) < n:
                raise EOFError("truncated record stream")
            yield loads(data)


class CompressionCodec:
    """zlib stream codec (Spark's lz4 role). Level 1: shuffle wants speed."""

    def __init__(self, enabled: bool = True, level: int = 1):
        self.enabled = enabled
        self.level = level

    def compress(self, data: bytes) -> bytes:
        if not self.enabled:
            return data
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        if not self.enabled:
            return data
        return zlib.decompress(data)


def frame_compressed(codec: CompressionCodec, raw: bytes) -> bytes:
    """Compress one block and length-prefix it — THE wire frame format."""
    block = codec.compress(raw)
    return _LEN.pack(len(block)) + block


class CompressedBlockWriter:
    """Accumulates serialized bytes, emits one compressed block on flush.

    Write side of the symmetric contract: each map task's bytes for one
    partition become one length-prefixed compressed block, so the read
    side can frame blocks from many map outputs concatenated back to
    back.
    """

    def __init__(self, codec: CompressionCodec, sink):
        self._codec = codec
        self._sink = sink  # callable(bytes) → None
        self._buf = bytearray()

    def write(self, data: bytes) -> int:
        self._buf.extend(data)
        return len(data)

    @property
    def pending(self) -> int:
        """Bytes accumulated since the last flush_block."""
        return len(self._buf)

    def flush_block(self) -> int:
        """Compress and emit the accumulated block; returns emitted size."""
        if not self._buf:
            return 0
        framed = frame_compressed(self._codec, bytes(self._buf))
        self._sink(framed)
        self._buf.clear()
        return len(framed)


def iter_compressed_blocks(inp: BinaryIO, codec: CompressionCodec) -> Iterator[bytes]:
    """Read side: yield decompressed blocks until the stream is exhausted."""
    while True:
        header = inp.read(4)
        if len(header) < 4:
            return
        (n,) = _LEN.unpack(header)
        if n == 0:
            return
        block = inp.read(n)
        if len(block) < n:
            raise EOFError("truncated compressed block")
        yield codec.decompress(block)
