"""Record serialization + stream compression, applied symmetrically.

The reference delegates both to Spark (serializerManager.wrapStream on
read, the serializer instance inside the sort writer) and applies them
symmetrically on write and read (SURVEY.md §5.1 #8; reflected
wrapStream at RdmaShuffleReader.scala:116-126). Here the same contract:
a :class:`Serializer` turns an iterator of (key, value) records into a
byte stream and back, and an optional zlib compression codec wraps both
sides.

Wire format per record: 4-byte length + pickled (k, v) tuple. A zero
length marks end-of-stream (so concatenated partition segments from
different map outputs can be framed independently and read back to
exhaustion of the underlying stream).
"""

from __future__ import annotations

import pickle
import struct
import zlib
from typing import BinaryIO, Iterator, Tuple

_LEN = struct.Struct(">I")

# shuffle/columnar.py MAGIC_BYTES, duplicated because the engine layer
# must not import the shuffle package (circular: shuffle.manager imports
# this module). Pinned equal by tests/test_columnar.py.
_COLUMNAR_MAGIC = b"\xa7\xc1"


class Serializer:
    name = "base"

    def dump_stream(self, records: Iterator[Tuple], out: BinaryIO) -> None:
        raise NotImplementedError

    def load_stream(self, inp: BinaryIO) -> Iterator[Tuple]:
        raise NotImplementedError


class PickleSerializer(Serializer):
    name = "pickle"

    def dump_stream(self, records, out: BinaryIO) -> None:
        pack = _LEN.pack
        dumps = pickle.dumps
        for rec in records:
            data = dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            out.write(pack(len(data)))
            out.write(data)

    def load_stream(self, inp: BinaryIO):
        unpack = _LEN.unpack
        loads = pickle.loads
        read = inp.read
        while True:
            header = read(4)
            if len(header) < 4:
                return
            (n,) = unpack(header)
            if n == 0:
                return
            data = read(n)
            if len(data) < n:
                raise EOFError("truncated record stream")
            yield loads(data)

    def load_buffer(self, buf):
        """Zero-copy ``load_stream`` over an in-memory buffer
        (bytes/bytearray/memoryview): records deserialize straight from
        slices of ``buf`` — no BytesIO wrapper, no per-record ``read``
        copies. ``pickle.loads`` accepts buffer objects, so the only
        materialization is the record tuples themselves."""
        view = buf if isinstance(buf, memoryview) else memoryview(buf)
        unpack_from = _LEN.unpack_from
        loads = pickle.loads
        pos, end = 0, len(view)
        while end - pos >= 4:
            (n,) = unpack_from(view, pos)
            pos += 4
            if n == 0:
                return
            if end - pos < n:
                raise EOFError("truncated record stream")
            yield loads(view[pos : pos + n])
            pos += n


class CompressionCodec:
    """zlib stream codec (Spark's lz4 role). Level 1: shuffle wants speed."""

    def __init__(self, enabled: bool = True, level: int = 1):
        self.enabled = enabled
        self.level = level

    def compress(self, data: bytes) -> bytes:
        if not self.enabled:
            return data
        return zlib.compress(data, self.level)

    def decompress(self, data) -> bytes:
        """Accepts bytes OR a memoryview (zlib reads any buffer): the
        read path hands wire slices straight in without copying. With
        compression off the input passes through unchanged — consumers
        must treat the result as a buffer, not assume ``bytes``."""
        if not self.enabled:
            return data
        return zlib.decompress(data)


def frame_compressed(codec: CompressionCodec, raw: bytes) -> bytes:
    """Compress one block and length-prefix it — THE wire frame format."""
    block = codec.compress(raw)
    return _LEN.pack(len(block)) + block


def frame_columnar(payload: bytes) -> bytes:
    """Length-prefix one columnar payload, UNCOMPRESSED.

    Columnar blocks skip the codec on both sides: compression would
    force a decompress copy on read, destroying the zero-copy column
    views, and the payload's magic (shuffle/columnar.py: 0xA7C1 —
    impossible as a zlib header byte or a sane record length) lets
    ``iter_compressed_blocks`` tell the two frame kinds apart, so
    pickle and columnar frames interleave freely in one block."""
    return _LEN.pack(len(payload)) + payload


class CompressedBlockWriter:
    """Accumulates serialized bytes, emits one compressed block on flush.

    Write side of the symmetric contract: each map task's bytes for one
    partition become one length-prefixed compressed block, so the read
    side can frame blocks from many map outputs concatenated back to
    back.
    """

    def __init__(self, codec: CompressionCodec, sink):
        self._codec = codec
        self._sink = sink  # callable(bytes) → None
        self._buf = bytearray()

    def write(self, data: bytes) -> int:
        self._buf.extend(data)
        return len(data)

    @property
    def pending(self) -> int:
        """Bytes accumulated since the last flush_block."""
        return len(self._buf)

    def flush_block(self) -> int:
        """Compress and emit the accumulated block; returns emitted size."""
        if not self._buf:
            return 0
        framed = frame_compressed(self._codec, bytes(self._buf))
        self._sink(framed)
        self._buf.clear()
        return len(framed)


def iter_compressed_blocks(inp: BinaryIO, codec: CompressionCodec) -> Iterator[bytes]:
    """Read side: yield decompressed blocks until the stream is exhausted.

    Streams exposing ``read_view`` (MemoryviewInputStream: registered
    slices, mapped page-cache windows) are sliced zero-copy — the
    compressed frame never materializes as a bytes object. Yielded
    blocks derived from such views are only valid until the stream
    closes; consumers decode fully before closing.

    Columnar frames (first payload bytes = the 0xA7C1 magic,
    shuffle/columnar.py) are framed uncompressed and yielded as-is —
    the raw view passes straight through to the column decoder, never
    touching the codec. Callers sniff the magic per yielded block to
    pick the decode path.
    """
    read_block = getattr(inp, "read_view", inp.read)
    magic = _COLUMNAR_MAGIC
    while True:
        header = inp.read(4)
        if len(header) < 4:
            return
        (n,) = _LEN.unpack(header)
        if n == 0:
            return
        block = read_block(n)
        if len(block) < n:
            raise EOFError("truncated compressed block")
        if n > 2 and bytes(block[:2]) == magic:
            yield block
        else:
            yield codec.decompress(block)
