from sparkrdma_tpu.engine.serializer import PickleSerializer, Serializer

__all__ = ["PickleSerializer", "Serializer", "TpuContext"]


def __getattr__(name):
    # lazy to avoid a circular import with shuffle.handle
    if name == "TpuContext":
        from sparkrdma_tpu.engine.context import TpuContext

        return TpuContext
    raise AttributeError(name)
