"""Executor worker process — `python -m sparkrdma_tpu.engine.worker`.

The reference's process topology is one endpoint per *JVM*: executors
are separate processes that register with the driver and serve/pull
shuffle blocks over the network (SURVEY.md §1 "Process topology").
This module is that executor process for the TPU framework: it owns a
full `TpuShuffleManager` (transport endpoint, registered memory,
writers/readers) plus a small task server through which the driver
dispatches map/reduce closures (the Spark-core role the reference
delegates to Spark; closures travel via cloudpickle).

Task protocol (length-prefixed cloudpickle, one request per
connection): {"kind": "map" | "map_batch" | "reduce" | "finalize" |
"ping" | "stop", ...} -> {"ok": bool, "result"/"error": ...}.

Map tasks — single or batched — run through the manager's bounded
``map_pool`` (conf ``map.parallelism``), so per-process map concurrency
is the config knob regardless of how many task connections the driver
opens. ``map_batch`` ships a whole stage's tasks for this worker in ONE
request (one socket round trip instead of one per map) and runs them
concurrently up to the pool bound.
"""

from __future__ import annotations

import argparse
import json
import socket
import struct
import threading
import time
import traceback

import cloudpickle

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.obs.metrics import get_registry
from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler
from sparkrdma_tpu.obs.telemetry import Heartbeater
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.utils.config import TpuShuffleConf

_LEN = struct.Struct(">I")


def _recv_obj(sock: socket.socket):
    schedule_point("proto", "task.recv")
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return cloudpickle.loads(bytes(buf))


def _send_obj(sock: socket.socket, obj) -> None:
    schedule_point("proto", "task.send")
    data = cloudpickle.dumps(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


class Worker:
    def __init__(self, conf: TpuShuffleConf, executor_id: str):
        self.manager = TpuShuffleManager(conf, is_driver=False, executor_id=executor_id)
        self.manager.start_node_if_missing()  # hello to driver now
        self._stop = threading.Event()
        # in-flight reduce readers keyed (shuffle_id, start, end) so a
        # cancel_reduce request can fire the pipeline's abort latch
        self._reduces: dict = {}
        self._reduce_lock = threading.Lock()
        # continuous profiling: this process's wall-clock sampler; its
        # collapsed-stack tables ride the heartbeat payloads below
        self.profiler = acquire_profiler(conf, role=executor_id)
        # outbox-mode heartbeater: samples role-filtered registry deltas
        # on a timer; the driver pulls them with {"kind": "telemetry"}
        self.heartbeater = None
        if conf.telemetry_enabled:
            # arm the process event journal: this worker's control-plane
            # transitions (circuit trips, quota blocks) ride the
            # heartbeat payloads below into the driver's merged journal
            from sparkrdma_tpu.obs import journal as _journal

            _journal.configure(conf, role=executor_id)
            self.heartbeater = Heartbeater(
                get_registry(),
                executor_id,
                interval_ms=conf.telemetry_interval_ms,
                match={"role": executor_id},
                profiler=self.profiler,
            ).start()

    def _run_map(self, handle, map_id, records_fn) -> None:
        t0 = time.perf_counter()
        plan = _faults.active()
        if plan is not None:
            plan.on_exec(self.manager.executor_id, stage="map_task")
            plan.on_stage("map_task", [], peer=self.manager.executor_id)
        try:
            with self.manager.tracer.span(
                "engine.task", kind="map", partition=map_id
            ):
                writer = self.manager.get_writer(handle, map_id)
                try:
                    writer.write(records_fn())
                    writer.stop(True)
                except Exception:
                    writer.stop(False)
                    raise
        finally:
            get_registry().histogram(
                "engine.task_ms", role=self.manager.executor_id, kind="map",
                tenant=tenancy.current_tenant(),
            ).observe((time.perf_counter() - t0) * 1000.0)

    def handle(self, req):
        kind = req["kind"]
        if kind == "ping":
            return {"ok": True, "result": "pong"}
        if kind == "map":
            # single map: still bounded by the pool so concurrent task
            # connections can't oversubscribe the process
            with tenancy.tenant_scope(req.get("tenant")):
                self.manager.map_pool.submit(
                    self._run_map, req["handle"], req["map_id"], req["records_fn"]
                ).result()
            return {"ok": True}
        if kind == "map_batch":
            # one request, N map tasks, bounded concurrency: every task
            # goes through the map pool; the first failure propagates
            # after ALL have settled (writers must reach stop() so a
            # failed task poisons/aborts cleanly before the reply)
            routes = req.get("push_routes")
            if routes and self.manager.push_client is not None:
                # {executor_id: (host, task_port)}: where this worker's
                # push client ships sealed blocks (shuffle/merge.py)
                self.manager.push_client.set_routes(routes)
            if routes and self.manager.replica_client is not None:
                # the replication plane rides the same routes (elastic/)
                self.manager.replica_client.set_routes(routes)
            # the submit captures the tenant scope, so the fair-share
            # pool queues this batch under the requesting tenant
            with tenancy.tenant_scope(req.get("tenant")):
                futures = [
                    self.manager.map_pool.submit(
                        self._run_map, req["handle"], mid, fn
                    )
                    for mid, fn in req["tasks"]
                ]
            errors = [f.exception() for f in futures]
            errors = [e for e in errors if e is not None]
            if errors:
                raise errors[0]
            return {"ok": True}
        if kind == "finalize":
            self.manager.finalize_maps(req["shuffle_id"])
            return {"ok": True}
        if kind == "republish":
            # control-plane HA re-adoption ladder (sparkrdma_tpu/
            # metastore): the driver's hub was wiped; re-publish every
            # committed map output and parked replica this executor
            # holds, fenced by the new generation
            n = self.manager.republish_for_readoption(
                req.get("meta_epoch", 0)
            )
            return {"ok": True, "result": n}
        if kind == "push_blocks":
            # push/merge plane ingest (shuffle/merge.py): the reply is
            # sent only after any seal-and-publish this batch triggers,
            # so a synchronous pushing finalizer gets ordering for free
            ep = self.manager.merge_endpoint
            accepted = 0
            if ep is not None:
                blocks = req.get("blocks") or []
                if req.get("blocks_rd"):
                    # descriptor mode (transport/staging.py): the bytes
                    # are staged in the sender's ProtectionDomain —
                    # pull them over the data plane before merging; the
                    # reply below releases the sender's registrations
                    from sparkrdma_tpu.transport.staging import pull_payloads

                    payloads = pull_payloads(
                        self.manager.node,
                        req["data_addr"],
                        [(mk, ln) for _, _, mk, ln in req["blocks_rd"]],
                    )
                    blocks = [
                        (pid, seq, data)
                        for (pid, seq, _, _), data in zip(
                            req["blocks_rd"], payloads
                        )
                    ]
                accepted = ep.push_blocks(
                    req["shuffle_id"],
                    req["source"],
                    blocks,
                    req.get("final"),
                    req.get("origin_span", 0),
                    req.get("origin_trace", 0),
                )
            return {"ok": True, "result": accepted}
        if kind == "reduce":
            handle = req["handle"]
            t0 = time.perf_counter()
            plan = _faults.active()
            if plan is not None:
                plan.on_exec(self.manager.executor_id, stage="reduce_task")
                plan.on_stage("reduce_task", [], peer=self.manager.executor_id)
            rkey = (handle.shuffle_id, req["start"], req["end"])
            with tenancy.tenant_scope(req.get("tenant")):
                reader = self.manager.get_reader(handle, req["start"], req["end"])
                with self._reduce_lock:
                    self._reduces[rkey] = reader
                try:
                    with self.manager.tracer.span(
                        "engine.task", kind="reduce", start=req["start"],
                        end=req["end"],
                    ):
                        it = reader.read()
                        fn = req.get("reduce_fn")
                        result = fn(it) if fn is not None else list(it)
                finally:
                    with self._reduce_lock:
                        if self._reduces.get(rkey) is reader:
                            del self._reduces[rkey]
                    # task-completion sweep: a reduce_fn that bails without
                    # consuming must not strand fetched streams until GC
                    reader.close()
                    get_registry().histogram(
                        "engine.task_ms", role=self.manager.executor_id,
                        kind="reduce", tenant=tenancy.current_tenant(),
                    ).observe((time.perf_counter() - t0) * 1000.0)
            return {"ok": True, "result": result}
        if kind == "cancel_reduce":
            # speculation loser drain (elastic/speculation.py): closing
            # the in-flight reader fires the reduce pipeline's abort
            # latch; the losing task thread unwinds instead of finishing
            rkey = (req["shuffle_id"], req["start"], req["end"])
            with self._reduce_lock:
                reader = self._reduces.pop(rkey, None)
            if reader is not None:
                try:
                    reader.close()
                except Exception:
                    pass
            return {"ok": True, "result": reader is not None}
        if kind == "replicate_blocks":
            # elastic replication ingest (elastic/replication.py): the
            # reply is sent only after the replica locations published,
            # so the source's map task never outruns its durability
            store = self.manager.replica_store
            accepted = 0
            if store is not None:
                blocks = req.get("blocks") or []
                if req.get("blocks_rd"):
                    # descriptor mode (transport/staging.py): replica
                    # bytes ride the data plane; the reply releases the
                    # source's registrations
                    from sparkrdma_tpu.transport.staging import pull_payloads

                    payloads = pull_payloads(
                        self.manager.node,
                        req["data_addr"],
                        [(mk, ln) for _, mk, ln in req["blocks_rd"]],
                    )
                    blocks = [
                        (pid, data)
                        for (pid, _, _), data in zip(
                            req["blocks_rd"], payloads
                        )
                    ]
                accepted = store.accept(
                    req["shuffle_id"],
                    req["source"],
                    req["map_id"],
                    blocks,
                )
            return {"ok": True, "result": accepted}
        if kind == "handoff":
            # shuffle-service handoff (elastic/service.py): describe all
            # committed map outputs by file path + partition lengths and
            # ask the daemon to adopt them — no byte copy; the daemon
            # hard-links and re-mmaps the same inodes
            from sparkrdma_tpu.elastic.service import send_adopt

            host, port = req["service"]
            manifests = {}
            for sid in self.manager.resolver.shuffle_ids():
                data = self.manager.resolver.get_shuffle_data(sid)
                manifest = getattr(data, "handoff_manifest", None)
                if manifest is not None:
                    maps = manifest()
                    if maps:
                        manifests[sid] = maps
            adopted = send_adopt(
                (host, port), self.manager.executor_id, manifests
            )
            return {"ok": True, "result": adopted}
        if kind == "telemetry":
            # control-plane pull: hand buffered heartbeats to the driver
            payloads = (
                self.heartbeater.drain() if self.heartbeater is not None else []
            )
            return {"ok": True, "result": payloads}
        if kind == "stop":
            self._stop.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown task kind {kind!r}"}

    def serve(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(16)
        srv.settimeout(0.2)
        # announce the task port to the parent (driver) on stdout
        print(f"WORKER_PORT {srv.getsockname()[1]}", flush=True)

        def one(conn):
            try:
                req = _recv_obj(conn)
                try:
                    resp = self.handle(req)
                except Exception as e:
                    resp = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "traceback": traceback.format_exc(),
                    }
                _send_obj(conn, resp)
            except Exception:
                pass
            finally:
                conn.close()

        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=one, args=(conn,), daemon=True).start()
        srv.close()
        if self.heartbeater is not None:
            self.heartbeater.stop(flush=False)  # nobody left to pull
        release_profiler(self.profiler)
        self.profiler = None
        self.manager.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--executor-id", required=True)
    ap.add_argument("--conf", required=True, help="JSON dict of tpu.shuffle.* keys")
    args = ap.parse_args()
    conf = TpuShuffleConf(json.loads(args.conf))
    Worker(conf, args.executor_id).serve()


if __name__ == "__main__":
    main()
