"""Minimal resilient-dataset API hosting the shuffle framework.

The reference is a plugin inside Spark; Spark itself supplies the
DAGScheduler, ShuffledRDD and task execution (SURVEY.md §1 "Sits
above"). This module supplies that host role so workloads (TeraSort,
WordCount, PageRank, ALS — BASELINE.md configs) can run end-to-end on
the TPU shuffle manager: lazy lineage of narrow ops, wide ops cut at
shuffle dependencies, stage recompute on fetch failure.
"""

from __future__ import annotations

import itertools
import random
from typing import Callable, Iterator, List, Optional

from sparkrdma_tpu.shuffle.handle import (
    Aggregator,
    BaseShuffleHandle,
    HashPartitioner,
    Partitioner,
    RangePartitioner,
)


class RDD:
    def __init__(self, ctx, num_partitions: int):
        self.ctx = ctx
        self.num_partitions = num_partitions
        self.rdd_id = ctx._next_rdd_id()

    def compute(self, partition: int) -> Iterator:
        raise NotImplementedError

    # -- narrow transformations ----------------------------------------
    def map(self, f: Callable) -> "RDD":
        return MapPartitionsRDD(self, lambda it: (f(x) for x in it))

    def flat_map(self, f: Callable) -> "RDD":
        return MapPartitionsRDD(
            self, lambda it: (y for x in it for y in f(x))
        )

    def filter(self, f: Callable) -> "RDD":
        return MapPartitionsRDD(self, lambda it: (x for x in it if f(x)))

    def map_partitions(self, f: Callable[[Iterator], Iterator]) -> "RDD":
        return MapPartitionsRDD(self, f)

    def key_by(self, f: Callable) -> "RDD":
        return self.map(lambda x: (f(x), x))

    # -- wide transformations (shuffle boundaries) ---------------------
    def partition_by(self, partitioner: Partitioner) -> "RDD":
        return ShuffledRDD(self, partitioner)

    def reduce_by_key(self, f: Callable, num_partitions: Optional[int] = None) -> "RDD":
        agg = Aggregator(lambda v: v, f, f)
        return ShuffledRDD(
            self,
            HashPartitioner(num_partitions or self.num_partitions),
            aggregator=agg,
            map_side_combine=True,
        )

    def group_by_key(self, num_partitions: Optional[int] = None) -> "RDD":
        agg = Aggregator(
            lambda v: [v],
            lambda c, v: (c.append(v), c)[1],
            lambda a, b: a + b,
        )
        return ShuffledRDD(
            self,
            HashPartitioner(num_partitions or self.num_partitions),
            aggregator=agg,
        )

    def sort_by_key(self, num_partitions: Optional[int] = None) -> "RDD":
        """Total order: range-partition on sampled bounds + per-partition sort."""
        n = num_partitions or self.num_partitions
        bounds = self._sample_bounds(n)
        return ShuffledRDD(self, RangePartitioner(bounds), key_ordering=True)

    def _sample_bounds(self, num_partitions: int, sample_per_part: int = 200) -> List:
        if num_partitions <= 1:
            return []
        sample: List = []
        for p in range(self.num_partitions):
            it = self.compute_via_ctx(p)
            part_sample = list(itertools.islice(it, sample_per_part * 5))
            if len(part_sample) > sample_per_part:
                part_sample = random.Random(17 + p).sample(part_sample, sample_per_part)
            sample.extend(k for k, _ in part_sample)
        if not sample:
            return []
        sample.sort()
        step = len(sample) / num_partitions
        bounds = [sample[int(step * i)] for i in range(1, num_partitions)]
        # dedupe to keep RangePartitioner sound on skewed keys
        out: List = []
        for b in bounds:
            if not out or b > out[-1]:
                out.append(b)
        return out

    def join(self, other: "RDD", num_partitions: Optional[int] = None) -> "RDD":
        """Hash join via cogroup semantics on a shared shuffle."""
        n = num_partitions or max(self.num_partitions, other.num_partitions)
        tagged = self.map(lambda kv: (kv[0], (0, kv[1]))).union(
            other.map(lambda kv: (kv[0], (1, kv[1])))
        )
        grouped = tagged.group_by_key(n)

        def emit(kv):
            k, vals = kv
            left = [v for tag, v in vals if tag == 0]
            right = [v for tag, v in vals if tag == 1]
            return [(k, (loc, r)) for loc in left for r in right]

        return grouped.flat_map(emit)

    def union(self, other: "RDD") -> "RDD":
        return UnionRDD(self, other)

    # -- actions --------------------------------------------------------
    def collect(self) -> List:
        return self.ctx.run_job(self)

    def count(self) -> int:
        return len(self.collect())

    def reduce(self, f: Callable):
        vals = self.collect()
        import functools

        return functools.reduce(f, vals)

    def compute_via_ctx(self, partition: int) -> Iterator:
        """Compute one partition, materializing parent shuffles first."""
        self.ctx.ensure_parents(self)
        return self.compute(partition)


class ParallelCollectionRDD(RDD):
    def __init__(self, ctx, data: List, num_partitions: int):
        super().__init__(ctx, num_partitions)
        self._slices: List[List] = [[] for _ in range(num_partitions)]
        for i, item in enumerate(data):
            self._slices[i % num_partitions].append(item)

    def compute(self, partition: int) -> Iterator:
        return iter(self._slices[partition])


class GeneratorRDD(RDD):
    """Partitions produced by a generator fn(partition_index) → iterator."""

    def __init__(self, ctx, gen: Callable[[int], Iterator], num_partitions: int):
        super().__init__(ctx, num_partitions)
        self._gen = gen

    def compute(self, partition: int) -> Iterator:
        return self._gen(partition)


class MapPartitionsRDD(RDD):
    def __init__(self, parent: RDD, f: Callable[[Iterator], Iterator]):
        super().__init__(parent.ctx, parent.num_partitions)
        self.parent = parent
        self.f = f

    def compute(self, partition: int) -> Iterator:
        return self.f(self.parent.compute(partition))


class UnionRDD(RDD):
    def __init__(self, a: RDD, b: RDD):
        super().__init__(a.ctx, a.num_partitions + b.num_partitions)
        self.a = a
        self.b = b

    def compute(self, partition: int) -> Iterator:
        if partition < self.a.num_partitions:
            return self.a.compute(partition)
        return self.b.compute(partition - self.a.num_partitions)


class ShuffledRDD(RDD):
    def __init__(
        self,
        parent: RDD,
        partitioner: Partitioner,
        aggregator: Optional[Aggregator] = None,
        map_side_combine: bool = False,
        key_ordering: bool = False,
    ):
        super().__init__(parent.ctx, partitioner.num_partitions)
        self.parent = parent
        self.partitioner = partitioner
        self.aggregator = aggregator
        self.map_side_combine = map_side_combine
        self.key_ordering = key_ordering
        self.handle: Optional[BaseShuffleHandle] = None  # set when materialized

    def compute(self, partition: int) -> Iterator:
        assert self.handle is not None, "shuffle not materialized"
        executor = self.ctx.executor_for_partition(partition)
        reader = executor.get_reader(self.handle, partition, partition + 1)

        def closing():
            # reader.close() on exit (success OR mid-iteration abandon):
            # unconsumed fetched streams release deterministically
            try:
                yield from reader.read()
            finally:
                reader.close()

        return closing()
