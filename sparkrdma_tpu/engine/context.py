"""TpuContext — driver + executors + stage scheduler.

The Spark-role host: owns one driver TpuShuffleManager (metadata hub)
and N executor managers (each a full transport endpoint, as in the
reference's process topology — SURVEY.md §1 "Process topology"), cuts
the RDD lineage at shuffle dependencies, runs map stages with a
barrier, and recomputes stages on fetch failure (the reference
delegates recompute to Spark via FetchFailedException;
RdmaShuffleFetcherIterator.scala:381-391).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from sparkrdma_tpu.engine.rdd import (
    GeneratorRDD,
    ParallelCollectionRDD,
    RDD,
    ShuffledRDD,
)
from sparkrdma_tpu.obs.metrics import get_registry
from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler
from sparkrdma_tpu.obs.telemetry import Heartbeater
from sparkrdma_tpu.shuffle.errors import ShuffleError
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu import tenancy
from sparkrdma_tpu.tenancy import FairShareExecutor
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


class TpuContext:
    def __init__(
        self,
        num_executors: int = 2,
        conf: Optional[TpuShuffleConf] = None,
        task_threads: int = 4,
    ):
        self.conf = conf or TpuShuffleConf()
        self.driver = TpuShuffleManager(self.conf, is_driver=True)
        self.executors: List[TpuShuffleManager] = [
            TpuShuffleManager(self.conf, is_driver=False, executor_id=f"exec-{i}")
            for i in range(num_executors)
        ]
        # reduce-task pool: deficit-round-robin across tenants when
        # tenancy is on (one tenant's 1000 queued partitions cannot
        # convoy another's 10), plain FIFO otherwise
        if self.conf.tenancy_enabled:
            self._pool = FairShareExecutor(
                max_workers=task_threads,
                weights=self.conf.tenancy_weights,
                default_weight=self.conf.tenancy_default_weight,
                quantum_ms=self.conf.tenancy_quantum_ms,
                thread_name_prefix="reduce",
                pool="reduce",
            )
        else:
            self._pool = ThreadPoolExecutor(max_workers=task_threads)
        self._id_lock = threading.Lock()
        self._rdd_counter = 0
        self._shuffle_counter = 0
        self._stopped = False
        # last finished job's critical-path attribution verdict
        # (obs/attr.py TimeBreakdown), surfaced via metrics_snapshot()
        self.last_breakdown = None
        # continuous profiling (obs/profiler.py): one refcounted sampler
        # for the whole process — the in-process topology shares every
        # thread, so its table rides the FIRST executor's heartbeat
        self.profiler = acquire_profiler(self.conf, role="proc")
        # in-process topology: heartbeats push straight into the driver
        # hub (no control-plane hop); each executor samples its own
        # role-filtered view of the shared process registry
        self.heartbeaters: List[Heartbeater] = []
        if self.driver.telemetry is not None:
            for executor in self.executors:
                self.heartbeaters.append(
                    Heartbeater(
                        get_registry(),
                        executor.executor_id,
                        interval_ms=self.conf.telemetry_interval_ms,
                        send=self.driver.telemetry.ingest,
                        match={"role": executor.executor_id},
                    ).start()
                )
            if self.heartbeaters:
                self.heartbeaters[0].attach_profiler(self.profiler)

    # ------------------------------------------------------------------
    def _next_rdd_id(self) -> int:
        with self._id_lock:
            self._rdd_counter += 1
            return self._rdd_counter

    def _next_shuffle_id(self) -> int:
        with self._id_lock:
            self._shuffle_counter += 1
            return self._shuffle_counter

    def executor_for_partition(self, partition: int) -> TpuShuffleManager:
        return self.executors[partition % len(self.executors)]

    def lose_executor(self, executor_id: str) -> None:
        """Simulate executor death in the in-process topology
        (DESIGN.md §21): drop the executor from the partition router,
        run the driver's peer-loss path — prune, replica promotion,
        barrier re-arm — then release the lost manager's resources
        (a dead process never unpublishes, so the teardown happens
        only AFTER the prune, and quietly).

        With replica coverage (`tpu.shuffle.elastic.replicas` > 0)
        later reads complete against the promoted holders with zero
        recompute; without it they defer into
        MetadataFetchFailedError and ``run_job``'s stage-recompute
        attempt re-runs the lost maps on the survivors."""
        lost = next(
            (m for m in self.executors if m.executor_id == executor_id), None
        )
        if lost is None:
            raise KeyError(f"unknown executor {executor_id!r}")
        if len(self.executors) == 1:
            raise ValueError("cannot lose the last executor")
        self.executors = [m for m in self.executors if m is not lost]
        self.driver._on_peer_lost(lost.executor_id)
        lost.stop()

    def _driver_failover(self) -> None:
        """In-process control-plane HA chaos rig (the ``driver:kill``
        fault): wipe the metadata hub, sweep every live executor's
        committed map outputs and parked replicas back in (fenced by
        the new generation), then replay pre-crash executor losses so
        their re-parked replicas promote again — re-publish, never
        recompute (docs/RESILIENCE.md "Control-plane HA")."""
        t0 = time.perf_counter()
        generation = self.driver.metastore_crash()
        for executor in self.executors:
            executor.republish_for_readoption(generation)
        with self.driver._lock:
            lost = sorted(self.driver._lost_executors)
        for exec_id in lost:
            self.driver._on_peer_lost(exec_id)
        get_registry().histogram(
            "metastore.readoption_ms", role=self.driver.executor_id
        ).observe((time.perf_counter() - t0) * 1e3)
        logger.warning(
            "driver failover complete: generation %d, %d pre-crash "
            "losses replayed", generation, len(lost),
        )

    # ------------------------------------------------------------------
    def parallelize(self, data, num_partitions: int = None) -> RDD:
        n = num_partitions or len(self.executors)
        return ParallelCollectionRDD(self, list(data), n)

    def generate(self, gen, num_partitions: int) -> RDD:
        return GeneratorRDD(self, gen, num_partitions)

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def ensure_parents(self, rdd: RDD) -> None:
        """Materialize every un-materialized shuffle below rdd."""
        for dep in self._shuffle_deps(rdd):
            if dep.handle is None:
                self._run_map_stage(dep)

    def _shuffle_deps(self, rdd: RDD) -> List[ShuffledRDD]:
        """Direct shuffle dependencies (stage boundary cut)."""
        out: List[ShuffledRDD] = []
        seen = set()

        def walk(r: RDD) -> None:
            if id(r) in seen:
                return
            seen.add(id(r))
            if isinstance(r, ShuffledRDD):
                out.append(r)
                return  # deeper deps handled when r's map stage runs
            for attr in ("parent", "a", "b"):
                child = getattr(r, attr, None)
                if isinstance(child, RDD):
                    walk(child)

        walk(rdd)
        return out

    def _run_map_stage(self, dep: ShuffledRDD, attempts: int = 2) -> None:
        """Run the parent stage of a shuffle with a completion barrier.

        Transient map-task failures retry the whole stage under a fresh
        shuffle id (the failed id is unregistered so its deferred-fetch
        state doesn't linger on the driver).
        """
        parent = dep.parent
        self.ensure_parents(parent)  # recursive stage materialization

        last_error: Optional[Exception] = None
        for attempt in range(attempts):
            shuffle_id = self._next_shuffle_id()
            handle = BaseShuffleHandle(
                shuffle_id=shuffle_id,
                num_maps=parent.num_partitions,
                partitioner=dep.partitioner,
                aggregator=dep.aggregator,
                map_side_combine=dep.map_side_combine,
                key_ordering=dep.key_ordering,
            )
            self.driver.register_shuffle(handle)

            def run_map(map_id: int) -> None:
                executor = self.executor_for_partition(map_id)
                t0 = time.perf_counter()
                plan = _faults.active()
                if plan is not None:
                    plan.on_stage("map_task", [], peer=executor.executor_id)
                try:
                    with executor.tracer.span(
                        "engine.task", kind="map", partition=map_id
                    ):
                        writer = executor.get_writer(handle, map_id)
                        try:
                            writer.write(parent.compute(map_id))
                            writer.stop(True)
                        except Exception:
                            writer.stop(False)
                            raise
                finally:
                    get_registry().histogram(
                        "engine.task_ms", role=executor.executor_id,
                        kind="map", tenant=tenancy.current_tenant(),
                    ).observe((time.perf_counter() - t0) * 1000.0)

            # dispatch each map through ITS executor's bounded map pool
            # (conf map.parallelism) — per-executor concurrency is the
            # config knob, not an artifact of the context's task pool
            futures = [
                self.executor_for_partition(m).map_pool.submit(run_map, m)
                for m in range(parent.num_partitions)
            ]
            errors = [f.exception() for f in futures if f.exception() is not None]
            if not errors:
                for executor in self.executors:
                    executor.finalize_maps(shuffle_id)
                dep.handle = handle
                return
            last_error = errors[0]
            logger.warning(
                "map stage for shuffle %d failed (attempt %d/%d): %s",
                shuffle_id,
                attempt + 1,
                attempts,
                last_error,
            )
            self.driver.unregister_shuffle(shuffle_id)
            for executor in self.executors:
                executor.unregister_shuffle(shuffle_id)
        assert last_error is not None
        raise last_error

    def _partition_weights(self, rdd: RDD) -> Dict[int, int]:
        """Published per-partition byte totals of rdd's direct shuffle
        dependency, when the partition counts line up — the adaptive
        scheduling signal (shuffle/planner.py): the heaviest reduce
        partitions are SUBMITTED first so a hot partition never starts
        last and stretches the stage tail behind the task-pool bound.
        Results still collect in partition order."""
        if not self.conf.planner_enabled:
            return {}
        for dep in self._shuffle_deps(rdd):
            if (
                dep.handle is not None
                and dep.partitioner.num_partitions == rdd.num_partitions
            ):
                sizes = self.driver.partition_sizes(dep.handle.shuffle_id)
                if sizes:
                    return sizes
        return {}

    def run_job(self, rdd: RDD, tenant: Optional[str] = None) -> List:
        """Compute all partitions of rdd; recompute stages on fetch failure.

        ``tenant`` names the job's owner for admission, fair-share
        dispatch, quotas, breaker scoping, and obs labels (defaults to
        the calling thread's tenant scope). Admission brackets the
        WHOLE job including recompute attempts — the in-flight bound
        counts jobs, not stages."""
        t = tenant or tenancy.current_tenant()
        admission = self.driver.admission
        with tenancy.tenant_scope(t):
            if admission is None:
                return self._run_job_admitted(rdd, t)
            with admission.admit(t):
                return self._run_job_admitted(rdd, t)

    def _run_job_admitted(self, rdd: RDD, tenant: str) -> List:
        for attempt in range(2):
            jsp = None
            try:
                # the job span bounds the critical-path window
                # (obs/critpath.py); every map/reduce span of this
                # attempt lands inside it on the shared timeline
                with self.driver.tracer.span(
                    "job.run", tenant=tenant, attempt=attempt
                ) as jsp:
                    self.ensure_parents(rdd)
                    # driver:kill chaos seam (testing/faults.py): the
                    # hub dies between the map barrier and the reduce
                    # fan-out — worst case for metadata loss — and the
                    # job must finish byte-identical via re-adoption
                    plan = _faults.active()
                    if plan is not None and plan.on_driver(
                        stage="reduce_phase"
                    ):
                        self._driver_failover()
                    order = list(range(rdd.num_partitions))
                    weights = self._partition_weights(rdd)
                    if weights:
                        order.sort(key=lambda p: -weights.get(p, 0))

                    def run_reduce(p: int) -> List:
                        t0 = time.perf_counter()
                        try:
                            # task span: keeps the critical path lit
                            # across user compute (obs/attr.py)
                            with self.driver.tracer.span(
                                "engine.task", kind="reduce", partition=p
                            ):
                                return list(rdd.compute(p))
                        finally:
                            get_registry().histogram(
                                "engine.task_ms", role="driver", kind="reduce",
                                tenant=tenancy.current_tenant(),
                            ).observe((time.perf_counter() - t0) * 1000.0)

                    futures = {
                        p: self._pool.submit(run_reduce, p)
                        for p in order
                    }
                    out: List = []
                    errors = []
                    for p in range(rdd.num_partitions):
                        f = futures[p]
                        e = f.exception()
                        if e is not None:
                            errors.append(e)
                        else:
                            out.extend(f.result())
                    if errors:
                        raise errors[0]
                self._attribute_job(jsp)
                return out
            except ShuffleError as e:
                if self.driver.telemetry is not None:
                    # post-mortem artifact BEFORE recompute mutates state
                    bd = self._attribute_job(jsp)
                    self.driver.telemetry.flight_record(
                        "fetch_failed", error=e,
                        breakdown=bd.to_dict() if bd is not None else None,
                    )
                if attempt == 1:
                    raise
                get_registry().counter("engine.stage_recomputes").inc()
                logger.warning("fetch failed (%s); recomputing stages", e)
                # invalidate materialized shuffles below rdd and retry
                for dep in self._shuffle_deps(rdd):
                    dep.handle = None
        raise RuntimeError("unreachable")

    def _attribute_job(self, job_span):
        """Fold the finished (or failed) job span's window into a
        TimeBreakdown (obs/critpath.py). Best-effort: attribution can
        never fail a job. Returns the verdict (also kept as
        ``self.last_breakdown``) or None when gated off."""
        if job_span is None or not self.conf.critpath_enabled:
            return None
        try:
            from sparkrdma_tpu.obs.critpath import job_breakdown

            self.last_breakdown = job_breakdown(job_span, role="driver")
            if self.driver.telemetry is not None:
                # diagnosis evidence: the SLO engine's root-cause pass
                # reads the dominant category from the hub
                self.driver.telemetry.note_breakdown(
                    self.last_breakdown.to_dict()
                )
            return self.last_breakdown
        except Exception:
            logger.exception("critical-path attribution failed")
            return None

    # ------------------------------------------------------------------
    def metrics_snapshot(self) -> Dict[str, dict]:
        """Per-role manager snapshots plus the process-wide registry.

        In this in-process topology every manager shares one registry,
        so ``registry`` is reported once at the top level (the per-role
        entries keep their role-filtered view from
        ``TpuShuffleManager.metrics_snapshot``)."""
        snap: Dict[str, dict] = {
            "driver": self.driver.metrics_snapshot(),
        }
        for executor in self.executors:
            snap[executor.executor_id] = executor.metrics_snapshot()
        snap["registry"] = get_registry().snapshot()
        if self.last_breakdown is not None:
            snap["breakdown"] = self.last_breakdown.to_dict()
        if self.driver.telemetry is not None:
            snap["slo"] = self.driver.telemetry.slo.summary()
        return snap

    def telemetry_flush(self) -> None:
        """Force one heartbeat from every executor NOW (tests/benches:
        deterministic hub state without waiting out the interval)."""
        for hb in self.heartbeaters:
            hb.beat()

    def export_trace(self, path: str) -> dict:
        """Write the Chrome-trace JSON for every role's tracer."""
        from sparkrdma_tpu.obs import export_chrome_trace

        return export_chrome_trace(path)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._pool.shutdown(wait=True)
        for hb in self.heartbeaters:
            hb.stop(flush=True)  # final delta lands in the hub
        release_profiler(self.profiler)
        self.profiler = None
        for executor in self.executors:
            executor.stop()
        self.driver.stop()

    def __enter__(self) -> "TpuContext":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
