"""ClusterContext — real multi-process map/shuffle/reduce jobs.

The in-process :class:`~sparkrdma_tpu.engine.context.TpuContext` runs
executors as threads; this runs them as genuine OS processes (the
reference's one-endpoint-per-JVM topology, SURVEY.md §1): the driver
process owns the metadata-hub manager, each executor subprocess owns a
full transport endpoint, map outputs stage in the *executor's*
registered memory, and reducers pull them executor-to-executor with
one-sided READs — the driver never touches data.

Closures ship via cloudpickle over a tiny task protocol
(`engine/worker.py`); the shuffle itself rides the framework's own
control + data planes (python or native transport per conf).

Elastic behavior (docs/DESIGN.md §21): the driver survives executor
loss. Map and reduce phases both run under a bounded recovery loop —
when a worker process dies, the driver prunes it (``_on_peer_lost``
promotes any replicas), re-runs exactly the *unaccounted* maps (those
neither a surviving publish nor a promoted replica covers) on
survivors, and re-issues the dead worker's reduce ranges. The reduce
fan-out itself is the speculative phase from elastic/speculation.py:
straggler-flagged attempts get cloned, first finisher wins.
"""

from __future__ import annotations

import json
import logging
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.engine.worker import _recv_obj, _send_obj
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner, Partitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, executor_id: str, task_port: int):
        self.proc = proc
        self.executor_id = executor_id
        self.task_port = task_port

    def request(self, obj, timeout_s: float = 120.0):
        with socket.create_connection(("127.0.0.1", self.task_port), timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            _send_obj(s, obj)
            resp = _recv_obj(s)
        if not resp.get("ok"):
            raise RuntimeError(
                f"task failed on {self.executor_id}: {resp.get('error')}\n"
                f"{resp.get('traceback', '')}"
            )
        return resp.get("result")


class ClusterContext:
    """Driver-side handle to a multi-process executor cluster."""

    def __init__(
        self,
        num_executors: int = 2,
        conf: Optional[TpuShuffleConf] = None,
        start_timeout_s: float = 30.0,
    ):
        self.conf = conf or TpuShuffleConf()
        self.driver = TpuShuffleManager(self.conf, is_driver=True)
        self.workers: List[WorkerHandle] = []
        self._shuffle_counter = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max(4, num_executors * 2))
        # last finished job's critical-path verdict (obs/attr.py)
        self.last_breakdown = None
        # driver-process sampler: workers run their own (engine/worker.py)
        # and ship tables in heartbeats; the driver's feeds gap-frame
        # annotation and is folded into the hub by the poll loop below
        self.profiler = acquire_profiler(self.conf, role="driver")

        conf_json = json.dumps(self.conf.to_dict())  # includes driverPort
        for i in range(num_executors):
            executor_id = f"proc-exec-{i}"
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "sparkrdma_tpu.engine.worker",
                    "--executor-id", executor_id,
                    "--conf", conf_json,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
            )
            port = self._await_port(proc, start_timeout_s)
            self.workers.append(WorkerHandle(proc, executor_id, port))
        # liveness check
        for w in self.workers:
            assert w.request({"kind": "ping"}) == "pong"

        # telemetry pull loop: drain each worker's heartbeat outbox over
        # the task protocol and fold it into the driver hub. A worker
        # that fails a poll is skipped this round (its gap shows up as a
        # missed heartbeat), never a job failure.
        self._telemetry_stop = threading.Event()
        self._telemetry_thread: Optional[threading.Thread] = None
        if self.driver.telemetry is not None:
            self._telemetry_thread = threading.Thread(
                target=self._poll_telemetry, name="telemetry-poll", daemon=True
            )
            self._telemetry_thread.start()

    @staticmethod
    def _await_port(proc: subprocess.Popen, timeout_s: float) -> int:
        deadline = time.monotonic() + timeout_s
        assert proc.stdout is not None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("worker exited before announcing its port")
            if line.startswith("WORKER_PORT "):
                return int(line.split()[1])
        raise TimeoutError("worker did not announce its task port in time")

    def _poll_telemetry(self) -> None:
        hub = self.driver.telemetry
        interval_s = hub.interval_ms / 1000.0
        while not self._telemetry_stop.wait(interval_s):
            for w in list(self.workers):
                try:
                    payloads = w.request({"kind": "telemetry"}, timeout_s=10.0)
                except Exception:
                    logger.debug("telemetry poll of %s failed", w.executor_id,
                                 exc_info=True)
                    continue
                for p in payloads or []:
                    hub.ingest(p)
            # the driver's own profile table joins the cluster merge
            hub.profiles.ingest_local(self.profiler, "driver")
            hub.check_missed()

    def _next_shuffle_id(self) -> int:
        with self._lock:
            self._shuffle_counter += 1
            return self._shuffle_counter

    # ------------------------------------------------------------------
    def run_map_reduce(
        self,
        map_fns: Sequence[Callable[[], "object"]],
        num_partitions: int,
        reduce_fn: Optional[Callable] = None,
        partitioner: Optional[Partitioner] = None,
        tenant: Optional[str] = None,
    ) -> List:
        """One full distributed job: every ``map_fns[i]`` runs on a
        worker process and yields (k, v) records; records repartition by
        key across all workers; ``reduce_fn(iterator)`` runs per
        partition range on its worker. Returns the per-worker reduce
        results in worker order.

        ``tenant`` rides every task request so the workers' fair-share
        pools, quotas, and breaker keys attribute the job correctly;
        the driver's admission controller brackets the whole job."""
        t = tenant or tenancy.current_tenant()
        handle = BaseShuffleHandle(
            shuffle_id=self._next_shuffle_id(),
            num_maps=len(map_fns),
            partitioner=partitioner or HashPartitioner(num_partitions),
        )
        self.driver.register_shuffle(handle)
        admission = self.driver.admission
        jsp = None
        try:
            with tenancy.tenant_scope(t):
                # the job span bounds the critical-path window
                # (obs/critpath.py) for the driver-visible spans
                with self.driver.tracer.span(
                    "job.run", shuffle_id=handle.shuffle_id, tenant=t
                ) as jsp:
                    if admission is None:
                        out = self._run_map_reduce(
                            handle, map_fns, num_partitions, reduce_fn, t
                        )
                    else:
                        with admission.admit(t):
                            out = self._run_map_reduce(
                                handle, map_fns, num_partitions, reduce_fn, t
                            )
            self._attribute_job(jsp)
            return out
        except Exception as e:
            if self.driver.telemetry is not None:
                bd = self._attribute_job(jsp)
                self.driver.telemetry.flight_record(
                    "job_failed", error=e,
                    breakdown=bd.to_dict() if bd is not None else None,
                )
            raise

    def _attribute_job(self, job_span):
        """Best-effort per-job TimeBreakdown (obs/critpath.py) over the
        driver-process spans; kept as ``self.last_breakdown``."""
        if job_span is None or not self.conf.critpath_enabled:
            return None
        try:
            from sparkrdma_tpu.obs.critpath import job_breakdown

            self.last_breakdown = job_breakdown(job_span, role="driver")
            if self.driver.telemetry is not None:
                self.driver.telemetry.note_breakdown(
                    self.last_breakdown.to_dict()
                )
            return self.last_breakdown
        except Exception:
            logger.exception("critical-path attribution failed")
            return None

    def _run_map_reduce(self, handle, map_fns, num_partitions, reduce_fn, tenant):
        items = list(enumerate(map_fns))
        self._run_map_phase(handle, items, tenant, recompute=False)
        bounds = self._plan_bounds(handle, num_partitions)
        return self._run_reduce_phase(handle, bounds, reduce_fn, tenant, items)

    # -- elastic plumbing ----------------------------------------------
    def _live_workers(self) -> List[WorkerHandle]:
        return [w for w in self.workers if w.proc.poll() is None]

    def _reap_dead(self) -> List[WorkerHandle]:
        """Detect dead worker processes and prune them everywhere: the
        driver's location registry (which promotes any replicas the
        dead executor's maps left behind) and this context's dispatch
        set. Idempotent per worker."""
        dead = [w for w in self.workers if w.proc.poll() is not None]
        for w in dead:
            logger.warning(
                "executor %s died (exit %s); pruning and promoting replicas",
                w.executor_id, w.proc.poll(),
            )
            self.driver._on_peer_lost(w.executor_id)
            self.workers.remove(w)
        return dead

    def _run_map_phase(self, handle, items, tenant, recompute: bool) -> None:
        """Run every map task to an *accounted* publish, surviving
        executor loss. Each round ships the still-unaccounted maps as
        one map_batch per live worker (one socket round trip, bounded
        worker-side concurrency) and finalizes; a round that lost
        executors re-runs exactly ``driver.unaccounted_maps`` — maps a
        surviving publish or a promoted replica covers are never
        recomputed. Recovery rounds are bounded by
        ``elastic.maxRecoveries``.

        ``recompute=True`` (the reduce phase's recovery call) makes the
        first round count as lineage recompute too; when replicas
        already cover every map it is a no-op.

        Accounting has two tiers: the wrapper writer publishes with
        per-map lineage tags, so ``driver.map_owners`` is authoritative
        (and replica promotion keeps covered maps owned); the
        chunked-agg writer publishes whole-executor aggregates with no
        per-map attribution, so for it "accounted" falls back to
        "batch succeeded and its executor is still alive" — and
        executor loss is only recoverable under the wrapper method
        (re-publishing an aggregate writer's maps piecemeal could
        double-count surviving data)."""
        sid = handle.shuffle_id
        fns = dict(items)
        all_ids = [mid for mid, _ in items]
        # batch-success accounting for writers without lineage tags
        assigned: Dict[int, str] = {}

        def unaccounted() -> List[int]:
            owners = self.driver.map_owners(sid)
            return [
                mid for mid in all_ids
                if mid not in owners and mid not in assigned
            ]

        pending = unaccounted()
        if recompute:
            if not pending:
                return  # promoted replicas cover everything: zero recompute
            self._note_recompute(len(pending))
        recoveries = 0
        while True:
            if not pending:
                return
            workers = self._live_workers()
            if not workers:
                raise RuntimeError("no live executors left for map stage")
            # push + replica routes: where each executor reaches its
            # peers' task servers (shuffle/merge.py, elastic/)
            routes = {
                w.executor_id: ("127.0.0.1", w.task_port) for w in workers
            }
            by_worker: Dict[WorkerHandle, List] = {}
            for j, mid in enumerate(pending):
                by_worker.setdefault(workers[j % len(workers)], []).append(
                    (mid, fns[mid])
                )
            futures = {
                w: self._pool.submit(
                    w.request,
                    {
                        "kind": "map_batch",
                        "handle": handle,
                        "tasks": tasks,
                        "push_routes": routes,
                        "tenant": tenant,
                    },
                )
                for w, tasks in by_worker.items()
            }
            errors: List[Exception] = []
            for w, f in futures.items():
                try:
                    f.result()
                except Exception as e:
                    errors.append(e)
                else:
                    for mid, _fn in by_worker[w]:
                        assigned[mid] = w.executor_id
            for w in workers:  # every live worker, not just batch targets
                if w.proc.poll() is not None:
                    continue
                try:
                    w.request({"kind": "finalize", "shuffle_id": sid})
                except Exception as e:
                    errors.append(e)
            dead = self._reap_dead()
            dead_ids = {w.executor_id for w in dead}
            if dead_ids:
                for mid, eid in list(assigned.items()):
                    if eid in dead_ids:
                        del assigned[mid]
            pending = unaccounted()
            if not pending and not errors:
                return
            if errors and not dead:
                # a genuine task failure (not executor loss) is the
                # job's failure — recompute can't fix a deterministic
                # exception
                raise errors[0]
            if not dead and pending:
                raise RuntimeError(
                    f"maps {pending} unaccounted with all executors live"
                )
            if dead and not self._elastic_recovery_ok():
                raise errors[0] if errors else RuntimeError(
                    f"executors {sorted(dead_ids)} lost and the "
                    "chunked-agg writer cannot recompute piecemeal"
                )
            if recoveries >= self.conf.elastic_max_recoveries:
                raise errors[0] if errors else RuntimeError(
                    f"maps {pending} still unaccounted after "
                    f"{recoveries} recoveries"
                )
            recoveries += 1
            self._note_recompute(len(pending))
            logger.warning(
                "map recovery %d: re-running %d unaccounted maps %s on "
                "%d survivors", recoveries, len(pending), pending,
                len(self._live_workers()),
            )

    def _driver_failover(self) -> None:
        """Control-plane HA chaos rig (the ``driver:kill`` fault): the
        metadata hub dies mid-job and recovers by RE-PUBLISH, never
        recompute. Three rungs (docs/RESILIENCE.md "Control-plane HA"):

        1. wipe — every registry entry, barrier count, ownership claim,
           and parked replica is gone; leases re-grant under bumped
           epochs and the generation advances;
        2. re-adoption sweep — every live executor re-publishes its
           committed map outputs (rebuilt from the writer-committed
           files) and parked replicas (lineage tags intact), fenced by
           the new generation so a stale sweep can never merge in;
        3. re-promotion — executors that died BEFORE the crash get
           their loss replayed, so their re-parked replicas promote
           again instead of recomputing."""
        from sparkrdma_tpu.obs import get_registry

        t0 = time.perf_counter()
        generation = self.driver.metastore_crash()
        for w in self._live_workers():
            try:
                w.request({"kind": "republish", "meta_epoch": generation})
            except Exception:
                logger.warning(
                    "re-adoption sweep on %s failed", w.executor_id,
                    exc_info=True,
                )
        with self.driver._lock:
            lost = sorted(self.driver._lost_executors)
        for exec_id in lost:
            self.driver._on_peer_lost(exec_id)
        get_registry().histogram(
            "metastore.readoption_ms", role=self.driver.executor_id
        ).observe((time.perf_counter() - t0) * 1e3)
        logger.warning(
            "driver failover complete: generation %d, %d pre-crash losses "
            "replayed", generation, len(lost),
        )

    def _elastic_recovery_ok(self) -> bool:
        """Executor-loss recovery needs per-map lineage tags on the
        published locations — only the wrapper writer provides them."""
        from sparkrdma_tpu.utils.config import ShuffleWriterMethod

        return self.conf.shuffle_writer_method == ShuffleWriterMethod.WRAPPER

    def _note_recompute(self, num_maps: int) -> None:
        from sparkrdma_tpu.obs import get_registry

        reg = get_registry()
        reg.counter("engine.stage_recomputes").inc()
        reg.counter(
            "elastic.recoveries", role=self.driver.executor_id
        ).inc()
        reg.counter(
            "elastic.recomputed_maps", role=self.driver.executor_id
        ).inc(num_maps)

    def _plan_bounds(self, handle, num_partitions) -> List:
        """Split the partition range across live workers: contiguous
        static bounds, re-planned from the published per-partition
        sizes by the adaptive partitioner when enabled
        (shuffle/planner.py) so a hot partition's worker is not also
        loaded with its neighbors."""
        n = len(self._live_workers())
        bounds = [
            (w * num_partitions // n, (w + 1) * num_partitions // n)
            for w in range(n)
        ]
        if self.conf.planner_enabled:
            from sparkrdma_tpu.shuffle.planner import AdaptivePartitioner

            size_map = self.driver.partition_sizes(handle.shuffle_id)
            sizes = [size_map.get(p, 0) for p in range(num_partitions)]
            if any(sizes):
                lane_sizes = None
                if self.conf.collective_lane_balance:
                    # per-source lanes: cuts balance DMA-lane occupancy
                    # (the collective schedule's wave wall), not just
                    # byte totals
                    lanes = self.driver.partition_lane_sizes(
                        handle.shuffle_id
                    )
                    if len(lanes) > 1:
                        lane_sizes = {
                            src: [per.get(p, 0) for p in range(num_partitions)]
                            for src, per in lanes.items()
                        }
                ranges = AdaptivePartitioner(self.conf).plan(
                    sizes, n, lane_sizes=lane_sizes
                )
                # pad with empty ranges so every worker keeps a slot
                bounds = ranges + [
                    (num_partitions, num_partitions)
                ] * (n - len(ranges))
        return bounds

    def _run_reduce_phase(self, handle, bounds, reduce_fn, tenant, items):
        """Reduce fan-out with speculation and executor-loss recovery.

        Ranges are fixed up front (results must align regardless of
        later deaths); each round runs the outstanding ranges through a
        :class:`SpeculativeReducePhase`. Ranges whose every attempt
        failed trigger recovery when the failure was an executor death:
        prune + promote, re-run unaccounted maps, then re-issue just
        the failed ranges on survivors."""
        from sparkrdma_tpu.elastic.speculation import SpeculativeReducePhase

        # driver-death seam: the hub dies between map and reduce — the
        # worst moment, every barrier complete, nothing fetched yet.
        # The failover ladder must leave the reduce phase able to
        # resolve every location it would have seen (chaos bar:
        # byte-identical results, metastore.adoptions > 0)
        plan = _faults.active()
        if plan is not None and plan.on_driver(stage="reduce_phase"):
            self._driver_failover()
        workers = self._live_workers()
        assignments = [
            (i, rng, workers[i]) for i, rng in enumerate(bounds) if rng[1] > rng[0]
        ]
        rng_by_idx = {idx: rng for idx, rng, _ in assignments}
        results: Dict[int, object] = {}
        todo = assignments
        recoveries = 0
        while todo:
            phase = SpeculativeReducePhase(
                self.driver, self._pool, self.conf, self._live_workers,
                handle, reduce_fn, tenant,
            )
            done, failed = phase.run(todo)
            results.update(done)
            if not failed:
                break
            dead = self._reap_dead()
            if (
                not dead
                or not self._elastic_recovery_ok()
                or recoveries >= self.conf.elastic_max_recoveries
            ):
                raise next(iter(failed.values()))
            recoveries += 1
            # re-run the maps the dead executors took with them, then
            # re-issue only the failed ranges on survivors (fresh
            # locations resolve on fetch)
            self._run_map_phase(handle, items, tenant, recompute=True)
            survivors = self._live_workers()
            if not survivors:
                raise RuntimeError("no live executors left for reduce stage")
            todo = [
                (idx, rng_by_idx[idx], survivors[k % len(survivors)])
                for k, idx in enumerate(sorted(failed))
            ]
        return [results[idx] for idx, _rng, _w in assignments]

    # ------------------------------------------------------------------
    def stop(self) -> None:
        self._telemetry_stop.set()
        if self._telemetry_thread is not None:
            self._telemetry_thread.join(timeout=5)
            self._telemetry_thread = None
        for w in self.workers:
            try:
                w.request({"kind": "stop"}, timeout_s=5.0)
            except Exception:
                pass
        for w in self.workers:
            try:
                w.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.proc.kill()
        self._pool.shutdown(wait=False)
        release_profiler(self.profiler)
        self.profiler = None
        self.driver.stop()

    def __enter__(self) -> "ClusterContext":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
