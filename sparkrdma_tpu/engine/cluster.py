"""ClusterContext — real multi-process map/shuffle/reduce jobs.

The in-process :class:`~sparkrdma_tpu.engine.context.TpuContext` runs
executors as threads; this runs them as genuine OS processes (the
reference's one-endpoint-per-JVM topology, SURVEY.md §1): the driver
process owns the metadata-hub manager, each executor subprocess owns a
full transport endpoint, map outputs stage in the *executor's*
registered memory, and reducers pull them executor-to-executor with
one-sided READs — the driver never touches data.

Closures ship via cloudpickle over a tiny task protocol
(`engine/worker.py`); the shuffle itself rides the framework's own
control + data planes (python or native transport per conf).
"""

from __future__ import annotations

import json
import logging
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.engine.worker import _recv_obj, _send_obj
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner, Partitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, executor_id: str, task_port: int):
        self.proc = proc
        self.executor_id = executor_id
        self.task_port = task_port

    def request(self, obj, timeout_s: float = 120.0):
        with socket.create_connection(("127.0.0.1", self.task_port), timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            _send_obj(s, obj)
            resp = _recv_obj(s)
        if not resp.get("ok"):
            raise RuntimeError(
                f"task failed on {self.executor_id}: {resp.get('error')}\n"
                f"{resp.get('traceback', '')}"
            )
        return resp.get("result")


class ClusterContext:
    """Driver-side handle to a multi-process executor cluster."""

    def __init__(
        self,
        num_executors: int = 2,
        conf: Optional[TpuShuffleConf] = None,
        start_timeout_s: float = 30.0,
    ):
        self.conf = conf or TpuShuffleConf()
        self.driver = TpuShuffleManager(self.conf, is_driver=True)
        self.workers: List[WorkerHandle] = []
        self._shuffle_counter = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max(4, num_executors * 2))

        conf_json = json.dumps(self.conf.to_dict())  # includes driverPort
        for i in range(num_executors):
            executor_id = f"proc-exec-{i}"
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "sparkrdma_tpu.engine.worker",
                    "--executor-id", executor_id,
                    "--conf", conf_json,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
            )
            port = self._await_port(proc, start_timeout_s)
            self.workers.append(WorkerHandle(proc, executor_id, port))
        # liveness check
        for w in self.workers:
            assert w.request({"kind": "ping"}) == "pong"

        # telemetry pull loop: drain each worker's heartbeat outbox over
        # the task protocol and fold it into the driver hub. A worker
        # that fails a poll is skipped this round (its gap shows up as a
        # missed heartbeat), never a job failure.
        self._telemetry_stop = threading.Event()
        self._telemetry_thread: Optional[threading.Thread] = None
        if self.driver.telemetry is not None:
            self._telemetry_thread = threading.Thread(
                target=self._poll_telemetry, name="telemetry-poll", daemon=True
            )
            self._telemetry_thread.start()

    @staticmethod
    def _await_port(proc: subprocess.Popen, timeout_s: float) -> int:
        deadline = time.monotonic() + timeout_s
        assert proc.stdout is not None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("worker exited before announcing its port")
            if line.startswith("WORKER_PORT "):
                return int(line.split()[1])
        raise TimeoutError("worker did not announce its task port in time")

    def _poll_telemetry(self) -> None:
        hub = self.driver.telemetry
        interval_s = hub.interval_ms / 1000.0
        while not self._telemetry_stop.wait(interval_s):
            for w in list(self.workers):
                try:
                    payloads = w.request({"kind": "telemetry"}, timeout_s=10.0)
                except Exception:
                    logger.debug("telemetry poll of %s failed", w.executor_id,
                                 exc_info=True)
                    continue
                for p in payloads or []:
                    hub.ingest(p)
            hub.check_missed()

    def _next_shuffle_id(self) -> int:
        with self._lock:
            self._shuffle_counter += 1
            return self._shuffle_counter

    # ------------------------------------------------------------------
    def run_map_reduce(
        self,
        map_fns: Sequence[Callable[[], "object"]],
        num_partitions: int,
        reduce_fn: Optional[Callable] = None,
        partitioner: Optional[Partitioner] = None,
        tenant: Optional[str] = None,
    ) -> List:
        """One full distributed job: every ``map_fns[i]`` runs on a
        worker process and yields (k, v) records; records repartition by
        key across all workers; ``reduce_fn(iterator)`` runs per
        partition range on its worker. Returns the per-worker reduce
        results in worker order.

        ``tenant`` rides every task request so the workers' fair-share
        pools, quotas, and breaker keys attribute the job correctly;
        the driver's admission controller brackets the whole job."""
        t = tenant or tenancy.current_tenant()
        handle = BaseShuffleHandle(
            shuffle_id=self._next_shuffle_id(),
            num_maps=len(map_fns),
            partitioner=partitioner or HashPartitioner(num_partitions),
        )
        self.driver.register_shuffle(handle)
        admission = self.driver.admission
        try:
            with tenancy.tenant_scope(t):
                if admission is None:
                    return self._run_map_reduce(
                        handle, map_fns, num_partitions, reduce_fn, t
                    )
                with admission.admit(t):
                    return self._run_map_reduce(
                        handle, map_fns, num_partitions, reduce_fn, t
                    )
        except Exception as e:
            if self.driver.telemetry is not None:
                self.driver.telemetry.flight_record("job_failed", error=e)
            raise

    def _run_map_reduce(self, handle, map_fns, num_partitions, reduce_fn, tenant):
        # group this stage's tasks by worker and ship each group as ONE
        # map_batch request: one socket round trip per worker instead of
        # one per map, with the worker's bounded map pool (conf
        # map.parallelism) running the batch concurrently
        by_worker: Dict[int, List] = {}
        for i, fn in enumerate(map_fns):
            by_worker.setdefault(i % len(self.workers), []).append((i, fn))
        # push routes for the merge plane (shuffle/merge.py): where each
        # executor's push client reaches its peers' task servers
        push_routes = {
            w.executor_id: ("127.0.0.1", w.task_port) for w in self.workers
        }
        futures = [
            self._pool.submit(
                self.workers[w].request,
                {
                    "kind": "map_batch",
                    "handle": handle,
                    "tasks": tasks,
                    "push_routes": push_routes,
                    "tenant": tenant,
                },
            )
            for w, tasks in by_worker.items()
        ]
        for f in futures:
            f.result()  # raise the first map failure
        for w in self.workers:
            w.request({"kind": "finalize", "shuffle_id": handle.shuffle_id})

        # split the partition range across workers: contiguous static
        # bounds, re-planned from the published per-partition sizes by
        # the adaptive partitioner when enabled (shuffle/planner.py) so
        # a hot partition's worker is not also loaded with its neighbors
        n = len(self.workers)
        bounds = [
            (w * num_partitions // n, (w + 1) * num_partitions // n)
            for w in range(n)
        ]
        if self.conf.planner_enabled:
            from sparkrdma_tpu.shuffle.planner import AdaptivePartitioner

            size_map = self.driver.partition_sizes(handle.shuffle_id)
            sizes = [size_map.get(p, 0) for p in range(num_partitions)]
            if any(sizes):
                ranges = AdaptivePartitioner(self.conf).plan(sizes, n)
                # pad with empty ranges so every worker keeps a slot
                bounds = ranges + [
                    (num_partitions, num_partitions)
                ] * (n - len(ranges))
        futures = [
            self._pool.submit(
                self.workers[w].request,
                {
                    "kind": "reduce",
                    "handle": handle,
                    "start": lo,
                    "end": hi,
                    "reduce_fn": reduce_fn,
                    "tenant": tenant,
                },
            )
            for w, (lo, hi) in enumerate(bounds)
            if hi > lo
        ]
        return [f.result() for f in futures]

    # ------------------------------------------------------------------
    def stop(self) -> None:
        self._telemetry_stop.set()
        if self._telemetry_thread is not None:
            self._telemetry_thread.join(timeout=5)
            self._telemetry_thread = None
        for w in self.workers:
            try:
                w.request({"kind": "stop"}, timeout_s=5.0)
            except Exception:
                pass
        for w in self.workers:
            try:
                w.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.proc.kill()
        self._pool.shutdown(wait=False)
        self.driver.stop()

    def __enter__(self) -> "ClusterContext":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
