"""Process-wide metrics registry: labeled counters, gauges, histograms.

One registry per process (``get_registry()``); every layer of the
shuffle stack registers named instruments against it and the e2e
artifacts (``metrics_snapshot()``, bench records, the ``python -m
sparkrdma_tpu.obs`` CLI) read a point-in-time ``snapshot()``.

Conventions (see docs/OBSERVABILITY.md):

- names are dotted ``layer.metric`` (``transport.sends``,
  ``rpc.messages``, ``writer.spill_bytes``, ``mempool.hits``,
  ``hbm.spill_victims``, ``reader.remote_bytes``,
  ``exchange.bytes_sent``);
- labels are low-cardinality key=value pairs (``role=exec-0``,
  ``purpose=data``, ``type=FETCH_PARTITION_LOCATIONS``,
  ``schedule=ring``);
- snapshot keys render as ``name{k=v,...}`` with label keys sorted.

Everything here is stdlib-only and import-cycle-free: the rest of the
package may import this module unconditionally (including modules that
must stay importable without jax).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# Exponential-ish latency bounds in milliseconds; the last bucket in a
# snapshot is the overflow (> bounds[-1]).
DEFAULT_BOUNDS: Tuple[float, ...] = (
    1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000,
)

# -- declared metric families ---------------------------------------------
# name -> (kind, frozenset of label keys). The single source of truth
# the metric-families analysis pass checks every library call site
# against (sparkrdma_tpu/analysis/metrics_pass.py): an undeclared name,
# a kind mismatch, or a label set that drops/invents a key fails the
# lint. Every family listed here must have an anchor in
# docs/OBSERVABILITY.md. Tests may mint ad-hoc instruments freely.
_L = frozenset
METRIC_FAMILIES: Dict[str, Tuple[str, frozenset]] = {
    # admission control (tenancy/admission.py)
    "admission.admitted": ("counter", _L({"tenant"})),
    "admission.queue_waits": ("counter", _L({"tenant"})),
    "admission.timeouts": ("counter", _L({"tenant"})),
    "admission.wait_ms": ("histogram", _L({"tenant"})),
    "admission.inflight": ("gauge", _L({"role"})),
    "admission.queue_depth": ("gauge", _L({"role"})),
    # columnar block format (shuffle/columnar.py, writer/columnar.py)
    "block.columnar_blocks": ("counter", _L({"role"})),
    "block.columnar_bytes": ("counter", _L({"role"})),
    "block.pickle_fallbacks": ("counter", _L({"role"})),
    "block.view_decodes": ("counter", _L({"role"})),
    # whole-stage collective shuffle (shuffle/collective.py, planner.py)
    "collective.plans": ("counter", _L({"role"})),
    "collective.waves": ("counter", _L({"role", "schedule"})),
    "collective.blocks": ("counter", _L({"role"})),
    "collective.bytes": ("counter", _L({"role"})),
    "collective.fused_merges": ("counter", _L({"role"})),
    "collective.degrades": ("counter", _L({"role"})),
    "collective.compiles": ("counter", _L({"role"})),
    "collective.cache_hits": ("counter", _L({"role"})),
    "collective.lane_plans": ("counter", _L({"role"})),
    "collective.plan_ms": ("histogram", _L({"role"})),
    "collective.wave_ms": ("histogram", _L({"role", "schedule"})),
    "collective.wave_dispatch_ms": ("histogram", _L({"role", "schedule"})),
    "collective.wave_inflight": ("histogram", _L({"role"})),
    "collective.wave_overlap_ms": ("counter", _L({"role"})),
    "collective.autotune_adjustments": ("counter", _L({"role"})),
    "collective.tuned_wave_bytes": ("gauge", _L({"role"})),
    # critical-path attribution (obs/critpath.py)
    "critpath.builds": ("counter", _L({"role"})),
    "critpath.build_ms": ("histogram", _L({"role"})),
    "critpath.coverage_pct": ("gauge", _L()),
    # continuous profiling plane (obs/profiler.py)
    "profile.samples": ("counter", _L({"role"})),
    "profile.dropped": ("counter", _L({"role"})),
    "profile.overhead_ms": ("counter", _L({"role"})),
    "profile.stacks": ("gauge", _L({"role"})),
    # device fetch plane (shuffle/device_fetch.py, device_io.py)
    "device_fetch.bytes": ("counter", _L()),
    "device_fetch.stage_ms": ("histogram", _L()),
    "device_fetch.transport_ms": ("histogram", _L()),
    "device_fetch.plane.bytes": ("counter", _L({"role"})),
    "device_fetch.plane.fallbacks": ("counter", _L({"role"})),
    "device_fetch.plane.pulls": ("counter", _L({"role"})),
    "device_fetch.plane.plan_ms": ("histogram", _L({"role"})),
    # elastic cluster: replication, speculation, service (elastic/)
    "elastic.publishes_dropped": ("counter", _L({"role"})),
    "elastic.replica_promotions": ("counter", _L({"role"})),
    "elastic.replica_accepts": ("counter", _L({"role"})),
    "elastic.replica_drops": ("counter", _L({"role"})),
    "elastic.replicated_maps": ("counter", _L({"role"})),
    "elastic.replicated_bytes": ("counter", _L({"role"})),
    "elastic.replica_errors": ("counter", _L({"role"})),
    "elastic.speculations": ("counter", _L({"role"})),
    "elastic.speculation_wins": ("counter", _L({"role"})),
    "elastic.clone_cancels": ("counter", _L({"role"})),
    "elastic.recoveries": ("counter", _L({"role"})),
    "elastic.recomputed_maps": ("counter", _L({"role"})),
    "elastic.handoff_maps": ("counter", _L({"role"})),
    # engine (engine/)
    "engine.stage_recomputes": ("counter", _L()),
    "engine.task_ms": ("histogram", _L({"kind", "role", "tenant"})),
    # device exchange plane (ops/)
    "exchange.exchanges": ("counter", _L({"schedule"})),
    "exchange.bytes_sent": ("counter", _L({"schedule"})),
    "exchange.bytes_received": ("counter", _L({"schedule"})),
    "exchange.bytes_received_valid": ("counter", _L({"schedule"})),
    "exchange.time_ms": ("histogram", _L({"schedule"})),
    # HBM arena (ops/hbm_arena.py)
    "hbm.pool_hits": ("counter", _L()),
    "hbm.pool_misses": ("counter", _L()),
    "hbm.spill_victims": ("counter", _L()),
    "hbm.disk_spills": ("counter", _L()),
    "hbm.in_use_bytes": ("gauge", _L()),
    # registered-buffer pool (memory/)
    "mempool.hits": ("counter", _L()),
    "mempool.misses": ("counter", _L()),
    "mempool.returns": ("counter", _L()),
    "mempool.frees": ("counter", _L()),
    "mempool.registrations": ("counter", _L()),
    "mempool.deregistrations": ("counter", _L()),
    "mempool.in_use_bytes": ("gauge", _L()),
    # control-plane HA metadata hub (sparkrdma_tpu/metastore)
    "metastore.shards": ("gauge", _L({"role"})),
    "metastore.epoch": ("gauge", _L({"role"})),
    "metastore.lease_renewals": ("counter", _L({"role"})),
    "metastore.lease_takeovers": ("counter", _L({"role"})),
    "metastore.stale_epoch_rejects": ("counter", _L({"role"})),
    "metastore.peer_kills": ("counter", _L({"role"})),
    "metastore.adoptions": ("counter", _L({"role"})),
    "metastore.readoption_ms": ("histogram", _L({"role"})),
    # adaptive partition planner (shuffle/planner.py)
    "planner.splits": ("counter", _L({"role"})),
    "planner.coalesces": ("counter", _L({"role"})),
    "planner.plan_ms": ("histogram", _L({"role"})),
    # push-based merge (shuffle/merge.py)
    "push.pushed_blocks": ("counter", _L({"role"})),
    "push.pushed_bytes": ("counter", _L({"role"})),
    "push.merged_bytes": ("counter", _L({"role"})),
    "push.merge_segments": ("counter", _L({"role"})),
    "push.budget_drops": ("counter", _L({"role"})),
    "push.dedup_drops": ("counter", _L({"role"})),
    "push.dropped": ("counter", _L({"role"})),
    "push.fallbacks": ("counter", _L({"role"})),
    "push.send_errors": ("counter", _L({"role"})),
    "push.skipped": ("counter", _L({"role"})),
    # reduce/reader plane (shuffle/reader/)
    "reader.local_blocks": ("counter", _L({"role"})),
    "reader.local_bytes": ("counter", _L({"role"})),
    "reader.remote_blocks": ("counter", _L({"role"})),
    "reader.remote_bytes": ("counter", _L({"role"})),
    "reader.merged_reads": ("counter", _L({"role"})),
    "reader.fetch_wait_ms": ("counter", _L({"role"})),
    "reader.fetch_ms": ("histogram", _L({"role"})),
    "reader.remote_fetch_ms": ("histogram", _L({"peer"})),
    "reader.inflight_bytes": ("gauge", _L({"role"})),
    "reader.pipeline.inflight": ("gauge", _L({"role"})),
    "reader.pipeline.stage_ms": ("histogram", _L({"role", "stage"})),
    "reader.pipeline.overlap_ms": ("histogram", _L({"role"})),
    # resilience ladder (shuffle/fetcher.py, resilience.py)
    "resilience.retries": ("counter", _L({"role"})),
    "resilience.failovers": ("counter", _L({"role"})),
    "resilience.splits": ("counter", _L({"role"})),
    "resilience.checksum_failures": ("counter", _L({"role"})),
    "resilience.circuit_open": ("counter", _L({"role"})),
    "resilience.circuit_close": ("counter", _L({"role"})),
    "resilience.circuit_fail_fast": ("counter", _L({"role"})),
    "resilience.straggler_advisories": ("counter", _L({"role"})),
    # control-plane RPC (shuffle/manager.py)
    "rpc.messages": ("counter", _L({"role", "type"})),
    "rpc.errors": ("counter", _L({"role"})),
    "rpc.handle_ms": ("histogram", _L({"role", "type"})),
    # cluster event journal (obs/journal.py)
    "journal.events": ("counter", _L({"role"})),
    "journal.merged": ("counter", _L({"role"})),
    "journal.duplicates": ("counter", _L({"role"})),
    "journal.gaps": ("counter", _L({"role"})),
    "journal.size": ("gauge", _L({"role"})),
    # USE-method capacity plane (obs/capacity.py)
    "capacity.evaluations": ("counter", _L({"role"})),
    "capacity.utilization": ("gauge", _L({"resource"})),
    "capacity.saturation": ("gauge", _L({"resource"})),
    "capacity.errors": ("gauge", _L({"resource"})),
    "capacity.binding_headroom": ("gauge", _L({"role"})),
    # SLO engine + automated diagnosis (obs/slo.py, obs/diagnose.py)
    "slo.evaluations": ("counter", _L({"role"})),
    "slo.objectives": ("gauge", _L({"role"})),
    "slo.breaches": ("counter", _L({"objective", "role", "severity"})),
    "slo.breaching": ("gauge", _L({"role"})),
    "slo.burn_rate": ("gauge", _L({"objective", "role", "window"})),
    "diagnosis.builds": ("counter", _L({"role"})),
    "diagnosis.build_ms": ("histogram", _L({"role"})),
    # cluster telemetry plane (obs/telemetry.py)
    "telemetry.heartbeats": ("counter", _L({"executor", "role"})),
    "telemetry.bad_payloads": ("counter", _L({"role"})),
    "telemetry.executors": ("gauge", _L({"role"})),
    "telemetry.missed_heartbeats": ("gauge", _L({"role"})),
    "telemetry.straggler": ("gauge", _L({"executor", "role"})),
    "telemetry.stragglers": ("gauge", _L({"role"})),
    # tenancy: fair share + quotas (tenancy/)
    "tenant.submits": ("counter", _L({"tenant", "pool"})),
    "tenant.tasks": ("counter", _L({"tenant", "pool"})),
    "tenant.task_ms": ("histogram", _L({"tenant", "pool"})),
    "tenant.wait_ms": ("histogram", _L({"tenant", "pool"})),
    "tenant.queued": ("gauge", _L({"tenant", "pool"})),
    "tenant.quota_blocks": ("counter", _L({"resource", "tenant"})),
    "tenant.quota_overruns": ("counter", _L({"resource", "tenant"})),
    "tenant.quota_wait_ms": ("histogram", _L({"resource", "tenant"})),
    "tenant.bytes": ("gauge", _L({"resource", "tenant"})),
    # perf-trend engine over bench ledgers (obs/trend.py)
    "trend.rounds": ("gauge", _L({"family"})),
    "trend.series": ("gauge", _L()),
    "trend.regressions": ("counter", _L()),
    "trend.skipped_rows": ("counter", _L()),
    # host transport (transport/)
    "transport.connects": ("counter", _L({"purpose"})),
    "transport.connect_retries": ("counter", _L({"purpose"})),
    "transport.accepts": ("counter", _L({"purpose"})),
    "transport.completions": ("counter", _L({"purpose"})),
    "transport.errors_latched": ("counter", _L({"purpose"})),
    "transport.sends": ("counter", _L({"purpose"})),
    "transport.send_bytes": ("counter", _L({"purpose"})),
    "transport.send_overflow": ("counter", _L({"purpose"})),
    "transport.recvs": ("counter", _L({"purpose"})),
    "transport.recv_bytes": ("counter", _L({"purpose"})),
    "transport.reads": ("counter", _L({"purpose"})),
    "transport.read_bytes": ("counter", _L({"purpose"})),
    "transport.reads_served": ("counter", _L({"purpose"})),
    "transport.read_bytes_served": ("counter", _L({"purpose"})),
    "transport.read_errors": ("counter", _L({"purpose"})),
    # native read submission plane (native/transport.cpp SubmissionPlane,
    # mirrored from the C++ atomics by transport/native_node.py);
    # process-global: multiple in-process nodes sum into one family
    "transport.sq.submits": ("counter", _L()),
    "transport.sq.batches": ("counter", _L()),
    "transport.sq.sqe_depth": ("gauge", _L()),
    "transport.sq.completions": ("counter", _L()),
    "transport.sq.backend_fallbacks": ("counter", _L()),
    "transport.consume.workers": ("gauge", _L()),
    "transport.consume.busy_ms": ("counter", _L()),
    # map/writer plane (shuffle/writer/)
    "writer.map_outputs": ("counter", _L({"method", "role"})),
    "writer.bytes_written": ("counter", _L({"role"})),
    "writer.flush_bytes": ("counter", _L({"role"})),
    "writer.partition_flushes": ("counter", _L({"role"})),
    "writer.partitions_written": ("counter", _L({"role"})),
    "writer.publishes": ("counter", _L({"role"})),
    "writer.incremental_publishes": ("counter", _L({"role"})),
    "writer.locations_published": ("counter", _L({"role"})),
    "writer.blocks_memory": ("counter", _L()),
    "writer.blocks_spilled": ("counter", _L()),
    "writer.spill_bytes": ("counter", _L()),
    "writer.chunk_allocations": ("counter", _L()),
    "writer.chunk_recycles": ("counter", _L()),
    "writer.pipeline.inflight": ("gauge", _L({"role"})),
    "writer.pipeline.stage_ms": ("histogram", _L({"role", "stage"})),
    "writer.pipeline.overlap_ms": ("histogram", _L({"role"})),
}
del _L


def metric_key(name: str, labels: Mapping[str, str]) -> str:
    """Canonical snapshot key: ``name`` or ``name{k=v,...}`` (sorted)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`metric_key`: ``name{k=v,...}`` -> (name, labels).

    Label values are low-cardinality identifiers by convention (roles,
    purposes, message types) and never contain ``,`` or ``}``."""
    if not key.endswith("}"):
        return key, {}
    name, _, inner = key[:-1].partition("{")
    labels: Dict[str, str] = {}
    for kv in inner.split(","):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        labels[k] = v
    return name, labels


def strip_label(key: str, *label_keys: str) -> str:
    """Canonical key with the given label keys removed (cross-executor
    comparison: drop ``role``/``executor`` so the same instrument on two
    executors folds to one comparable key)."""
    name, labels = parse_metric_key(key)
    for k in label_keys:
        labels.pop(k, None)
    return metric_key(name, labels)


def snapshot_delta(
    prev: Mapping[str, Mapping[str, object]],
    cur: Mapping[str, Mapping[str, object]],
) -> Dict[str, Dict[str, object]]:
    """Reset-safe diff of two ``snapshot()`` dicts.

    Counters and histogram count/sum/per-bucket counts are differenced;
    gauges report their current state. A *negative* difference means the instrument
    was zeroed (``reset()``) after ``prev`` was taken — the Prometheus
    counter-reset rule applies: the delta restarts from the current
    value instead of going negative, so a long-lived consumer holding a
    moving baseline (the telemetry Heartbeater) never resurrects
    pre-reset totals."""
    prev_c = prev.get("counters", {})
    prev_h = prev.get("histograms", {})
    out: Dict[str, Dict[str, object]] = {
        "counters": {},
        "gauges": dict(cur.get("gauges", {})),
        "histograms": {},
    }
    for key, v in cur.get("counters", {}).items():
        d = v - prev_c.get(key, 0)
        out["counters"][key] = v if d < 0 else d
    for key, h in cur.get("histograms", {}).items():
        ph = prev_h.get(key, {})
        dc = h["count"] - ph.get("count", 0)
        ds = h["sum"] - ph.get("sum", 0.0)
        cur_b = h.get("buckets") or {}
        prev_b = ph.get("buckets") or {}
        db = {b: c - prev_b.get(b, 0) for b, c in cur_b.items()}
        if dc < 0 or ds < 0 or any(v < 0 for v in db.values()):
            dc, ds, db = h["count"], h["sum"], dict(cur_b)
        entry: Dict[str, object] = {
            "count": dc,
            "sum": ds,
            "min": h["min"],
            "max": h["max"],
        }
        if cur_b:
            entry["buckets"] = db
        out["histograms"][key] = entry
    return out


class Counter:
    """Monotonic counter. ``inc`` is the only mutator."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value with a high-water mark."""

    __slots__ = ("name", "labels", "_value", "_hwm", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._value = 0
        self._hwm = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v
            if v > self._hwm:
                self._hwm = v

    def add(self, n) -> None:
        with self._lock:
            self._value += n
            if self._value > self._hwm:
                self._hwm = self._value

    @property
    def value(self):
        return self._value

    @property
    def hwm(self):
        return self._hwm


class Histogram:
    """Fixed-bound histogram (count/sum/min/max + per-bucket counts).

    ``bounds`` are inclusive upper edges; one extra overflow bucket
    catches everything above ``bounds[-1]``.
    """

    __slots__ = ("name", "labels", "bounds", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str],
                 bounds: Sequence[float] = DEFAULT_BOUNDS):
        self.name = name
        self.labels = dict(labels)
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            buckets = {}
            for b, c in zip(self.bounds, self._counts):
                buckets[f"le_{b:g}"] = c
            buckets["overflow"] = self._counts[-1]
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "buckets": buckets,
            }


class MetricsRegistry:
    """Thread-safe get-or-create registry of named, labeled instruments."""

    def __init__(self):
        # hot: held for dict lookups only, every layer's instrument
        # resolution goes through it (lock-order detector, docs/ANALYSIS.md)
        from sparkrdma_tpu.analysis.lockorder import named_lock

        self._lock = named_lock("metrics.registry", hot=True)
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, labels: Mapping[str, str],
                       *extra):
        key = metric_key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, *extra)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS,
                  **labels: str) -> Histogram:
        return self._get_or_create(Histogram, name, labels, bounds)

    # -- read side --------------------------------------------------------
    def _select(self, match: Optional[Mapping[str, str]],
                prefix: Optional[str]) -> List[Tuple[str, object]]:
        with self._lock:
            items = list(self._metrics.items())
        out = []
        for key, m in items:
            if prefix and not m.name.startswith(prefix):
                continue
            if match:
                # A metric matches if every requested label either equals
                # the requested value or is absent on the metric (shared /
                # process-global instruments stay visible in role views).
                labels = m.labels
                if any(labels.get(k, v) != v for k, v in match.items()):
                    continue
            out.append((key, m))
        return out

    def snapshot(self, match: Optional[Mapping[str, str]] = None,
                 prefix: Optional[str] = None) -> Dict[str, Dict[str, object]]:
        """Point-in-time view: ``{"counters": {key: int}, "gauges":
        {key: {"value", "hwm"}}, "histograms": {key: {...}}}``.

        ``match`` filters by labels (metrics lacking a requested label
        key are included); ``prefix`` filters by metric-name prefix.
        """
        snap = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, m in self._select(match, prefix):
            if isinstance(m, Counter):
                snap["counters"][key] = m.value
            elif isinstance(m, Gauge):
                snap["gauges"][key] = {"value": m.value, "hwm": m.hwm}
            else:
                snap["histograms"][key] = m.snapshot()
        return snap

    def delta(self, prev: Mapping[str, Mapping[str, object]],
              match: Optional[Mapping[str, str]] = None,
              prefix: Optional[str] = None) -> Dict[str, Dict[str, object]]:
        """Change since a prior ``snapshot()``: counters and histogram
        count/sum are differenced (reset-safe, see
        :func:`snapshot_delta`); gauges report their current state."""
        return snapshot_delta(prev, self.snapshot(match, prefix))

    def to_json(self, match: Optional[Mapping[str, str]] = None,
                prefix: Optional[str] = None, indent: Optional[int] = None
                ) -> str:
        return json.dumps(self.snapshot(match, prefix), indent=indent,
                          sort_keys=True)

    def reset(self) -> None:
        """Zero every registered instrument in place (tests only).

        Instruments are NOT dropped: modules pre-resolve and cache them
        at import (e.g. the mempool counters in memory/buffer_manager),
        so clearing the dict would orphan those references — they would
        keep counting into objects no snapshot can see for the rest of
        the process.
        """
        with self._lock:
            for m in self._metrics.values():
                with m._lock:
                    if isinstance(m, Counter):
                        m._value = 0
                    elif isinstance(m, Gauge):
                        m._value = 0
                        m._hwm = 0
                    else:
                        m._counts = [0] * (len(m.bounds) + 1)
                        m._count = 0
                        m._sum = 0.0
                        m._min = None
                        m._max = None


    def family_violations(self) -> List[str]:
        """Registered instruments that contradict METRIC_FAMILIES.

        The runtime complement of the static metric-families lint: it
        sees instruments minted through dynamic helpers (e.g. the
        fair-share executor's cached ``getattr(reg, kind)`` factories)
        that no AST pass can. Undeclared names are ignored — tests mint
        ad-hoc instruments freely; only declared families are held to
        their kind and label set."""
        kinds = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}
        out: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            fam = METRIC_FAMILIES.get(m.name)
            if fam is None:
                continue
            kind, labels = fam
            if kinds[type(m)] != kind:
                out.append(
                    f"{m.name}: registered as {kinds[type(m)]}, "
                    f"declared {kind}"
                )
            if frozenset(m.labels) != labels:
                out.append(
                    f"{m.name}: label set {sorted(m.labels)} != "
                    f"declared {sorted(labels)}"
                )
        return out


_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry all layers instrument against."""
    return _DEFAULT
