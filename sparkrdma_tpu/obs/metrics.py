"""Process-wide metrics registry: labeled counters, gauges, histograms.

One registry per process (``get_registry()``); every layer of the
shuffle stack registers named instruments against it and the e2e
artifacts (``metrics_snapshot()``, bench records, the ``python -m
sparkrdma_tpu.obs`` CLI) read a point-in-time ``snapshot()``.

Conventions (see docs/OBSERVABILITY.md):

- names are dotted ``layer.metric`` (``transport.sends``,
  ``rpc.messages``, ``writer.spill_bytes``, ``mempool.hits``,
  ``hbm.spill_victims``, ``reader.remote_bytes``,
  ``exchange.bytes_sent``);
- labels are low-cardinality key=value pairs (``role=exec-0``,
  ``purpose=data``, ``type=FETCH_PARTITION_LOCATIONS``,
  ``schedule=ring``);
- snapshot keys render as ``name{k=v,...}`` with label keys sorted.

Everything here is stdlib-only and import-cycle-free: the rest of the
package may import this module unconditionally (including modules that
must stay importable without jax).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# Exponential-ish latency bounds in milliseconds; the last bucket in a
# snapshot is the overflow (> bounds[-1]).
DEFAULT_BOUNDS: Tuple[float, ...] = (
    1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000,
)


def metric_key(name: str, labels: Mapping[str, str]) -> str:
    """Canonical snapshot key: ``name`` or ``name{k=v,...}`` (sorted)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_metric_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`metric_key`: ``name{k=v,...}`` -> (name, labels).

    Label values are low-cardinality identifiers by convention (roles,
    purposes, message types) and never contain ``,`` or ``}``."""
    if not key.endswith("}"):
        return key, {}
    name, _, inner = key[:-1].partition("{")
    labels: Dict[str, str] = {}
    for kv in inner.split(","):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        labels[k] = v
    return name, labels


def strip_label(key: str, *label_keys: str) -> str:
    """Canonical key with the given label keys removed (cross-executor
    comparison: drop ``role``/``executor`` so the same instrument on two
    executors folds to one comparable key)."""
    name, labels = parse_metric_key(key)
    for k in label_keys:
        labels.pop(k, None)
    return metric_key(name, labels)


def snapshot_delta(
    prev: Mapping[str, Mapping[str, object]],
    cur: Mapping[str, Mapping[str, object]],
) -> Dict[str, Dict[str, object]]:
    """Reset-safe diff of two ``snapshot()`` dicts.

    Counters and histogram count/sum are differenced; gauges report
    their current state. A *negative* difference means the instrument
    was zeroed (``reset()``) after ``prev`` was taken — the Prometheus
    counter-reset rule applies: the delta restarts from the current
    value instead of going negative, so a long-lived consumer holding a
    moving baseline (the telemetry Heartbeater) never resurrects
    pre-reset totals."""
    prev_c = prev.get("counters", {})
    prev_h = prev.get("histograms", {})
    out: Dict[str, Dict[str, object]] = {
        "counters": {},
        "gauges": dict(cur.get("gauges", {})),
        "histograms": {},
    }
    for key, v in cur.get("counters", {}).items():
        d = v - prev_c.get(key, 0)
        out["counters"][key] = v if d < 0 else d
    for key, h in cur.get("histograms", {}).items():
        ph = prev_h.get(key, {})
        dc = h["count"] - ph.get("count", 0)
        ds = h["sum"] - ph.get("sum", 0.0)
        if dc < 0 or ds < 0:
            dc, ds = h["count"], h["sum"]
        out["histograms"][key] = {
            "count": dc,
            "sum": ds,
            "min": h["min"],
            "max": h["max"],
        }
    return out


class Counter:
    """Monotonic counter. ``inc`` is the only mutator."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value with a high-water mark."""

    __slots__ = ("name", "labels", "_value", "_hwm", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._value = 0
        self._hwm = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v
            if v > self._hwm:
                self._hwm = v

    def add(self, n) -> None:
        with self._lock:
            self._value += n
            if self._value > self._hwm:
                self._hwm = self._value

    @property
    def value(self):
        return self._value

    @property
    def hwm(self):
        return self._hwm


class Histogram:
    """Fixed-bound histogram (count/sum/min/max + per-bucket counts).

    ``bounds`` are inclusive upper edges; one extra overflow bucket
    catches everything above ``bounds[-1]``.
    """

    __slots__ = ("name", "labels", "bounds", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, labels: Mapping[str, str],
                 bounds: Sequence[float] = DEFAULT_BOUNDS):
        self.name = name
        self.labels = dict(labels)
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            buckets = {}
            for b, c in zip(self.bounds, self._counts):
                buckets[f"le_{b:g}"] = c
            buckets["overflow"] = self._counts[-1]
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "buckets": buckets,
            }


class MetricsRegistry:
    """Thread-safe get-or-create registry of named, labeled instruments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, labels: Mapping[str, str],
                       *extra):
        key = metric_key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, *extra)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS,
                  **labels: str) -> Histogram:
        return self._get_or_create(Histogram, name, labels, bounds)

    # -- read side --------------------------------------------------------
    def _select(self, match: Optional[Mapping[str, str]],
                prefix: Optional[str]) -> List[Tuple[str, object]]:
        with self._lock:
            items = list(self._metrics.items())
        out = []
        for key, m in items:
            if prefix and not m.name.startswith(prefix):
                continue
            if match:
                # A metric matches if every requested label either equals
                # the requested value or is absent on the metric (shared /
                # process-global instruments stay visible in role views).
                labels = m.labels
                if any(labels.get(k, v) != v for k, v in match.items()):
                    continue
            out.append((key, m))
        return out

    def snapshot(self, match: Optional[Mapping[str, str]] = None,
                 prefix: Optional[str] = None) -> Dict[str, Dict[str, object]]:
        """Point-in-time view: ``{"counters": {key: int}, "gauges":
        {key: {"value", "hwm"}}, "histograms": {key: {...}}}``.

        ``match`` filters by labels (metrics lacking a requested label
        key are included); ``prefix`` filters by metric-name prefix.
        """
        snap = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, m in self._select(match, prefix):
            if isinstance(m, Counter):
                snap["counters"][key] = m.value
            elif isinstance(m, Gauge):
                snap["gauges"][key] = {"value": m.value, "hwm": m.hwm}
            else:
                snap["histograms"][key] = m.snapshot()
        return snap

    def delta(self, prev: Mapping[str, Mapping[str, object]],
              match: Optional[Mapping[str, str]] = None,
              prefix: Optional[str] = None) -> Dict[str, Dict[str, object]]:
        """Change since a prior ``snapshot()``: counters and histogram
        count/sum are differenced (reset-safe, see
        :func:`snapshot_delta`); gauges report their current state."""
        return snapshot_delta(prev, self.snapshot(match, prefix))

    def to_json(self, match: Optional[Mapping[str, str]] = None,
                prefix: Optional[str] = None, indent: Optional[int] = None
                ) -> str:
        return json.dumps(self.snapshot(match, prefix), indent=indent,
                          sort_keys=True)

    def reset(self) -> None:
        """Zero every registered instrument in place (tests only).

        Instruments are NOT dropped: modules pre-resolve and cache them
        at import (e.g. the mempool counters in memory/buffer_manager),
        so clearing the dict would orphan those references — they would
        keep counting into objects no snapshot can see for the rest of
        the process.
        """
        with self._lock:
            for m in self._metrics.values():
                with m._lock:
                    if isinstance(m, Counter):
                        m._value = 0
                    elif isinstance(m, Gauge):
                        m._value = 0
                        m._hwm = 0
                    else:
                        m._counts = [0] * (len(m.bounds) + 1)
                        m._count = 0
                        m._sum = 0.0
                        m._min = None
                        m._max = None


_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry all layers instrument against."""
    return _DEFAULT
