"""OpenMetrics / Prometheus text exposition for the metrics registry.

Renders a ``MetricsRegistry.snapshot()`` dict (live, or one saved
inside a bench/workload artifact) as OpenMetrics text:

- metric names map ``layer.metric`` -> ``layer_metric`` (dots and any
  other non-``[a-zA-Z0-9_:]`` characters become ``_``);
- labels pass through as-is (``role``, ``executor``, ``purpose``,
  ``type``, ...), with values escaped per the spec (``\\`` ``"`` and
  newline);
- counters expose as ``<name>_total``; gauges expose the value plus a
  ``<name>_hwm`` gauge family for the high-water mark; histograms
  expose cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``;
- the document ends with ``# EOF`` (OpenMetrics terminator).

Two egress paths: :class:`OpenMetricsServer` (a stdlib ``http.server``
thread for scrapes, conf ``obs.telemetry.httpPort``) and
:func:`write_openmetrics` (a file for scrape-less runs, also the
``python -m sparkrdma_tpu.obs --openmetrics`` CLI).

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import http.server
import logging
import re
import threading
from typing import Callable, Dict, List, Mapping, Optional

from sparkrdma_tpu.obs.metrics import parse_metric_key

logger = logging.getLogger(__name__)

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(dotted: str) -> str:
    """``transport.read_bytes`` -> ``transport_read_bytes``."""
    name = _NAME_SANITIZE.sub("_", dotted)
    if not name or not (name[0].isalpha() or name[0] in "_:"):
        name = "_" + name
    return name


def escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: Mapping[str, str], extra: Optional[Mapping[str, str]] = None) -> str:
    merged: Dict[str, str] = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{metric_name(k)}="{escape_label_value(str(v))}"'
        for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class _FamilyWriter:
    """Groups samples by family so HELP/TYPE render once per family."""

    def __init__(self):
        self._families: Dict[str, List[str]] = {}
        self._types: Dict[str, str] = {}
        self._order: List[str] = []

    def add(self, family: str, mtype: str, sample_lines: List[str]) -> None:
        if family not in self._families:
            self._families[family] = []
            self._types[family] = mtype
            self._order.append(family)
        self._families[family].extend(sample_lines)

    def render(self) -> List[str]:
        out: List[str] = []
        for family in self._order:
            out.append(f"# HELP {family} sparkrdma_tpu metric {family}")
            out.append(f"# TYPE {family} {self._types[family]}")
            out.extend(self._families[family])
        return out


def render_openmetrics(snapshot: Mapping[str, Mapping[str, object]],
                       extra_labels: Optional[Mapping[str, str]] = None) -> str:
    """One OpenMetrics document from a ``snapshot()`` dict."""
    w = _FamilyWriter()
    for key in sorted(snapshot.get("counters", {})):
        value = snapshot["counters"][key]
        dotted, labels = parse_metric_key(key)
        family = metric_name(dotted)
        w.add(family, "counter", [
            f"{family}_total{_labels_str(labels, extra_labels)} {_fmt(value)}"
        ])
    for key in sorted(snapshot.get("gauges", {})):
        g = snapshot["gauges"][key]
        dotted, labels = parse_metric_key(key)
        family = metric_name(dotted)
        ls = _labels_str(labels, extra_labels)
        w.add(family, "gauge", [f"{family}{ls} {_fmt(g.get('value', 0))}"])
        w.add(family + "_hwm", "gauge", [f"{family}_hwm{ls} {_fmt(g.get('hwm', 0))}"])
    for key in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][key]
        dotted, labels = parse_metric_key(key)
        family = metric_name(dotted)
        lines: List[str] = []
        cumulative = 0
        buckets = h.get("buckets") or {}
        for bname, count in buckets.items():
            if bname == "overflow":
                continue
            cumulative += count
            le = bname[3:] if bname.startswith("le_") else bname
            extra = dict(extra_labels or {})
            extra["le"] = le
            lines.append(f"{family}_bucket{_labels_str(labels, extra)} {cumulative}")
        extra = dict(extra_labels or {})
        extra["le"] = "+Inf"
        lines.append(
            f"{family}_bucket{_labels_str(labels, extra)} {_fmt(h.get('count', 0))}"
        )
        ls = _labels_str(labels, extra_labels)
        lines.append(f"{family}_sum{ls} {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{family}_count{ls} {_fmt(h.get('count', 0))}")
        w.add(family, "histogram", lines)
    return "\n".join(w.render() + ["# EOF", ""])


def extract_snapshot(doc: Mapping) -> Dict[str, Dict[str, object]]:
    """Find a registry snapshot inside a saved JSON document.

    Accepts a raw ``snapshot()`` dict, a manager/context
    ``metrics_snapshot()`` (``"registry"`` key), or a bench/workload
    artifact (``"obs_registry"`` key)."""
    for key in ("obs_registry", "registry"):
        inner = doc.get(key)
        if isinstance(inner, Mapping) and "counters" in inner:
            return {
                "counters": dict(inner.get("counters", {})),
                "gauges": dict(inner.get("gauges", {})),
                "histograms": dict(inner.get("histograms", {})),
            }
    if "counters" in doc or "gauges" in doc or "histograms" in doc:
        return {
            "counters": dict(doc.get("counters", {})),
            "gauges": dict(doc.get("gauges", {})),
            "histograms": dict(doc.get("histograms", {})),
        }
    raise ValueError(
        "no registry snapshot found (expected 'counters'/'gauges'/'histograms', "
        "or an 'obs_registry'/'registry' key containing them)"
    )


def write_openmetrics(path: str, snapshot: Mapping[str, Mapping[str, object]],
                      extra_labels: Optional[Mapping[str, str]] = None) -> str:
    """File egress for scrape-less runs; returns the rendered text."""
    text = render_openmetrics(snapshot, extra_labels)
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return text


class OpenMetricsServer:
    """Stdlib HTTP scrape endpoint serving ``source()`` as OpenMetrics.

    ``source`` is any zero-arg callable returning the exposition text
    (typically ``lambda: render_openmetrics(get_registry().snapshot())``).
    Binds ``host:port`` (port 0 = ephemeral; read ``.port`` after
    construction) and serves on a daemon thread until :meth:`stop`.
    """

    def __init__(self, source: Callable[[], str], port: int = 0,
                 host: str = "127.0.0.1"):
        self._source = source

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                try:
                    body = server._source().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception:
                    logger.exception("openmetrics render failed")
                    self.send_response(500)
                    self.end_headers()

            def log_message(self, fmt, *args):
                logger.debug("openmetrics: " + fmt, *args)

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="openmetrics-http",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
