"""Dapper-style span tracer with cross-executor trace correlation.

A 64-bit trace id is minted when the driver registers a shuffle
(``TpuShuffleManager.register_shuffle``) and rides inside the
``PublishPartitionLocationsMsg`` / ``FetchPartitionLocationsMsg`` wire
frames, so the publish → resolve → fetch spans of one shuffle share an
id across every process role that touched it. Spans nest through a
``contextvars`` context variable (thread- and task-local), and export
as Chrome trace-event JSON (``{"traceEvents": [...]}``) loadable in
Perfetto / chrome://tracing.

Timestamps: spans record ``time.perf_counter()`` internally and are
rebased to wall-clock microseconds at export via a per-tracer epoch
(default: this process's module-load anchor), so spans from every
tracer in the process share one timeline — and spans merged from
OTHER processes can be aligned by handing the exporter each remote
role's wall-clock anchor (carried in the telemetry heartbeat as
``epoch_ms``, see obs/telemetry.py).

Causal edges (critical-path attribution, docs/OBSERVABILITY.md): a
span can declare that it *follows* another span — a hand-off across a
queue, a thread pool, or a wire frame — via ``follows=`` on
``span()``/``record()`` or ``Span.add_follows``. The reference is a
:class:`SpanHandle` (two ints, trivially serializable), so it rides
pipeline queue tuples and RPC trailing extensions. The exporter emits
each edge as a Perfetto flow event pair (``ph:"s"`` at the origin's
end, ``ph:"f"`` at the follower's start), and ``obs/critpath.py``
walks the same edges to extract the per-job critical path.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

# Wall-clock anchor for the perf_counter timeline (export-time rebase).
_EPOCH = time.time() - time.perf_counter()

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "sparkrdma_tpu_obs_span", default=None
)

# Thread-ident → innermost OPEN span, maintained by ``Tracer.span()``
# only while a watcher (the sampling profiler, obs/profiler.py) has
# asked for it via ``set_span_watch(True)``: a contextvar can't be read
# cross-thread, and the profiler's timer thread must tag each sampled
# thread with its active span. Plain dict ops are atomic under the GIL;
# the gate keeps the disabled cost at one module-global load per span.
_span_watch = False
_active_by_ident: Dict[int, "Span"] = {}


def set_span_watch(enabled: bool) -> None:
    """Turn the thread-ident → active-span side table on/off (profiler
    lifecycle hook). Turning it off clears the table."""
    global _span_watch
    _span_watch = bool(enabled)
    if not enabled:
        _active_by_ident.clear()


def active_span_of_ident(ident: int) -> "Optional[Span]":
    """Innermost open span on thread ``ident`` — readable from any
    thread, None when the thread has no open span (or the watch is
    off). Spans opened before the watch was enabled are not visible."""
    return _active_by_ident.get(ident)


_span_ids = itertools.count(1)
_tracers_lock = threading.Lock()
_tracers: "List[Tracer]" = []
_named_lock = threading.Lock()
_named: Dict[str, "Tracer"] = {}


def now() -> float:
    """Monotonic timestamp compatible with ``Tracer.record``."""
    return time.perf_counter()


def epoch_anchor() -> float:
    """This process's wall-clock anchor for the span timeline (seconds):
    ``epoch_anchor() + span.start`` is a wall-clock time. Carried in
    the telemetry heartbeat as ``epoch_ms`` so cross-process trace
    merges rebase every role onto one timeline (obs/telemetry.py)."""
    return _EPOCH


def mint_trace_id() -> int:
    """Random nonzero 63-bit trace id (0 means "unknown" on the wire)."""
    return (int.from_bytes(os.urandom(8), "big") & 0x7FFFFFFFFFFFFFFF) | 1


class SpanHandle:
    """Serializable causal reference to a span.

    Two ints — small enough to ride a pipeline queue tuple, a task-
    protocol dict, or an 8-byte wire extension. ``span_id`` 0 is the
    null handle (``bool(handle)`` is False), the wire's "no origin".
    """

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int = 0, span_id: int = 0):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)

    def __bool__(self) -> bool:
        return bool(self.span_id)

    def __repr__(self) -> str:
        return f"SpanHandle(trace_id={self.trace_id:#x}, span_id={self.span_id})"

    @classmethod
    def of(cls, span: "Optional[Span]") -> "Optional[SpanHandle]":
        return None if span is None else cls(span.trace_id, span.span_id)


class Span:
    __slots__ = ("name", "role", "trace_id", "span_id", "parent_id",
                 "start", "end", "tid", "args", "follows")

    def __init__(self, name: str, role: str, trace_id: int, parent_id: int,
                 start: float, args: Dict[str, object]):
        self.name = name
        self.role = role
        self.trace_id = trace_id
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.start = start
        self.end = start
        self.tid = threading.get_ident()
        self.args = args
        # causal predecessors: list of (trace_id, span_id), lazily built
        self.follows: Optional[List[tuple]] = None

    def handle(self) -> SpanHandle:
        return SpanHandle(self.trace_id, self.span_id)

    def add_follows(self, origin) -> None:
        """Record a causal edge: this span's work was handed off from
        ``origin`` (a Span, SpanHandle, or None). Null/zero origins are
        ignored so callers can pass handles through unconditionally."""
        if origin is None:
            return
        sid = getattr(origin, "span_id", 0)
        if not sid:
            return
        if self.follows is None:
            self.follows = []
        self.follows.append((getattr(origin, "trace_id", 0), int(sid)))


def _link(sp: Span, follows) -> None:
    if follows is None:
        return
    if isinstance(follows, (Span, SpanHandle)):
        sp.add_follows(follows)
        return
    try:
        for origin in follows:
            sp.add_follows(origin)
    except TypeError:
        pass


class Tracer:
    """Per-role span recorder (one per shuffle manager / process role).

    Spans live in a bounded deque (``max_spans``); ``bind_shuffle``
    records the shuffle→trace-id association learned from the wire so
    spans opened before the binding arrived (the reducer's fetch span)
    can resolve their trace id at close time.
    """

    def __init__(self, role: str = "proc", max_spans: int = 20000,
                 enabled: bool = True, epoch: Optional[float] = None):
        self.role = role
        self.enabled = enabled
        # wall-clock anchor for this tracer's perf_counter timeline; a
        # remote role's spans are merged by constructing the local
        # stand-in tracer with the anchor from its telemetry heartbeat
        self.epoch = _EPOCH if epoch is None else float(epoch)
        self._spans: "deque[Span]" = deque(maxlen=max(1, int(max_spans)))
        self._lock = threading.Lock()
        self._bindings: Dict[int, int] = {}
        with _tracers_lock:
            _tracers.append(self)

    # -- shuffle → trace-id bindings --------------------------------------
    def bind_shuffle(self, shuffle_id: int, trace_id: int) -> None:
        if trace_id:
            with self._lock:
                self._bindings[shuffle_id] = trace_id

    def trace_for(self, shuffle_id: Optional[int]) -> int:
        if shuffle_id is None:
            return 0
        with self._lock:
            return self._bindings.get(shuffle_id, 0)

    # -- span recording ---------------------------------------------------
    def _resolve_trace(self, trace_id: int, shuffle_id: Optional[int],
                       parent: Optional[Span]) -> int:
        if trace_id:
            return trace_id
        bound = self.trace_for(shuffle_id)
        if bound:
            return bound
        return parent.trace_id if parent is not None else 0

    @contextlib.contextmanager
    def span(self, name: str, shuffle_id: Optional[int] = None,
             trace_id: int = 0, follows=None, **args):
        """Context-managed span; nests under the current contextvar span.

        The trace id is resolved eagerly at open (explicit arg, else the
        shuffle binding, else the parent's id) so nested spans inherit
        it, and re-resolved at close if still unknown — the binding may
        arrive over the wire while the span is open. ``follows`` adds
        causal edges (Span / SpanHandle / iterable thereof)."""
        if not self.enabled:
            yield None
            return
        parent = _current_span.get()
        if shuffle_id is not None:
            args.setdefault("shuffle_id", shuffle_id)
        sp = Span(name, self.role,
                  self._resolve_trace(trace_id, shuffle_id, parent),
                  parent.span_id if parent is not None else 0,
                  now(), args)
        _link(sp, follows)
        token = _current_span.set(sp)
        if _span_watch:
            _active_by_ident[sp.tid] = sp
        try:
            yield sp
        finally:
            _current_span.reset(token)
            if _span_watch:
                if parent is not None:
                    _active_by_ident[sp.tid] = parent
                else:
                    _active_by_ident.pop(sp.tid, None)
            sp.end = now()
            if not sp.trace_id:
                sp.trace_id = self._resolve_trace(trace_id, shuffle_id, parent)
            with self._lock:
                self._spans.append(sp)

    def record(self, name: str, start: float, end: float,
               shuffle_id: Optional[int] = None, trace_id: int = 0,
               follows=None, **args) -> Optional[Span]:
        """Retroactive span from already-measured ``now()`` timestamps
        (hot paths that keep their own timers). Nests under the current
        contextvar span like ``span()`` does, so retroactive hot-path
        spans stay attached to the causal DAG."""
        if not self.enabled:
            return None
        parent = _current_span.get()
        if shuffle_id is not None:
            args.setdefault("shuffle_id", shuffle_id)
        sp = Span(name, self.role, 0,
                  parent.span_id if parent is not None else 0,
                  start, args)
        sp.end = end
        sp.trace_id = self._resolve_trace(trace_id, shuffle_id, parent)
        _link(sp, follows)
        with self._lock:
            self._spans.append(sp)
        return sp

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._bindings.clear()


def get_tracer(role: str = "proc") -> Tracer:
    """Named-tracer convenience for code without a manager (benches)."""
    with _named_lock:
        t = _named.get(role)
        if t is None:
            t = Tracer(role=role)
            _named[role] = t
        return t


def all_tracers() -> List[Tracer]:
    with _tracers_lock:
        return list(_tracers)


def collect_spans(tracers: Optional[Iterable[Tracer]] = None) -> List[Span]:
    out: List[Span] = []
    for t in (tracers if tracers is not None else all_tracers()):
        out.extend(t.spans())
    out.sort(key=lambda s: s.start)
    return out


def collect_spans_with_epochs(
        tracers: Optional[Iterable[Tracer]] = None,
        epochs: Optional[Dict[str, float]] = None) -> List[tuple]:
    """``(span, epoch)`` pairs sorted on the merged wall-clock timeline.

    ``epochs`` maps role → wall-clock anchor and overrides the owning
    tracer's epoch — how cluster-mode merges align spans from remote
    processes (anchors from the telemetry heartbeat's ``epoch_ms``)."""
    epochs = epochs or {}
    out: List[tuple] = []
    for t in (tracers if tracers is not None else all_tracers()):
        ep = epochs.get(t.role, t.epoch)
        out.extend((sp, ep) for sp in t.spans())
    out.sort(key=lambda pair: pair[1] + pair[0].start)
    return out


def to_chrome_trace(tracers: Optional[Iterable[Tracer]] = None,
                    epochs: Optional[Dict[str, float]] = None,
                    journal_events: Optional[Iterable[Dict]] = None) -> Dict:
    """Chrome trace-event JSON dict: one complete event ("ph": "X") per
    span, one pid per tracer role (with process_name metadata), tids
    mapped to small ints per role, and one Perfetto flow-event pair
    (``ph:"s"`` / ``ph:"f"``) per causal ``follows`` edge whose origin
    span is part of this export. ``journal_events`` (merged cluster
    journal dicts, obs/journal.py) draw as instant markers (``ph:"i"``)
    on the same wall-clock timeline — spans already use wall-anchored
    timestamps, so the two align without translation."""
    events: List[Dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    # span_id → (span, epoch, pid, tid) for flow-event origin lookup
    placed: Dict[int, tuple] = {}
    pairs = collect_spans_with_epochs(tracers, epochs)
    for sp, ep in pairs:
        pid = pids.setdefault(sp.role, len(pids) + 1)
        tid = tids.setdefault((sp.role, sp.tid), len(tids) + 1)
        placed[sp.span_id] = (sp, ep, pid, tid)
        args = dict(sp.args)
        args["span_id"] = sp.span_id
        if sp.trace_id:
            args["trace_id"] = f"{sp.trace_id:#x}"
        if sp.parent_id:
            args["parent_span"] = sp.parent_id
        events.append({
            "name": sp.name,
            "cat": "shuffle",
            "ph": "X",
            "ts": (ep + sp.start) * 1e6,
            "dur": max(0.0, (sp.end - sp.start) * 1e6),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    flow_ids = itertools.count(1)
    flows: List[Dict] = []
    for sp, ep in pairs:
        if not sp.follows:
            continue
        _, _, pid, tid = placed[sp.span_id]
        for _tid_unused, origin_id in sp.follows:
            origin = placed.get(origin_id)
            if origin is None:
                continue  # origin fell off a bounded deque or lives remote
            osp, oep, opid, otid = origin
            fid = next(flow_ids)
            flows.append({
                "name": "critpath", "cat": "critpath", "ph": "s",
                "id": fid, "ts": (oep + osp.end) * 1e6,
                "pid": opid, "tid": otid,
                "args": {"from_span": osp.span_id, "to_span": sp.span_id},
            })
            flows.append({
                "name": "critpath", "cat": "critpath", "ph": "f",
                "bp": "e", "id": fid, "ts": (ep + sp.start) * 1e6,
                "pid": pid, "tid": tid,
                "args": {"from_span": osp.span_id, "to_span": sp.span_id},
            })
    instants: List[Dict] = []
    if journal_events:
        from sparkrdma_tpu.obs.journal import events_to_chrome

        jpid = len(pids) + 1
        instants = events_to_chrome(journal_events, pid=jpid)
        pids["journal"] = jpid
    meta = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": role}}
        for role, pid in sorted(pids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events + flows + instants,
            "displayTimeUnit": "ms"}


def export_chrome_trace(path: str,
                        tracers: Optional[Iterable[Tracer]] = None,
                        epochs: Optional[Dict[str, float]] = None,
                        journal_events: Optional[Iterable[Dict]] = None
                        ) -> Dict:
    """Write the Chrome trace JSON to ``path`` and return the dict."""
    doc = to_chrome_trace(tracers, epochs, journal_events=journal_events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc
