"""``python -m sparkrdma_tpu.obs`` — dump the unified metrics registry.

Without flags this prints the process-wide registry snapshot as JSON
(empty unless something in this process has run shuffle code first,
which is why ``--demo`` exists: it drives a small in-process cluster
shuffle — driver + two executors over real TCP, wrapper writer method
— so every layer's counters populate). ``--trace-out PATH`` also
exports the span trace as Chrome trace-event JSON (open in Perfetto or
chrome://tracing).

The demo is jax-free: it exercises the host shuffle planes (transport,
rpc, writer, mempool, reader) only.
"""

from __future__ import annotations

import argparse
import sys

from sparkrdma_tpu.obs import export_chrome_trace, get_registry


def _run_demo() -> None:
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        records = [(f"key-{i % 97}", i) for i in range(500)]
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter(records))
            w.stop(True)
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        for ex, (lo, hi) in [(ex0, (0, 1)), (ex1, (1, 2))]:
            for _ in ex.get_reader(handle, lo, hi).read():
                pass
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparkrdma_tpu.obs",
        description="dump the unified metrics registry as JSON",
    )
    ap.add_argument(
        "--demo", action="store_true",
        help="run a small in-process cluster shuffle first so every "
        "layer's counters populate",
    )
    ap.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="also export the span trace as Chrome trace-event JSON",
    )
    ap.add_argument(
        "--prefix", default=None,
        help="only include metrics whose name starts with this prefix "
        "(e.g. 'transport.')",
    )
    ap.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)

    if args.demo:
        _run_demo()
    if args.trace_out:
        export_chrome_trace(args.trace_out)
    print(get_registry().to_json(prefix=args.prefix, indent=args.indent))
    return 0


if __name__ == "__main__":
    sys.exit(main())
