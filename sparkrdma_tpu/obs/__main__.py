"""``python -m sparkrdma_tpu.obs`` — dump the unified metrics registry.

Without flags this prints the process-wide registry snapshot as JSON
(empty unless something in this process has run shuffle code first,
which is why ``--demo`` exists: it drives a small in-process cluster
shuffle — driver + two executors over real TCP, wrapper writer method
— so every layer's counters populate). ``--trace-out PATH`` also
exports the span trace as Chrome trace-event JSON (open in Perfetto or
chrome://tracing).

Telemetry-plane egress: ``--openmetrics [DEST]`` renders the
OpenMetrics text exposition instead of the JSON dump ('-' or no value
= stdout), from the live registry or — with ``--from-snapshot FILE`` —
from a registry snapshot saved inside a bench/workload artifact JSON.
``--flight-recorder FILE`` pretty-prints a flight-record artifact
(obs/telemetry.py) and exits. ``--critical-path FILE`` replays the
critical-path attribution (obs/critpath.py) over a saved Chrome trace,
or prints the ``breakdown`` stored in a bench/flight artifact.
``--diagnose FILE`` renders SLO breach diagnoses (obs/diagnose.py)
from a standalone diagnosis artifact, a flight record's ``slo``
section, or a soak ledger's ``slo.diagnosis_records``.
``--timeline FILE`` renders the causally-ordered incident timeline
(obs/journal.py) from any artifact carrying journal events — a flight
record, a soak ledger, a live snapshot, or a bare event list.

Continuous profiling (obs/profiler.py): ``--demo`` runs under the
default sampling profiler, and ``--flamegraph [DEST]`` /
``--folded [DEST]`` render the merged samples as a self-contained HTML
flamegraph / collapsed-stack text ('-' = stdout). Saved flight records
carry per-executor profile windows, so both flags also accept
``--from-snapshot FLIGHT.json`` as their sample source.

The demo is jax-free: it exercises the host shuffle planes (transport,
rpc, writer, mempool, reader) only.
"""

from __future__ import annotations

import argparse
import json
import sys

from sparkrdma_tpu.obs import export_chrome_trace, get_registry
from sparkrdma_tpu.obs.export import extract_snapshot, render_openmetrics
from sparkrdma_tpu.obs.profiler import ProfileHub


def _run_demo() -> "ProfileHub":
    from sparkrdma_tpu.obs import journal as journal_mod
    from sparkrdma_tpu.obs.capacity import CapacityPlane
    from sparkrdma_tpu.obs.journal import render_timeline
    from sparkrdma_tpu.obs.metrics import get_registry as _get_registry
    from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            # sample fast enough that even this sub-second demo folds a
            # non-trivial profile (default 19 Hz targets long-lived jobs)
            "tpu.shuffle.obs.profile.hz": "199",
        }
    )
    profiler = acquire_profiler(conf, role="proc")
    # arm the event journal before the shuffle so every control-plane
    # transition site that fires lands in the demo timeline
    journal_mod.configure(conf, role="proc")
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        records = [(f"key-{i % 97}", i) for i in range(500)]
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter(records))
            w.stop(True)
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        for ex, (lo, hi) in [(ex0, (0, 1)), (ex1, (1, 2))]:
            for _ in ex.get_reader(handle, lo, hi).read():
                pass
        if profiler is not None:
            profiler.sample_once()  # at least one sample, however fast
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()
    # exercise the PR-20 planes: one USE evaluation (capacity.* gauges
    # land in the registry dump below) and the incident timeline —
    # stderr only, the stdout contract is still pure JSON
    cap = CapacityPlane(conf, _get_registry(), role="proc")
    cap.evaluate()
    rep = cap.capacity_report(refresh=False)
    binding = rep.get("binding") or {}
    if binding:
        print(
            f"capacity: binding={binding.get('resource')} "
            f"headroom={binding.get('headroom', 1.0):.0%} over "
            f"{len(rep.get('resources', {}))} resources",
            file=sys.stderr,
        )
    j = journal_mod.active_journal()
    if j is not None and j.events():
        print(render_timeline(j.events(), limit=20), file=sys.stderr)
    hub = ProfileHub()
    hub.ingest_local(profiler, "proc")
    release_profiler(profiler)
    return hub


def _print_flight(path: str) -> int:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("kind") != "sparkrdma_flight_record":
        print(f"{path}: not a flight record (kind={doc.get('kind')!r})",
              file=sys.stderr)
        return 2
    print(f"flight record v{doc.get('version')} — {doc.get('reason')} "
          f"(role {doc.get('role')}, wall {doc.get('generated_wall_ms')} ms)")
    err = doc.get("error")
    if err:
        print(f"  error: {err.get('type')}: {err.get('message')}")
    failed = doc.get("failed_group")
    if failed:
        inner = ", ".join(f"{k}={v}" for k, v in sorted(failed.items()))
        print(f"  failed group: {inner}")
    stragglers = (doc.get("stragglers") or {}).get("stragglers") or []
    if stragglers:
        print(f"  stragglers: {', '.join(stragglers)}")
    health = doc.get("source_health") or {}
    for peer, state in sorted(health.items()):
        print(f"  circuit[{peer}]: {state}")
    execs = doc.get("executors") or {}
    print(f"  executors: {len(execs)} "
          f"(interval {doc.get('interval_ms')} ms)")
    for eid in sorted(execs):
        wins = execs[eid]
        gaps = sum(1 for w in wins if w.get("gap"))
        span = ""
        if wins:
            span = f", wall {wins[0]['wall_ms']}..{wins[-1]['wall_ms']}"
        print(f"    {eid}: {len(wins)} windows, {gaps} gaps{span}")
    profiles = doc.get("profiles") or {}
    if profiles:
        print("  last profile window per executor (obs/profiler.py):")
        for eid in sorted(profiles):
            win = profiles[eid]
            rows = sorted(win.get("rows") or [], key=lambda r: -r[3])
            total = sum(r[3] for r in rows)
            hz = win.get("hz") or 0
            print(f"    {eid}: {total} samples @ {hz:g} Hz")
            for tenant, cat, stack, n in rows[:3]:
                leaf = ";".join(stack.split(";")[-2:])
                print(f"      {n:6d}  [{tenant}|{cat}] {leaf}")
    print(f"  spans captured: {len(doc.get('spans') or [])}")
    return 0


def _print_diagnosis(path: str) -> int:
    """Render every diagnosis artifact reachable from ``path``: a
    standalone ``sparkrdma_diagnosis`` JSON, a flight record (its
    ``slo`` section), or a soak/bench ledger (``["slo"]``)."""
    from sparkrdma_tpu.obs.diagnose import render

    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        print(f"{path}: not a JSON object", file=sys.stderr)
        return 2
    if doc.get("kind") == "sparkrdma_diagnosis":
        print(render(doc))
        return 0
    slo = doc.get("slo") or {}
    breaches = slo.get("breach_records") or []
    diagnoses = slo.get("diagnosis_records") or []
    if not breaches and not diagnoses and "slo" not in doc:
        print(f"{path}: no 'slo' section and not a diagnosis artifact "
              "(kind=sparkrdma_diagnosis)", file=sys.stderr)
        return 2
    print(f"{path}: {slo.get('objectives', 0)} objectives, "
          f"{slo.get('breach_count', len(breaches))} breaches, "
          f"{len(diagnoses)} diagnoses")
    for b in breaches:
        where = f" executor={b['executor']}" if b.get("executor") else ""
        print(f"  breach: {b.get('objective')} [{b.get('severity')}]"
              f"{where} at wall {b.get('wall_ms')} ms")
    for diag in diagnoses:
        print()
        print(render(diag))
    return 0


def _print_timeline(path: str) -> int:
    """Render the causally-ordered incident timeline from any artifact
    carrying journal events (obs/journal.py)."""
    from sparkrdma_tpu.obs.journal import extract_events, render_timeline

    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    events = extract_events(doc)
    if not events:
        print(f"{path}: no journal events found (expected a flight "
              "record, soak ledger, snapshot with a 'journal' key, or "
              "a bare event list)", file=sys.stderr)
        return 2
    print(render_timeline(events))
    return 0


def _hub_from_flight(doc: dict) -> ProfileHub:
    """Rebuild a ProfileHub from a flight record's profile windows."""
    hub = ProfileHub()
    for eid, win in (doc.get("profiles") or {}).items():
        hub.ingest(eid, {"hz": win.get("hz"), "rows": win.get("rows")},
                   wall_ms=win.get("wall_ms"))
    return hub


def _print_critical_path(path: str, top: int = 12) -> int:
    from sparkrdma_tpu.obs.attr import attribute
    from sparkrdma_tpu.obs.critpath import extract, spans_from_chrome

    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        spans = spans_from_chrome(doc)
        if not spans:
            print(f"{path}: no spans carry args.span_id — exported by an "
                  "older to_chrome_trace?", file=sys.stderr)
            return 2
        jobs = [p for p in spans if p.name == "job.run"]
        if jobs:
            job = max(jobs, key=lambda p: p.t1)
            t0, t1, exclude = job.t0, job.t1, {job.span_id}
            print(f"window: job.run span {job.span_id}")
        else:
            t0 = min(p.t0 for p in spans)
            t1 = max(p.t1 for p in spans)
            exclude = set()
            print("window: full trace extent (no job.run span found)")
        cp = extract(spans, t0, t1, exclude=exclude)
        print(attribute(cp, top_segments=top).render())
        print("top segments:")
        for seg in cp.top_segments(top):
            label = seg.name if seg.kind == "span" else "(idle/untraced)"
            role = f" [{seg.role}]" if seg.role else ""
            print(f"  {seg.dur_s * 1e3:10.3f} ms  {label}{role}")
        return 0
    bd = doc.get("breakdown") if isinstance(doc, dict) else None
    if bd:
        print(f"stored breakdown: wall {bd.get('wall_ms')} ms, "
              f"coverage {float(bd.get('coverage', 0.0)) * 100:.1f}%")
        cats = bd.get("categories_ms") or {}
        for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
            print(f"  {cat:<16} {ms:10.3f} ms")
        segs = bd.get("critical_path") or []
        if segs:
            print("top segments:")
            for seg in segs[:top]:
                label = seg.get("name") or "(idle/untraced)"
                print(f"  {seg.get('ms', 0.0):10.3f} ms  {label}")
        return 0
    print(f"{path}: neither a Chrome trace (traceEvents) nor an artifact "
          "with a stored 'breakdown'", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparkrdma_tpu.obs",
        description="dump the unified metrics registry as JSON",
    )
    ap.add_argument(
        "--demo", action="store_true",
        help="run a small in-process cluster shuffle first so every "
        "layer's counters populate",
    )
    ap.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="also export the span trace as Chrome trace-event JSON",
    )
    ap.add_argument(
        "--prefix", default=None,
        help="only include metrics whose name starts with this prefix "
        "(e.g. 'transport.')",
    )
    ap.add_argument("--indent", type=int, default=2)
    ap.add_argument(
        "--openmetrics", nargs="?", const="-", default=None, metavar="DEST",
        help="render the OpenMetrics text exposition instead of the JSON "
        "dump; DEST is a file path or '-' for stdout (default)",
    )
    ap.add_argument(
        "--from-snapshot", default=None, metavar="FILE",
        help="with --openmetrics: read the registry snapshot from a saved "
        "JSON (raw snapshot, metrics_snapshot(), or bench artifact with "
        "an 'obs_registry' key) instead of the live registry",
    )
    ap.add_argument(
        "--flight-recorder", default=None, metavar="FILE",
        help="pretty-print a flight-record JSON artifact and exit",
    )
    ap.add_argument(
        "--critical-path", default=None, metavar="FILE",
        help="print the critical-path TimeBreakdown and top segments from "
        "a saved Chrome trace (traceEvents) or from the 'breakdown' stored "
        "in a bench/flight artifact, then exit",
    )
    ap.add_argument(
        "--diagnose", default=None, metavar="FILE",
        help="render SLO breach diagnoses from a diagnosis artifact, a "
        "flight record, or a soak ledger with an 'slo' section, then exit",
    )
    ap.add_argument(
        "--timeline", default=None, metavar="FILE",
        help="render the causally-ordered journal event timeline from a "
        "flight record, soak ledger, snapshot, or bare event list, then "
        "exit",
    )
    ap.add_argument(
        "--flamegraph", nargs="?", const="-", default=None, metavar="DEST",
        help="render the merged profile samples (from --demo, or the "
        "profile windows of a flight record given via --from-snapshot) as "
        "a self-contained HTML flamegraph; DEST is a file path or '-'",
    )
    ap.add_argument(
        "--folded", nargs="?", const="-", default=None, metavar="DEST",
        help="like --flamegraph but emit flamegraph.pl collapsed-stack "
        "text (executor;tenant:..;span:..;frames count)",
    )
    args = ap.parse_args(argv)

    if args.flight_recorder:
        return _print_flight(args.flight_recorder)
    if args.critical_path:
        return _print_critical_path(args.critical_path)
    if args.diagnose:
        return _print_diagnosis(args.diagnose)
    if args.timeline:
        return _print_timeline(args.timeline)
    hub = None
    if args.demo:
        hub = _run_demo()
    if args.flamegraph is not None or args.folded is not None:
        if hub is None and args.from_snapshot:
            with open(args.from_snapshot, "r", encoding="utf-8") as f:
                hub = _hub_from_flight(json.load(f))
        if hub is None or not hub.total_samples:
            print("no profile samples: run with --demo, or point "
                  "--from-snapshot at a flight record with profile "
                  "windows", file=sys.stderr)
            return 2
        for dest, text in (
            (args.folded, hub.folded()),
            (args.flamegraph,
             hub.flamegraph_html(title="sparkrdma_tpu profile")),
        ):
            if dest is None:
                continue
            if dest == "-":
                sys.stdout.write(text)
            else:
                with open(dest, "w", encoding="utf-8") as f:
                    f.write(text)
                print(f"wrote {dest} ({hub.total_samples} samples, "
                      f"{len(hub.merged_rows())} stacks)")
        return 0
    if args.trace_out:
        export_chrome_trace(args.trace_out)
    if args.openmetrics is not None:
        if args.from_snapshot:
            with open(args.from_snapshot, "r", encoding="utf-8") as f:
                snap = extract_snapshot(json.load(f))
        else:
            snap = get_registry().snapshot(prefix=args.prefix)
        text = render_openmetrics(snap)
        if args.openmetrics == "-":
            sys.stdout.write(text)
        else:
            with open(args.openmetrics, "w", encoding="utf-8") as f:
                f.write(text)
            print(f"wrote {args.openmetrics}")
        return 0
    print(get_registry().to_json(prefix=args.prefix, indent=args.indent))
    return 0


if __name__ == "__main__":
    sys.exit(main())
