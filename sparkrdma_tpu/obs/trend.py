"""Perf-trend engine over the committed bench ledgers.

Every benchmark round leaves a JSON ledger in the repo root (``BENCH_r01.json``,
``WORKLOADS_r04.json``, ``SOAK_r01.json``, ...).  Those ledgers were written by
different generations of ``bench.py`` and therefore do not share a schema: early
rounds record a single ``terasort_speedup_vs_host_sort`` row, later rounds nest
A/B sections, io_uring probes, and per-workload throughput arrays.  This module
normalizes all of them into one per-metric trajectory:

* every numeric leaf becomes a named series (``bench.native_read_samehost_gbps``,
  ``workloads.pagerank.records_per_s``, ``soak.checks.hwm_flat``, ...),
* booleans are folded to 0/1 so invariant checks chart as step functions,
* known string/list metadata is skipped *loudly* (each skip is recorded with a
  reason in the output), and anything unrecognized is an error — a new ledger
  field must either chart or be explicitly classified, never vanish silently.

Output is ``TREND.json`` (full trajectories + deltas + skip log) and
``TREND.md`` (a markdown table per family).  With ``--check`` the tool exits
nonzero when any tracked throughput row (``gbps`` series from the bench family)
drops, or any tracked latency row (``p99`` series from the soak/workloads
families) *rises*, by more than the regression threshold vs the previous round
it appeared in — or when a ledger row cannot be classified.  CI runs
``--check`` so a perf regression or a schema drift fails the build the same way
a broken test does.

Bench rounds are not all measured on the same machine, so absolute GB/s is
only comparable when the rig is: when both rounds of a bench throughput
series carry the rig probe (``exchange_loopback_gbps`` — a bare loopback
``device_put`` with no shuffle code in it), the gate judges the
roofline-NORMALIZED delta (series value / same-round probe).  A host that
got slower moves every series and the probe together; that is a fact about
the machine, not a code regression.  The probe series itself charts but
never gates, for the same reason.  Rounds without the probe gate on raw
deltas as before.

Run as ``python -m sparkrdma_tpu.obs.trend``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# Keys whose string values are descriptive metadata, never metrics.  They are
# skipped with reason "string-metadata"; a string under any other key is an
# error so schema drift cannot slip through unseen.
STRING_METADATA_KEYS = {
    "metric",
    "unit",
    "device",
    "note",
    "label",
    "cmd",
    "tail",
    "backend",
    "platform",
    "workload",
    "transport",
    "attn",
    "trace_file",
    "telemetry_timeline",
    "verified",
    "executor_id",
    "map_sorter",
    "gate_skip_reason",
    "resource",  # capacity_report binding/row names (obs/capacity.py)
}

# Numeric keys that describe the run rather than measure it (round index,
# return code, wall-clock stamp, problem size knobs).  Skipped loudly so the
# trajectory only contains rows where "down" can mean "regression".
NUMERIC_METADATA_KEYS = {
    "n",
    "rc",
    "generated_unix",
    "scale",
    "n_keys",
    "read_block_bytes",
    "num_blocks",
    "block_bytes",
    "num_partitions",
    "total_bytes_per_stage",
    "reps",
    "cores",
    "nproc",
    "b",
    "s",
    "d_model",
    "heads",
    "keys",
    "devices",
    "e2e_gb",
}

_LEDGER_RE = re.compile(r"^(BENCH|WORKLOADS|SOAK)_r(\d+)\.json$")

# Gate: a tracked series regressing by more than this fraction vs the previous
# round it appeared in fails --check.  Tracked series are bench.* rows
# containing "gbps" (regression = drop) and soak.*/workloads.* rows containing
# "p99" (regression = rise — latency climbing is the failure mode).
REGRESSION_THRESHOLD = 0.15
NOISE_FLOOR_MIN = 0.05

# The rig probe: a loopback device_put round-trip measured by the bench on
# the machine it ran on.  No shuffle code is in its path, so per-round it
# measures the RIG; bench throughput series gate on values normalized by it
# when both rounds carry it, and the probe itself charts without gating.
RIG_PROBE_SERIES = "bench.exchange_loopback_gbps"


class LedgerError(ValueError):
    """A ledger row could not be classified as metric or known metadata."""


def _is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and math.isfinite(v)


class _Flattener:
    def __init__(self) -> None:
        self.rows: Dict[str, float] = {}
        self.skipped: List[Dict[str, str]] = []
        self.errors: List[str] = []

    def skip(self, path: str, reason: str) -> None:
        self.skipped.append({"row": path, "reason": reason})

    def put(self, path: str, value: float) -> None:
        self.rows[path] = float(value)

    def walk(self, prefix: str, obj: Any) -> None:
        if isinstance(obj, bool):
            self.put(prefix, 1.0 if obj else 0.0)
        elif _is_number(obj):
            self.put(prefix, float(obj))
        elif isinstance(obj, str):
            key = prefix.rsplit(".", 1)[-1]
            if key in STRING_METADATA_KEYS or key.endswith("note"):
                self.skip(prefix, "string-metadata")
            else:
                self.errors.append(f"unclassifiable string row {prefix!r}={obj!r}")
        elif isinstance(obj, list):
            self.skip(prefix, "list-valued")
        elif isinstance(obj, dict):
            for k, v in obj.items():
                key = str(k)
                if _is_number(v) and key in NUMERIC_METADATA_KEYS:
                    self.skip(f"{prefix}.{key}" if prefix else key, "numeric-metadata")
                    continue
                self.walk(f"{prefix}.{key}" if prefix else key, v)
        elif obj is None:
            self.skip(prefix, "null")
        else:
            self.errors.append(f"unclassifiable row {prefix!r} of type {type(obj).__name__}")


def flatten_ledger(family: str, doc: Any, fname: str) -> _Flattener:
    """Turn one ledger document into ``series -> value`` rows."""
    fl = _Flattener()
    if not isinstance(doc, dict):
        fl.errors.append(f"{fname}: top-level document is {type(doc).__name__}, expected object")
        return fl
    if family == "bench":
        for k, v in doc.items():
            if k == "parsed":
                fl.walk("bench", v)
            else:
                fl.skip(f"bench.{k}", "run-metadata")
    elif family == "workloads":
        for entry in doc.get("workloads") or []:
            name = entry.get("workload", "unknown")
            for k, v in entry.items():
                if k == "workload":
                    continue
                fl.walk(f"workloads.{name}.{k}", v)
        for k in doc:
            if k != "workloads":
                fl.skip(f"workloads.{k}", "run-metadata")
    elif family == "soak":
        for k, v in doc.items():
            if k == "args":
                fl.skip("soak.args", "run-config")
            else:
                fl.walk(f"soak.{k}", v)
    else:  # pragma: no cover - discover() only yields the three families
        fl.errors.append(f"{fname}: unknown ledger family {family!r}")
    return fl


def discover(root: str) -> List[Tuple[str, int, str]]:
    """Find ledgers in *root*; returns (family, round, path) sorted by round."""
    out: List[Tuple[str, int, str]] = []
    for fname in sorted(os.listdir(root)):
        m = _LEDGER_RE.match(fname)
        if m:
            out.append((m.group(1).lower(), int(m.group(2)), os.path.join(root, fname)))
    out.sort(key=lambda t: (t[0], t[1]))
    return out


def _median(vals: List[float]) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def build_trend(root: str) -> Dict[str, Any]:
    """Scan *root* and build the full trend document (pure; no I/O but reads)."""
    ledgers = discover(root)
    if not ledgers:
        raise LedgerError(f"no BENCH_r*/WORKLOADS_r*/SOAK_r* ledgers found under {root}")

    series: Dict[str, List[Tuple[int, float]]] = {}
    skipped: List[Dict[str, str]] = []
    errors: List[str] = []
    rounds_by_family: Dict[str, List[int]] = {}
    for family, rnd, path in ledgers:
        try:
            with open(path, "r") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{os.path.basename(path)}: unreadable ledger ({e})")
            continue
        fl = flatten_ledger(family, doc, os.path.basename(path))
        for item in fl.skipped:
            skipped.append(dict(item, ledger=os.path.basename(path)))
        for msg in fl.errors:
            errors.append(f"{os.path.basename(path)}: {msg}")
        for name, value in fl.rows.items():
            series.setdefault(name, []).append((rnd, value))
        rounds_by_family.setdefault(family, []).append(rnd)

    trajectories: Dict[str, Any] = {}
    all_rel_deltas: List[float] = []
    for name, pts in sorted(series.items()):
        pts.sort(key=lambda p: p[0])
        deltas: List[Optional[float]] = [None]
        for (r0, v0), (r1, v1) in zip(pts, pts[1:]):
            deltas.append((v1 - v0) / abs(v0) if v0 else None)
        # Noise is learned from *historical* transitions only; the latest
        # delta is the one under judgment and must not raise its own bar.
        for d in deltas[:-1]:
            if d is not None:
                all_rel_deltas.append(abs(d))
        trajectories[name] = {
            "points": [{"round": r, "value": v} for r, v in pts],
            "latest": pts[-1][1],
            "latest_round": pts[-1][0],
            "rel_delta_latest": deltas[-1] if len(pts) > 1 else None,
        }

    # Noise floor: how much series wiggle round-over-round across the whole
    # ledger history.  A regression must clear both the hard threshold and the
    # observed noise to fail the gate.
    noise_floor = max(NOISE_FLOOR_MIN, 1.5 * _median(all_rel_deltas))
    gate_threshold = max(REGRESSION_THRESHOLD, noise_floor)

    # The gate protects the *newest* round of each family.  A tracked series
    # whose last sample is from an older round is stale — the bench schema
    # moved past it — and charts without gating (a drop between two historical
    # rounds is a fact, not an actionable regression).
    latest_round = {fam: max(rs) for fam, rs in rounds_by_family.items()}
    probe_by_round = {
        p["round"]: p["value"]
        for p in trajectories.get(RIG_PROBE_SERIES, {}).get("points", [])
        if p["value"] > 0
    }
    regressions: List[Dict[str, Any]] = []
    for name, traj in trajectories.items():
        # Two tracked shapes: throughput rows (bench gbps series, regress DOWN)
        # and latency rows (soak/workloads p99 series, regress UP).  Both share
        # the same noise-floored gate threshold and stale-series exemption.
        if name.startswith("bench.") and "gbps" in name:
            if name == RIG_PROBE_SERIES:
                traj["rig_probe"] = True
                continue
            direction = "down"
        elif name.startswith(("soak.", "workloads.")) and "p99" in name:
            direction = "up"
        else:
            continue
        traj["tracked"] = True
        family = name.split(".", 1)[0]
        if traj["latest_round"] != latest_round.get(family):
            traj["stale"] = True
            continue
        d = traj["rel_delta_latest"]
        if d is None:
            continue
        pts = traj["points"]
        normalized = False
        if direction == "down":
            # rig normalization: judge the roofline FRACTION when both
            # rounds measured the probe on their own machine
            p0 = probe_by_round.get(pts[-2]["round"])
            p1 = probe_by_round.get(pts[-1]["round"])
            if p0 and p1 and pts[-2]["value"]:
                v0n = pts[-2]["value"] / p0
                v1n = pts[-1]["value"] / p1
                d = (v1n - v0n) / abs(v0n)
                traj["rel_delta_normalized"] = d
                normalized = True
        regressed = d < -gate_threshold if direction == "down" else d > gate_threshold
        if regressed:
            regressions.append(
                {
                    "series": name,
                    "direction": direction,
                    "prev_round": pts[-2]["round"],
                    "prev_value": pts[-2]["value"],
                    "round": pts[-1]["round"],
                    "value": pts[-1]["value"],
                    "rel_delta": d,
                    "rig_normalized": normalized,
                }
            )

    return {
        "root": os.path.abspath(root),
        "rounds": {fam: sorted(set(rs)) for fam, rs in rounds_by_family.items()},
        "noise_floor": round(noise_floor, 4),
        "gate_threshold": round(gate_threshold, 4),
        "num_series": len(trajectories),
        "series": trajectories,
        "regressions": regressions,
        "skipped": skipped,
        "errors": errors,
    }


def render_markdown(trend: Dict[str, Any]) -> str:
    lines = [
        "# Perf trend",
        "",
        "Generated by `python -m sparkrdma_tpu.obs.trend` from the committed",
        "`BENCH_r*` / `WORKLOADS_r*` / `SOAK_r*` ledgers. Do not edit by hand.",
        "",
        f"- rounds scanned: "
        + ", ".join(f"{fam} {rs}" for fam, rs in sorted(trend["rounds"].items())),
        f"- series: {trend['num_series']}, noise floor: {trend['noise_floor']:.1%},"
        f" gate threshold: ±{trend['gate_threshold']:.1%}"
        " (gbps rows gate on drops, p99 rows gate on rises; bench gbps"
        " gates rig-normalized when the loopback probe covers both rounds)",
        f"- regressions: {len(trend['regressions'])},"
        f" skipped rows: {len(trend['skipped'])}, errors: {len(trend['errors'])}",
        "",
    ]
    if trend["regressions"]:
        lines += ["## Regressions", ""]
        for r in trend["regressions"]:
            what = "latency rose" if r.get("direction") == "up" else "throughput dropped"
            lines.append(
                f"- **{r['series']}**: {r['prev_value']:g} (r{r['prev_round']:02d})"
                f" -> {r['value']:g} (r{r['round']:02d}), {r['rel_delta']:+.1%} ({what})"
            )
        lines.append("")
    for family in ("bench", "workloads", "soak"):
        rows = [
            (name, t)
            for name, t in trend["series"].items()
            if name.startswith(family + ".")
        ]
        if not rows:
            continue
        lines += [f"## {family}", "", "| series | trajectory | latest | Δ vs prev |", "|---|---|---|---|"]
        for name, t in rows:
            traj = " → ".join(f"{p['value']:g}" for p in t["points"])
            d = t["rel_delta_latest"]
            delta = f"{d:+.1%}" if d is not None else "—"
            mark = " ⚠" if any(r["series"] == name for r in trend["regressions"]) else ""
            lines.append(f"| `{name}` | {traj} | {t['latest']:g} | {delta}{mark} |")
        lines.append("")
    return "\n".join(lines) + "\n"


def _record_metrics(trend: Dict[str, Any]) -> None:
    try:
        from sparkrdma_tpu.obs.metrics import get_registry
    except Exception:
        return
    reg = get_registry()
    for fam, rs in trend["rounds"].items():
        reg.gauge("trend.rounds", family=fam).set(len(rs))
    reg.gauge("trend.series").set(trend["num_series"])
    reg.counter("trend.regressions").inc(len(trend["regressions"]))
    reg.counter("trend.skipped_rows").inc(len(trend["skipped"]))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparkrdma_tpu.obs.trend",
        description="Normalize bench ledgers into per-metric trajectories and gate on regressions.",
    )
    ap.add_argument("--dir", default=".", help="directory holding the *_rNN.json ledgers (default: cwd)")
    ap.add_argument("--out", default="TREND.json", help="output JSON path (default: TREND.json)")
    ap.add_argument("--md", default="TREND.md", help="output markdown path (default: TREND.md)")
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit 1 on a tracked-series regression, 2 on unclassifiable ledger rows",
    )
    args = ap.parse_args(argv)

    try:
        trend = build_trend(args.dir)
    except LedgerError as e:
        print(f"trend: {e}", file=sys.stderr)
        return 2

    _record_metrics(trend)
    with open(args.out, "w") as f:
        json.dump(trend, f, indent=1, sort_keys=False)
        f.write("\n")
    with open(args.md, "w") as f:
        f.write(render_markdown(trend))

    print(
        f"trend: {trend['num_series']} series across rounds {trend['rounds']};"
        f" {len(trend['regressions'])} regression(s), {len(trend['skipped'])} skipped row(s),"
        f" {len(trend['errors'])} error(s) -> {args.out}, {args.md}"
    )
    for msg in trend["errors"]:
        print(f"trend: ERROR {msg}", file=sys.stderr)
    for r in trend["regressions"]:
        what = "latency rose" if r.get("direction") == "up" else "throughput dropped"
        print(
            f"trend: REGRESSION {r['series']} {r['prev_value']:g} -> {r['value']:g}"
            f" ({r['rel_delta']:+.1%}, {what}) at round r{r['round']:02d}",
            file=sys.stderr,
        )
    if args.check:
        if trend["errors"]:
            return 2
        if trend["regressions"]:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
