"""Cluster telemetry plane: executor heartbeats -> driver time-series.

PR 1 gave every *process* a registry; nothing ever crossed the wire, so
the driver could not see a slow executor while a job ran. This module
is the Dapper-style move of centralizing cross-role signal, applied to
metrics:

- :class:`Heartbeater` runs on each executor: every
  ``obs.telemetry.intervalMs`` it takes a role-filtered
  ``MetricsRegistry`` snapshot, diffs it against a *moving baseline*
  (reset-safe, :func:`~sparkrdma_tpu.obs.metrics.snapshot_delta`), and
  ships the labeled delta + in-flight gauge samples either directly
  (in-process clusters: ``send=hub.ingest``) or into a bounded outbox
  the driver pulls over the engine control plane (the ``"telemetry"``
  task-protocol kind in ``engine/worker.py`` / ``engine/cluster.py``).
- :class:`TelemetryHub` runs on the driver: heartbeats fold into
  bounded per-executor :class:`~sparkrdma_tpu.obs.timeseries.TimeSeriesRing`
  buffers (wall-bucketed at the heartbeat interval, capped by
  ``obs.telemetry.ringSize``), an online straggler/skew detector runs a
  per-stage robust z-score over ``writer.pipeline.*`` /
  ``reader.pipeline.*`` / ``engine.task_ms`` busy-ms and
  ``transport.read_bytes`` / ``writer.bytes_written`` work rates, and
  two egress paths serve the result: an OpenMetrics exposition
  (``obs/export.py``, HTTP scrape on ``obs.telemetry.httpPort`` or a
  file) and a flight recorder that dumps the last N ring windows +
  recent spans + circuit-breaker states to one JSON artifact on
  ``FetchFailedError``/abort.

Flagged executors surface as ``telemetry.straggler{executor=...}``
gauges and a structured :meth:`TelemetryHub.straggler_report`, which
``SourceHealthRegistry.apply_straggler_report`` consumes as an
*advisory* signal (suspects are recorded, circuits are not opened —
docs/RESILIENCE.md).

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional

from sparkrdma_tpu.obs import journal as _journal
from sparkrdma_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
    parse_metric_key,
    snapshot_delta,
    strip_label,
)
from sparkrdma_tpu.obs.profiler import ProfileHub, SamplingProfiler
from sparkrdma_tpu.obs.timeseries import TimeSeriesRing

logger = logging.getLogger(__name__)

# Metric families the straggler detector reads. Busy families are
# time-spent signals (histogram sums / counters in ms): a straggler is
# an abnormally HIGH outlier. Work families are throughput signals
# (byte counters): a straggler is an abnormally LOW outlier.
BUSY_PREFIXES = ("writer.pipeline.stage_ms", "reader.pipeline.stage_ms",
                 "engine.task_ms")
WORK_PREFIXES = ("transport.read_bytes", "writer.bytes_written")

# Detection guards: a stage is only scored when at least MIN_PARTICIPANTS
# executors report nonzero activity on it (an executor that simply was
# not scheduled any reduce range is not a straggler), a busy flag needs
# a real absolute excess over the median, and a work flag needs the
# value to fall below half the median of a non-trivial workload.
MIN_PARTICIPANTS = 3
MIN_BUSY_EXCESS_MS = 50.0
MIN_WORK_MEDIAN_BYTES = 1 << 16
# MAD == 0 fallback: treat 15% of the median as one deviation unit so
# identical-but-for-jitter executors don't divide by zero into flags.
MAD_FALLBACK_FRACTION = 0.15
# A heartbeat is "missed" once nothing arrived for this many intervals.
MISSED_AFTER_INTERVALS = 2.5


def _hist_payload(h: Mapping) -> dict:
    """Wire form of one histogram delta: count/sum plus the FULL
    per-bucket delta vector — what the hub's rings need to evaluate
    latency SLOs. Zero entries are kept deliberately: the exceedance
    snap (obs/slo.py) derives the instrument's bound set from the keys,
    and a pruned vector would snap a threshold past absent bounds and
    under-count real exceedances. Idle instruments (zero count delta)
    are pruned entirely at the call site, so this costs nothing while
    nothing happens."""
    out = {"count": h["count"], "sum": h["sum"]}
    buckets = h.get("buckets")
    if buckets:
        out["buckets"] = dict(buckets)
    return out


def _robust_z(value: float, values: List[float]) -> float:
    """Robust z-score of ``value`` within ``values`` (median/MAD)."""
    med = statistics.median(values)
    mad = statistics.median(abs(v - med) for v in values)
    scale = 1.4826 * mad
    if scale <= 0.0:
        scale = max(MAD_FALLBACK_FRACTION * abs(med), 1e-9)
    return (value - med) / scale


class Heartbeater:
    """Executor-side heartbeat loop over a moving registry baseline.

    Each :meth:`beat` produces one payload::

        {"v": 1, "executor_id": ..., "seq": n, "wall_ms": ...,
         "interval_ms": ..., "counters": {key: delta != 0},
         "gauges": {key: {"value", "hwm"}},
         "histograms": {key: {"count": dc, "sum": ds,
                              "buckets": <full per-bucket deltas>}
                        for keys with dc != 0}

    With ``send`` the payload ships immediately (in-process hub);
    without, it lands in a bounded outbox the driver drains via the
    ``"telemetry"`` control-plane request (``seq`` keeps counting when
    the outbox overflows, so the hub sees the gap). ``pause()`` /
    ``resume()`` simulate a lost executor without stopping the thread.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        executor_id: str,
        interval_ms: int = 1000,
        send: Optional[Callable[[dict], None]] = None,
        match: Optional[Mapping[str, str]] = None,
        outbox_size: int = 256,
        clock: Callable[[], float] = time.time,
        profiler: Optional[SamplingProfiler] = None,
    ):
        self._registry = registry
        self.executor_id = executor_id
        self.interval_ms = max(1, int(interval_ms))
        self._send = send
        self._match = dict(match) if match else None
        self._clock = clock
        self._profiler = profiler
        self._outbox: "deque[dict]" = deque(maxlen=max(1, outbox_size))
        self._lock = threading.Lock()
        self._prev = registry.snapshot(self._match)
        self._seq = 0
        self._paused = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # event-journal shipping state: cursor into the process journal
        # plus the previous beat's batch (one-beat redundancy). The
        # journal is resolved per beat (active_journal) so a journal
        # configured after this heartbeater starts still ships.
        self._journal_override: Optional[_journal.EventJournal] = None
        self._journal_cursor = 0
        self._journal_prev: List[dict] = []

    def beat(self) -> Optional[dict]:
        """One sample: delta vs the moving baseline, then advance it."""
        with self._lock:
            if self._paused:
                return None
            cur = self._registry.snapshot(self._match)
            delta = snapshot_delta(self._prev, cur)
            self._prev = cur
            self._seq += 1
            seq = self._seq
        from sparkrdma_tpu.obs.trace import epoch_anchor

        payload = {
            "v": 1,
            "executor_id": self.executor_id,
            "seq": seq,
            "wall_ms": int(self._clock() * 1000),
            "interval_ms": self.interval_ms,
            # wall-clock anchor of this process's span timeline: the
            # hub hands these to the trace exporter so cross-process
            # merges don't skew by per-process module-load epochs
            "epoch_ms": int(epoch_anchor() * 1000),
            "counters": {k: v for k, v in delta["counters"].items() if v},
            "gauges": {
                k: g for k, g in delta["gauges"].items()
                if g.get("value") or g.get("hwm")
            },
            "histograms": {
                k: _hist_payload(h)
                for k, h in delta["histograms"].items()
                if h["count"]
            },
        }
        # continuous-profiling piggyback: the collapsed-stack table
        # folded since the last beat rides the same payload/pull path
        if self._profiler is not None:
            profile = self._profiler.drain()
            if profile:
                payload["profile"] = profile
        # event-journal piggyback: heartbeats are the causality-carrying
        # messages of the journal's HLC protocol. Each beat ships the
        # PREVIOUS beat's batch again alongside the new events (one-beat
        # redundancy), so a single lost heartbeat loses nothing and the
        # hub's (origin, seq) dedupe folds the overlap to one copy.
        j = self._journal_override or _journal.active_journal()
        if j is not None:
            with self._lock:
                fresh = j.events_since(self._journal_cursor)
                if fresh:
                    self._journal_cursor = fresh[-1]["seq"]
                batch = self._journal_prev + fresh
                self._journal_prev = fresh
            if batch:
                payload["journal"] = batch
        if self._send is not None:
            try:
                self._send(payload)
            except Exception:
                logger.debug("heartbeat send failed", exc_info=True)
        else:
            self._outbox.append(payload)
        return payload

    def drain(self) -> List[dict]:
        """Pull-side: hand over (and clear) the buffered payloads."""
        out: List[dict] = []
        while True:
            try:
                out.append(self._outbox.popleft())
            except IndexError:
                return out

    def attach_profiler(self, profiler: Optional[SamplingProfiler]) -> None:
        """Piggyback a sampling profiler's drained collapsed-stack
        table onto every subsequent beat (``payload["profile"]``)."""
        self._profiler = profiler

    def attach_journal(self, journal) -> None:
        """Ship this journal's events instead of the process journal
        (tests / explicit wiring); None reverts to per-beat
        ``active_journal()`` resolution."""
        self._journal_override = journal

    def pause(self) -> None:
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    def start(self) -> "Heartbeater":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop,
                name=f"heartbeat-{self.executor_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.beat()
            except Exception:
                logger.exception("heartbeat loop error")

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if flush:
            self.beat()


class TelemetryHub:
    """Driver-side fold of executor heartbeats into bounded time series.

    Passive unless fed: :meth:`ingest` does all online work (ring fold,
    gap accounting, straggler detection, optional OpenMetrics file
    write), so the hub adds no threads of its own beyond the optional
    HTTP scrape server.
    """

    _flight_seq = 0

    def __init__(
        self,
        conf=None,
        *,
        role: str = "driver",
        health=None,
        registry: Optional[MetricsRegistry] = None,
        interval_ms: Optional[int] = None,
        ring_size: Optional[int] = None,
        straggler_z: Optional[float] = None,
        http_port: Optional[int] = None,
        openmetrics_file: Optional[str] = None,
        flight_dir: Optional[str] = None,
        flight_windows: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.role = role
        self._health = health
        self._registry = registry or get_registry()
        self._clock = clock
        self.interval_ms = int(
            interval_ms
            if interval_ms is not None
            else (conf.telemetry_interval_ms if conf is not None else 1000)
        )
        self.ring_size = int(
            ring_size
            if ring_size is not None
            else (conf.telemetry_ring_size if conf is not None else 128)
        )
        self.straggler_z = float(
            straggler_z
            if straggler_z is not None
            else (conf.telemetry_straggler_z if conf is not None else 3)
        )
        self._http_port = int(
            http_port
            if http_port is not None
            else (conf.telemetry_http_port if conf is not None else 0)
        )
        self._openmetrics_file = (
            openmetrics_file
            if openmetrics_file is not None
            else (conf.telemetry_openmetrics_file if conf is not None else "")
        )
        self._flight_dir = (
            flight_dir
            if flight_dir is not None
            else (conf.telemetry_flight_dir if conf is not None else "")
        )
        self.flight_windows = int(
            flight_windows
            if flight_windows is not None
            else (conf.telemetry_flight_windows if conf is not None else 16)
        )

        self._lock = threading.Lock()
        self._series: Dict[str, TimeSeriesRing] = {}
        # executor -> wall-clock span-timeline anchor (seconds), from
        # the heartbeat's epoch_ms; consumed by trace-merge exports
        self._epoch_anchors: Dict[str, float] = {}
        # per-executor missed-heartbeat accounting: True once the gap
        # was counted; cleared (and surfaced as a ring gap marker) when
        # the executor resumes
        self._missed_counted: Dict[str, bool] = {}
        self._last_report: dict = {"stragglers": []}
        # per-shuffle per-partition published byte totals, fed by the
        # driver's publish handler as map outputs (incremental windows
        # included) land — the adaptive partition planner's skew signal
        # (shuffle/planner.py). Bounded: oldest shuffle evicted.
        self._partition_bytes: Dict[int, Dict[int, int]] = {}
        self._partition_bytes_max_shuffles = 64
        # same totals split by SOURCE executor (the DMA "lane" of the
        # whole-stage collective schedule): shuffle -> source -> pid ->
        # bytes. Feeds the planner's lane-balanced cuts; bounded with
        # and evicted alongside _partition_bytes.
        self._partition_lane_bytes: Dict[int, Dict[str, Dict[int, int]]] = {}
        self._last_file_write_ms = 0
        self.last_flight_path: Optional[str] = None
        self.last_flight: Optional[dict] = None
        # cluster-wide merge of the executors' collapsed-stack profile
        # tables (heartbeat "profile" payloads, obs/profiler.py)
        self.profiles = ProfileHub(clock=clock)
        # cluster event journal: configure this process's journal from
        # conf (the driver-side transitions emit into it) and merge the
        # heartbeat-shipped batches into one causally-ordered record
        self.journal_flight_events = int(
            conf.journal_flight_events if conf is not None else 64
        )
        _journal.configure(conf, role=role, registry=self._registry,
                           clock=clock)
        journal_ring = int(
            conf.journal_ring_size if conf is not None else 512
        )
        self.journal = _journal.JournalHub(
            self._registry, role=role, ring_size=journal_ring * 4,
            clock=clock,
        )
        # USE-method capacity plane: evaluated on the ingest cadence
        # beside the SLO engine (obs/capacity.py)
        from sparkrdma_tpu.obs.capacity import CapacityPlane

        if conf is not None:
            cap_conf = conf
        else:
            from sparkrdma_tpu.utils.config import TpuShuffleConf

            cap_conf = TpuShuffleConf()
        self.capacity = CapacityPlane(
            cap_conf, self._registry, role=role, clock=clock
        )
        # last critical-path TimeBreakdown the engine attributed — the
        # diagnosis engine's dominant-category evidence (obs/attr.py)
        self.last_breakdown: Optional[dict] = None

        reg = self._registry
        self._g_executors = reg.gauge("telemetry.executors", role=role)
        self._g_missed = reg.gauge("telemetry.missed_heartbeats", role=role)
        self._g_stragglers = reg.gauge("telemetry.stragglers", role=role)
        self._c_bad = reg.counter("telemetry.bad_payloads", role=role)

        # SLO judgment layer: rides ingest() on its own cadence; every
        # page/warn transition is answered with an automated root-cause
        # diagnosis (obs/slo.py, obs/diagnose.py)
        from sparkrdma_tpu.obs.slo import SLOEngine

        self.slo = SLOEngine(self, conf, registry=self._registry,
                             role=role, clock=clock)
        self.slo.on_breach = self._on_slo_breach

        self._http = None
        if self._http_port > 0:
            from sparkrdma_tpu.obs.export import OpenMetricsServer

            self._http = OpenMetricsServer(
                self.render_openmetrics, port=self._http_port
            )

    # -- per-partition skew statistics (adaptive planner input) --------
    def record_partition_bytes(
        self, shuffle_id: int, pid: int, nbytes: int, source: str = ""
    ) -> None:
        """Accumulate one published location's bytes for (shuffle, pid).

        ``source`` (the publishing executor id) additionally files the
        bytes under that DMA lane for the planner's lane-balanced cuts;
        empty keeps the pre-existing totals-only accounting."""
        with self._lock:
            per = self._partition_bytes.get(shuffle_id)
            if per is None:
                while len(self._partition_bytes) >= self._partition_bytes_max_shuffles:
                    old = next(iter(self._partition_bytes))
                    self._partition_bytes.pop(old)
                    self._partition_lane_bytes.pop(old, None)
                per = self._partition_bytes[shuffle_id] = {}
            per[pid] = per.get(pid, 0) + int(nbytes)
            if source:
                lanes = self._partition_lane_bytes.setdefault(shuffle_id, {})
                lane = lanes.setdefault(source, {})
                lane[pid] = lane.get(pid, 0) + int(nbytes)

    def partition_bytes(self, shuffle_id: int) -> Dict[int, int]:
        """Per-partition byte totals observed so far for one shuffle."""
        with self._lock:
            return dict(self._partition_bytes.get(shuffle_id, ()))

    def partition_lane_bytes(self, shuffle_id: int) -> Dict[str, Dict[int, int]]:
        """Per-source per-partition byte totals (source -> pid -> bytes)."""
        with self._lock:
            lanes = self._partition_lane_bytes.get(shuffle_id, {})
            return {src: dict(per) for src, per in lanes.items()}

    def drop_partition_bytes(self, shuffle_id: int) -> None:
        with self._lock:
            self._partition_bytes.pop(shuffle_id, None)
            self._partition_lane_bytes.pop(shuffle_id, None)

    # -- ingest --------------------------------------------------------
    def ingest(self, payload: Mapping) -> None:
        """Fold one heartbeat payload into its executor's ring."""
        try:
            exec_id = str(payload["executor_id"])
            wall_ms = int(payload["wall_ms"])
            seq = int(payload.get("seq", 0))
        except (KeyError, TypeError, ValueError):
            self._c_bad.inc()
            return
        with self._lock:
            ring = self._series.get(exec_id)
            if ring is None:
                ring = TimeSeriesRing(self.ring_size, self.interval_ms)
                self._series[exec_id] = ring
            gap = False
            if ring.last_seq and seq > ring.last_seq + 1:
                gap = True
                self._g_missed.add(seq - ring.last_seq - 1)
            if self._missed_counted.pop(exec_id, False):
                gap = True  # resumed after a wall-clock gap
            anchor = payload.get("epoch_ms")
            if anchor:
                try:
                    self._epoch_anchors[exec_id] = float(anchor) / 1000.0
                except (TypeError, ValueError):
                    pass
            self._g_executors.set(len(self._series))
        ring.append(
            wall_ms,
            seq,
            counters=payload.get("counters"),
            gauges=payload.get("gauges"),
            histograms=payload.get("histograms"),
            gap=gap,
        )
        profile = payload.get("profile")
        if profile:
            try:
                self.profiles.ingest(exec_id, profile, wall_ms=wall_ms)
            except (KeyError, TypeError, ValueError):
                self._c_bad.inc()
        events = payload.get("journal")
        if events:
            try:
                # idempotent + gap-tolerant merge; folds each event's
                # HLC into the hub process's clock (message receive)
                self.journal.ingest(events)
            except (KeyError, TypeError, ValueError):
                self._c_bad.inc()
        self._registry.counter(
            "telemetry.heartbeats", role=self.role, executor=exec_id
        ).inc()
        self.check_missed(now_ms=wall_ms)
        self._update_stragglers()
        self.slo.maybe_evaluate(now_ms=wall_ms)
        self.capacity.maybe_evaluate(now_ms=wall_ms)
        self._maybe_write_file(wall_ms)

    def check_missed(self, now_ms: Optional[int] = None) -> List[str]:
        """Flag executors whose last heartbeat is stale; returns the
        newly-flagged ids. A gap is counted ONCE per outage (gauge
        ``telemetry.missed_heartbeats``); the executor's next heartbeat
        re-arms the check and marks the gap in its ring."""
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        stale_after = MISSED_AFTER_INTERVALS * self.interval_ms
        newly: List[str] = []
        with self._lock:
            for exec_id, ring in self._series.items():
                if self._missed_counted.get(exec_id):
                    continue
                if ring.last_wall_ms and now_ms - ring.last_wall_ms > stale_after:
                    self._missed_counted[exec_id] = True
                    self._g_missed.add(1)
                    newly.append(exec_id)
        for exec_id in newly:
            logger.warning(
                "telemetry: no heartbeat from %s for > %.0f ms",
                exec_id, stale_after,
            )
        return newly

    # -- read side -----------------------------------------------------
    def executors(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def epoch_anchors(self) -> Dict[str, float]:
        """Role → wall-clock span-timeline anchor (seconds), learned
        from heartbeats. Hand to ``to_chrome_trace(epochs=...)`` /
        ``collect_spans_with_epochs`` when merging spans shipped from
        other processes, so per-process module-load epochs don't skew
        the merged timeline."""
        with self._lock:
            return dict(self._epoch_anchors)

    def series(self, executor_id: str) -> Optional[TimeSeriesRing]:
        with self._lock:
            return self._series.get(executor_id)

    def timeline(self, last: Optional[int] = None) -> Dict[str, List[dict]]:
        """JSON-able per-executor window lists (bench artifacts)."""
        with self._lock:
            items = list(self._series.items())
        return {eid: ring.to_list(last) for eid, ring in items}

    def rollups(self, last: Optional[int] = None) -> Dict[str, dict]:
        with self._lock:
            items = list(self._series.items())
        return {eid: ring.rollup(last) for eid, ring in items}

    def ring_windows(self, last: Optional[int] = None) -> Dict[str, list]:
        """Live per-executor :class:`Window` lists — the SLO engine's
        burn-rate input (same data as :meth:`timeline`, un-serialized)."""
        with self._lock:
            items = list(self._series.items())
        return {eid: ring.windows(last) for eid, ring in items}

    def missed_executors(self) -> List[str]:
        """Executors currently inside a counted heartbeat outage."""
        with self._lock:
            return sorted(e for e, v in self._missed_counted.items() if v)

    def last_straggler_report(self) -> dict:
        with self._lock:
            return self._last_report

    def source_health(self) -> Dict[str, str]:
        """Circuit-breaker states, or {} when no registry is attached."""
        return self._health.states() if self._health is not None else {}

    def note_breakdown(self, breakdown: Optional[dict]) -> None:
        """Record the engine's latest critical-path TimeBreakdown dict
        as diagnosis evidence (best-effort; None is ignored)."""
        if breakdown:
            self.last_breakdown = breakdown

    def _on_slo_breach(self, breach) -> None:
        """Answer a page/warn transition with an automated root-cause
        pass. Best-effort: diagnosis must never add a failure mode to
        the ingest path that detected the breach."""
        try:
            from sparkrdma_tpu.obs.diagnose import build_diagnosis

            diag = build_diagnosis(self, breach, registry=self._registry,
                                   clock=self._clock)
            self.slo.note_diagnosis(diag)
        except Exception:
            logger.exception("automated diagnosis failed")

    def summary(self) -> dict:
        """Compact hub view for ``metrics_snapshot()`` on the driver."""
        with self._lock:
            execs = {
                eid: {
                    "windows": len(ring),
                    "last_wall_ms": ring.last_wall_ms,
                    "last_seq": ring.last_seq,
                    "missed": bool(self._missed_counted.get(eid)),
                }
                for eid, ring in self._series.items()
            }
        return {
            "interval_ms": self.interval_ms,
            "ring_size": self.ring_size,
            "executors": execs,
            "stragglers": list(self._last_report.get("stragglers", [])),
            "missed_heartbeats": self._g_missed.value,
            "profile": self.profiles.summary(),
            "journal": self.journal.summary(),
            "capacity": self.capacity.summary(),
        }

    # -- straggler / skew detection ------------------------------------
    def straggler_report(self) -> dict:
        """Online per-stage robust z-score over busy-ms and work rates.

        Keys are normalized (``role``/``executor`` labels stripped) so
        the same instrument on two executors compares directly. A stage
        is scored only when >= ``MIN_PARTICIPANTS`` executors report
        nonzero activity on it; an executor is a straggler when any
        busy stage scores ``> straggler_z`` with a real absolute excess,
        or any work family scores ``< -straggler_z`` at under half the
        median of a non-trivial workload."""
        rollups = self.rollups()
        busy_by_stage: Dict[str, Dict[str, float]] = {}
        work_by_family: Dict[str, Dict[str, float]] = {}
        for eid, roll in rollups.items():
            for key, h in roll["histograms"].items():
                name, _ = parse_metric_key(key)
                if name.startswith(BUSY_PREFIXES):
                    norm = strip_label(key, "role", "executor")
                    busy_by_stage.setdefault(norm, {})[eid] = (
                        busy_by_stage.get(norm, {}).get(eid, 0.0)
                        + float(h.get("sum", 0.0))
                    )
            for key, v in roll["counters"].items():
                name, _ = parse_metric_key(key)
                if name.startswith(BUSY_PREFIXES):
                    norm = strip_label(key, "role", "executor")
                    busy_by_stage.setdefault(norm, {})[eid] = (
                        busy_by_stage.get(norm, {}).get(eid, 0.0) + float(v)
                    )
                elif name.startswith(WORK_PREFIXES):
                    norm = strip_label(key, "role", "executor")
                    work_by_family.setdefault(norm, {})[eid] = (
                        work_by_family.get(norm, {}).get(eid, 0.0) + float(v)
                    )

        details: Dict[str, dict] = {
            eid: {"busy_ms": 0.0, "work_bytes": 0.0, "flags": []}
            for eid in rollups
        }
        stragglers: set = set()
        # (tenant, eid) pairs behind each flag: the tenant label
        # survives the role/executor strip, so the verdicts stay
        # tenant-scoped all the way into the health registry
        flagged_pairs: set = set()
        for stage, per_exec in busy_by_stage.items():
            for eid, v in per_exec.items():
                details[eid]["busy_ms"] += v
            participants = {e: v for e, v in per_exec.items() if v > 0}
            if len(participants) < MIN_PARTICIPANTS:
                continue
            values = list(participants.values())
            med = statistics.median(values)
            for eid, v in participants.items():
                z = _robust_z(v, values)
                if z > self.straggler_z and (v - med) > MIN_BUSY_EXCESS_MS:
                    stragglers.add(eid)
                    flagged_pairs.add(
                        (parse_metric_key(stage)[1].get("tenant", ""), eid)
                    )
                    details[eid]["flags"].append({
                        "kind": "busy", "stage": stage,
                        "z": round(z, 2), "value_ms": round(v, 3),
                        "median_ms": round(med, 3),
                    })
        for family, per_exec in work_by_family.items():
            for eid, v in per_exec.items():
                details[eid]["work_bytes"] += v
            participants = {e: v for e, v in per_exec.items() if v > 0}
            if len(participants) < MIN_PARTICIPANTS:
                continue
            values = list(participants.values())
            med = statistics.median(values)
            if med < MIN_WORK_MEDIAN_BYTES:
                continue
            for eid, v in participants.items():
                z = _robust_z(v, values)
                if z < -self.straggler_z and v < med / 2:
                    stragglers.add(eid)
                    flagged_pairs.add(
                        (parse_metric_key(family)[1].get("tenant", ""), eid)
                    )
                    details[eid]["flags"].append({
                        "kind": "work", "family": family,
                        "z": round(z, 2), "value_bytes": int(v),
                        "median_bytes": int(med),
                    })
        # breaker-registry-shaped suspect keys: bare executor id for
        # the default tenant, "<tenant>:<executor>" otherwise — the
        # exact format SourceHealthRegistry._key produces, so
        # apply_straggler_report needs no re-derivation
        from sparkrdma_tpu.tenancy import DEFAULT_TENANT

        suspect_keys = sorted(
            eid if (not t or t == DEFAULT_TENANT) else f"{t}:{eid}"
            for t, eid in flagged_pairs
        )
        report = {
            "generated_wall_ms": int(self._clock() * 1000),
            "threshold_z": self.straggler_z,
            "executors": details,
            "stragglers": sorted(stragglers),
            "suspect_keys": suspect_keys,
        }
        return report

    def _update_stragglers(self) -> None:
        report = self.straggler_report()
        flagged = set(report["stragglers"])
        known = set(report["executors"])
        prev = set(self._last_report.get("stragglers", ()))
        for eid in sorted(flagged - prev):
            _journal.emit("straggler.flag", role=self.role, executor=eid)
        for eid in sorted(prev - flagged):
            _journal.emit("straggler.clear", role=self.role, executor=eid)
        self._g_stragglers.set(len(flagged))
        for eid in known:
            self._registry.gauge(
                "telemetry.straggler", role=self.role, executor=eid
            ).set(1 if eid in flagged else 0)
        self._last_report = report
        if self._health is not None:
            try:
                self._health.apply_straggler_report(report)
            except Exception:
                logger.exception("straggler advisory failed")

    # -- egress: OpenMetrics -------------------------------------------
    def render_openmetrics(self) -> str:
        from sparkrdma_tpu.obs.export import render_openmetrics

        return render_openmetrics(self._registry.snapshot())

    def _maybe_write_file(self, now_ms: int) -> None:
        if not self._openmetrics_file:
            return
        if now_ms - self._last_file_write_ms < self.interval_ms:
            return
        self._last_file_write_ms = now_ms
        try:
            from sparkrdma_tpu.obs.export import write_openmetrics

            write_openmetrics(self._openmetrics_file,
                              self._registry.snapshot())
        except OSError:
            logger.warning("openmetrics file write failed",
                           exc_info=True)

    @property
    def http_port(self) -> Optional[int]:
        return self._http.port if self._http is not None else None

    # -- egress: flight recorder ---------------------------------------
    def flight_record(self, reason: str, error: Optional[BaseException] = None,
                      path: Optional[str] = None,
                      breakdown: Optional[dict] = None) -> Optional[str]:
        """Dump the post-mortem artifact: last N ring windows per
        executor + recent spans + circuit-breaker states + the failed
        group (from the error's ``shuffle_id``/``partition_id``/
        ``manager_id`` attributes when present). ``breakdown`` attaches
        the failed window's critical-path TimeBreakdown dict
        (obs/attr.py) when the caller computed one. Best-effort:
        returns the written path, or None — never a new failure mode."""
        doc: dict = {
            "kind": "sparkrdma_flight_record",
            "version": 1,
            "generated_wall_ms": int(self._clock() * 1000),
            "role": self.role,
            "reason": reason,
            "interval_ms": self.interval_ms,
            "executors": self.timeline(last=self.flight_windows),
            "stragglers": self._last_report,
            "source_health": (
                self._health.states() if self._health is not None else {}
            ),
            "slo": self.slo.summary(),
            # last-N merged journal events around the failure: the
            # causally-ordered incident context (obs/journal.py);
            # rendered by `python -m sparkrdma_tpu.obs --timeline`
            "journal": self.journal.merged(last=self.journal_flight_events),
            "capacity": self.capacity.capacity_report(refresh=True),
        }
        # last profile window per executor: the collapsed-stack view of
        # what each process's CPUs were doing just before the failure
        profiles = self.profiles.last_windows()
        if profiles:
            doc["profiles"] = profiles
        if breakdown is not None:
            doc["breakdown"] = breakdown
        if error is not None:
            doc["error"] = {
                "type": type(error).__name__,
                "message": str(error),
            }
            failed = {}
            for attr in ("shuffle_id", "map_id", "partition_id"):
                v = getattr(error, attr, None)
                if v is not None:
                    failed[attr] = v
            mid = getattr(error, "manager_id", None)
            if mid is not None:
                failed["source"] = str(mid)
            if failed:
                doc["failed_group"] = failed
        try:
            from sparkrdma_tpu.obs.trace import collect_spans

            doc["spans"] = [
                {
                    "name": sp.name,
                    "role": sp.role,
                    "trace_id": f"{sp.trace_id:#x}" if sp.trace_id else None,
                    "start": sp.start,
                    "end": sp.end,
                    "args": dict(sp.args),
                }
                for sp in collect_spans()[-200:]
            ]
        except Exception:
            doc["spans"] = []
        if path is None:
            base = self._flight_dir or tempfile.gettempdir()
            TelemetryHub._flight_seq += 1
            path = os.path.join(
                base,
                f"sparkrdma-flight-{os.getpid()}-{TelemetryHub._flight_seq}.json",
            )
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, default=str)
        except OSError:
            logger.warning("flight record write to %s failed", path,
                           exc_info=True)
            path = None
        else:
            logger.warning("flight record written: %s (%s)", path, reason)
        self.last_flight = doc
        self.last_flight_path = path
        return path

    def stop(self) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self._openmetrics_file:
            # final exposition so scrape-less runs keep the end state
            self._last_file_write_ms = 0
            self._maybe_write_file(int(self._clock() * 1000))
