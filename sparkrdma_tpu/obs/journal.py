"""Cluster event journal: HLC-ordered control-plane state transitions.

Every production incident in the driver-hub design is explained by a
handful of control-plane transitions — a lease takeover, a replica
promotion, a circuit trip, a quota block, an autotuner re-cut, an SLO
page — but until this module they existed only as counters: magnitudes
without order. The journal makes them an ordered record:

- Each process holds ONE bounded :class:`EventJournal` (process-local
  singleton, like the metrics registry). Control-plane code calls the
  module-level :func:`emit` at its transition sites; when no journal is
  configured (``tpu.shuffle.obs.journal.enabled=false`` or telemetry
  never started) the call is a single module-global load + None check —
  zero hot-path cost by construction.
- Events carry a **hybrid logical clock** ``(l_ms, c)``: ``l`` tracks
  the max wall clock observed, ``c`` breaks ties within one
  millisecond. Heartbeats are the causality-carrying messages — the hub
  folds every ingested event's HLC into its own process clock, so a
  driver event emitted *after* ingesting an executor's events always
  sorts *after* them, regardless of wall-clock skew.
- Events ship on the existing heartbeat payloads (push and pull modes,
  ``payload["journal"]``) with **one-beat redundancy**: each beat
  re-ships the previous beat's batch alongside the new events, so a
  single lost heartbeat loses nothing, and the hub-side
  :class:`JournalHub` merge is idempotent (dedup by ``(origin, seq)``)
  and gap-tolerant (a seq jump is counted, never fatal).
- The merged journal sorts by ``(l, c, origin, seq)`` — a total order
  consistent with causality as carried by heartbeats, with per-emitter
  order always preserved (``seq`` is strictly increasing per process
  and the process HLC never goes backward).

Event taxonomy (``kind`` values; docs/OBSERVABILITY.md "Event journal
& capacity plane"):

==================  ===================================================
kind                transition
==================  ===================================================
meta.takeover       a metastore shard lease expired and was taken over
meta.epoch_bump     hub wipe / driver restart bumped the generation
meta.peer_kill      a metadata peer's lease was revoked (chaos / loss)
meta.adopt          an executor re-published committed state post-wipe
elastic.promote     replicas of a lost executor promoted to primary
elastic.spec        a reduce range was speculatively cloned
elastic.spec_win    a speculative clone finished first
circuit.open        a source circuit breaker opened
circuit.half_open   an open breaker allowed its trial fetch
circuit.close       a breaker closed after a successful trial
admission.enqueue   a job waited for an admission slot
admission.deadline  a job timed out waiting for admission
quota.block         a tenant blocked on a resource quota
quota.release       a blocked tenant's charge finally succeeded
quota.overrun       a blocked tenant overran its deadline grace
autotune.adjust     the WaveAutoTuner re-cut a stage shape's waveBytes
straggler.flag      the robust-z detector flagged an executor
straggler.clear     a flagged executor recovered
slo.page / slo.warn an SLO objective transitioned into breach
slo.recover         a breaching objective recovered
fault.injected      a testing/faults.py rule actually fired
==================  ===================================================

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "HLC",
    "EventJournal",
    "JournalHub",
    "active_journal",
    "configure",
    "emit",
    "events_to_chrome",
    "extract_events",
    "get_journal",
    "render_timeline",
    "reset",
    "set_enabled",
    "sort_key",
]

DEFAULT_RING_SIZE = 512
DEFAULT_FLIGHT_EVENTS = 64


class HLC:
    """Hybrid logical clock: ``(l_ms, c)`` per Kulkarni et al.

    ``l`` never falls behind the local wall clock; ``c`` disambiguates
    events within one l. :meth:`observe` merges a remote timestamp so
    local events issued after a message sort after the message's
    events. Thread-safe; ticks are a few dict-free integer ops."""

    __slots__ = ("_l", "_c", "_lock")

    def __init__(self) -> None:
        self._l = 0
        self._c = 0
        self._lock = threading.Lock()

    def tick(self, wall_ms: int) -> Tuple[int, int]:
        """Timestamp one local event."""
        with self._lock:
            if wall_ms > self._l:
                self._l = wall_ms
                self._c = 0
            else:
                self._c += 1
            return (self._l, self._c)

    def observe(self, remote: Tuple[int, int], wall_ms: int) -> Tuple[int, int]:
        """Merge a remote HLC (message receive); returns the new local
        clock, which is strictly greater than both inputs' orderings."""
        rl, rc = int(remote[0]), int(remote[1])
        with self._lock:
            l = max(self._l, rl, wall_ms)
            if l == self._l == rl:
                self._c = max(self._c, rc) + 1
            elif l == self._l:
                self._c += 1
            elif l == rl:
                self._c = rc + 1
            else:
                self._c = 0
            self._l = l
            return (self._l, self._c)

    def read(self) -> Tuple[int, int]:
        with self._lock:
            return (self._l, self._c)


def sort_key(event: Mapping) -> Tuple[int, int, str, int]:
    """Total order of merged events: HLC first (causality), then
    ``(origin, seq)`` as a deterministic tie-break."""
    hlc = event.get("hlc") or (0, 0)
    return (int(hlc[0]), int(hlc[1]),
            str(event.get("origin", "")), int(event.get("seq", 0)))


class EventJournal:
    """Process-local bounded journal of control-plane events.

    One per process (module singleton via :func:`configure` /
    :func:`get_journal`); in-process clusters share it across roles, so
    every event carries its own ``role``/``executor`` attribution and
    ``origin`` identifies the emitting *process* for merge dedup."""

    def __init__(
        self,
        role: str = "proc",
        *,
        origin: Optional[str] = None,
        ring_size: int = DEFAULT_RING_SIZE,
        registry=None,
        clock: Callable[[], float] = time.time,
    ):
        self.role = role
        self.origin = origin or f"proc-{os.getpid()}"
        self._clock = clock
        self._hlc = HLC()
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(8, int(ring_size)))
        self._seq = 0
        if registry is None:
            from sparkrdma_tpu.obs.metrics import get_registry

            registry = get_registry()
        self._c_events = registry.counter("journal.events", role=role)

    # -- write side ----------------------------------------------------
    def emit(
        self,
        kind: str,
        *,
        role: Optional[str] = None,
        executor: str = "",
        tenant: str = "",
        shuffle_id: int = -1,
        span_id: int = 0,
        wall_ms: Optional[int] = None,
        **attrs,
    ) -> dict:
        """Record one typed event. Returns the event dict (wire form).

        Empty/zero identity fields are omitted from the wire form to
        keep heartbeat payloads small; ``attrs`` values must be
        JSON-able scalars/strings."""
        if wall_ms is None:
            wall_ms = int(self._clock() * 1000)
        event: dict = {
            "kind": str(kind),
            "wall_ms": int(wall_ms),
            "origin": self.origin,
            "role": role if role is not None else self.role,
        }
        if executor:
            event["executor"] = str(executor)
        if tenant:
            event["tenant"] = str(tenant)
        if shuffle_id >= 0:
            event["shuffle_id"] = int(shuffle_id)
        if span_id:
            event["span_id"] = int(span_id)
        if attrs:
            event["attrs"] = attrs
        with self._lock:
            # seq assignment and HLC tick must be one atomic step: if a
            # later seq could carry an earlier clock, the merged sort
            # would reorder one emitter's own events
            hlc = self._hlc.tick(wall_ms)
            self._seq += 1
            event["hlc"] = [hlc[0], hlc[1]]
            event["seq"] = self._seq
            self._ring.append(event)
        self._c_events.inc()
        return event

    def observe(self, remote_hlc) -> None:
        """Fold a received event's HLC into this process's clock — the
        message-receive half of the HLC protocol."""
        self._hlc.observe(remote_hlc, int(self._clock() * 1000))

    # -- read side -----------------------------------------------------
    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def events(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last else out

    def events_since(self, seq: int) -> List[dict]:
        """Non-destructive cursor read: events with ``seq`` greater than
        the given cursor, oldest first. A cursor older than the ring
        simply yields what survived — the shipping layer's one-beat
        redundancy plus the hub's gap counter cover the difference."""
        with self._lock:
            return [e for e in self._ring if e["seq"] > seq]


# ---------------------------------------------------------------------------
# process-local singleton + the zero-overhead emit seam
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_journal: Optional[EventJournal] = None
_suspended: Optional[EventJournal] = None
_disabled = False


def configure(
    conf=None,
    *,
    role: str = "proc",
    origin: Optional[str] = None,
    enabled: Optional[bool] = None,
    ring_size: Optional[int] = None,
    registry=None,
    clock: Callable[[], float] = time.time,
) -> Optional[EventJournal]:
    """Install (or disable) the process journal from conf/overrides.

    Called where telemetry starts (TelemetryHub / worker heartbeat
    setup). Idempotent: a live journal is kept (its ring survives
    reconfiguration) unless the new config disables it."""
    global _journal, _disabled
    on = bool(
        enabled if enabled is not None
        else (conf.journal_enabled if conf is not None else True)
    )
    size = int(
        ring_size if ring_size is not None
        else (conf.journal_ring_size if conf is not None
              else DEFAULT_RING_SIZE)
    )
    with _lock:
        if not on:
            _journal = None
            _disabled = True
            return None
        _disabled = False
        if _journal is None:
            _journal = EventJournal(
                role, origin=origin, ring_size=size,
                registry=registry, clock=clock,
            )
        return _journal


def get_journal() -> EventJournal:
    """The process journal, creating a default-configured one if none
    exists yet (and journaling was not explicitly disabled)."""
    global _journal
    with _lock:
        if _journal is None and not _disabled:
            _journal = EventJournal()
        if _journal is None:
            raise RuntimeError("event journal is disabled")
        return _journal


def active_journal() -> Optional[EventJournal]:
    """The process journal or None — never creates one."""
    return _journal


def emit(kind: str, **kwargs) -> Optional[dict]:
    """Module-level emit used by every control-plane transition site.

    The off path is ONE module-global load and a None check — the
    journal's entire disabled-mode hot-path cost."""
    j = _journal
    if j is None:
        return None
    return j.emit(kind, **kwargs)


def set_enabled(on: bool) -> None:
    """Flip the emit seam WITHOUT discarding the journal.

    Unlike :func:`configure` (which drops the journal when disabling),
    this parks the live journal aside and restores the same object on
    re-enable, preserving ``seq`` continuity and the ring contents — the
    seam the overhead A/B bench and the off-switch test flip."""
    global _journal, _suspended
    with _lock:
        if on:
            if _journal is None and _suspended is not None:
                _journal = _suspended
                _suspended = None
        else:
            if _journal is not None:
                _suspended = _journal
                _journal = None


def reset() -> None:
    """Drop the process journal and re-arm lazy creation (tests)."""
    global _journal, _suspended, _disabled
    with _lock:
        _journal = None
        _suspended = None
        _disabled = False


# ---------------------------------------------------------------------------
# hub-side merge
# ---------------------------------------------------------------------------
class JournalHub:
    """Driver-side merged journal over heartbeat-shipped event batches.

    Merge contract:

    - **idempotent** — events dedup by ``(origin, seq)``, so the
      one-beat redundancy in the shipping layer (and any outright
      heartbeat replay) folds to one copy;
    - **gap-tolerant** — a per-origin seq jump increments
      ``journal.gaps`` and the merge proceeds; nothing blocks on a
      lost event;
    - **causality-folding** — every ingested event's HLC is observed
      into the local process journal's clock, so hub-side events
      emitted after ingest sort after the executor events that caused
      them.
    """

    def __init__(
        self,
        registry=None,
        *,
        role: str = "driver",
        ring_size: int = 4 * DEFAULT_RING_SIZE,
        clock: Callable[[], float] = time.time,
    ):
        self.role = role
        self._clock = clock
        self._ring_size = max(8, int(ring_size))
        self._lock = threading.Lock()
        self._events: Dict[Tuple[str, int], dict] = {}
        self._last_seq: Dict[str, int] = {}
        if registry is None:
            from sparkrdma_tpu.obs.metrics import get_registry

            registry = get_registry()
        self._c_merged = registry.counter("journal.merged", role=role)
        self._c_dups = registry.counter("journal.duplicates", role=role)
        self._c_gaps = registry.counter("journal.gaps", role=role)
        self._g_size = registry.gauge("journal.size", role=role)
        # cursor into the LOCAL process journal: hub-side events fold
        # into the merged view without riding any heartbeat
        self._local_cursor = 0

    def ingest(self, events: Iterable[Mapping]) -> int:
        """Merge one shipped batch; returns how many were new."""
        local = _journal
        merged = 0
        max_hlc: Optional[Tuple[int, int]] = None
        with self._lock:
            for raw in events:
                try:
                    origin = str(raw["origin"])
                    seq = int(raw["seq"])
                    hlc = raw.get("hlc") or (0, 0)
                    hl, hc = int(hlc[0]), int(hlc[1])
                except (KeyError, TypeError, ValueError, IndexError):
                    continue
                key = (origin, seq)
                if key in self._events:
                    self._c_dups.inc()
                    continue
                last = self._last_seq.get(origin, 0)
                if seq > last + 1 and last:
                    self._c_gaps.inc(seq - last - 1)
                if seq > last:
                    self._last_seq[origin] = seq
                self._events[key] = dict(raw)
                merged += 1
                if max_hlc is None or (hl, hc) > max_hlc:
                    max_hlc = (hl, hc)
            self._trim_locked()
            self._g_size.set(len(self._events))
        if merged:
            self._c_merged.inc(merged)
        if max_hlc is not None and local is not None:
            local.observe(max_hlc)
        return merged

    def _trim_locked(self) -> None:
        over = len(self._events) - self._ring_size
        if over <= 0:
            return
        for key, _ in sorted(
            self._events.items(), key=lambda kv: sort_key(kv[1])
        )[:over]:
            del self._events[key]

    def fold_local(self) -> int:
        """Fold the local process journal's new events into the merged
        view (the driver's own transitions never ride a heartbeat)."""
        local = _journal
        if local is None:
            return 0
        events = local.events_since(self._local_cursor)
        if not events:
            return 0
        self._local_cursor = events[-1]["seq"]
        # local events share the hub's process clock: no observe needed
        merged = 0
        with self._lock:
            for e in events:
                key = (str(e["origin"]), int(e["seq"]))
                if key in self._events:
                    continue
                self._events[key] = e
                self._last_seq[key[0]] = max(
                    self._last_seq.get(key[0], 0), key[1]
                )
                merged += 1
            self._trim_locked()
            self._g_size.set(len(self._events))
        if merged:
            self._c_merged.inc(merged)
        return merged

    def merged(
        self,
        last: Optional[int] = None,
        *,
        kinds: Optional[Iterable[str]] = None,
        since_wall_ms: Optional[int] = None,
        until_wall_ms: Optional[int] = None,
    ) -> List[dict]:
        """The causally-ordered merged journal (filters optional;
        ``last`` keeps the N most recent by merged order)."""
        self.fold_local()
        with self._lock:
            out = sorted(self._events.values(), key=sort_key)
        if kinds is not None:
            want = set(kinds)
            out = [e for e in out if e.get("kind") in want]
        if since_wall_ms is not None:
            out = [e for e in out if e.get("wall_ms", 0) >= since_wall_ms]
        if until_wall_ms is not None:
            out = [e for e in out if e.get("wall_ms", 0) <= until_wall_ms]
        return out[-last:] if last else out

    def summary(self) -> dict:
        with self._lock:
            n = len(self._events)
            origins = sorted(self._last_seq)
        return {
            "events": n,
            "origins": [origins],
            "merged": self._c_merged.value,
            "duplicates": self._c_dups.value,
            "gaps": self._c_gaps.value,
        }


# ---------------------------------------------------------------------------
# exports: Chrome trace instants, artifact extraction, timeline render
# ---------------------------------------------------------------------------
def events_to_chrome(events: Iterable[Mapping],
                     pid: int = 0) -> List[dict]:
    """Journal events as Chrome trace *instant* events (``ph:"i"``) on
    the wall-clock timeline the span exporter already uses
    (``ts`` = wall microseconds) — global scope so each event draws a
    full-height marker through the trace."""
    out = []
    for e in sorted(events, key=sort_key):
        args = {
            "hlc": list(e.get("hlc") or (0, 0)),
            "origin": e.get("origin", ""),
            "seq": e.get("seq", 0),
        }
        for k in ("executor", "tenant", "shuffle_id", "span_id"):
            if e.get(k):
                args[k] = e[k]
        args.update(e.get("attrs") or {})
        out.append({
            "name": e.get("kind", "?"),
            "cat": "journal",
            "ph": "i",
            "s": "g",
            "ts": int(e.get("wall_ms", 0)) * 1000,
            "pid": pid,
            "tid": 0,
            "args": args,
        })
    return out


def extract_events(doc) -> List[dict]:
    """Pull journal events out of any artifact that carries them: a
    flight record (``doc["journal"]``), a soak ledger
    (``doc["journal"]`` at top level or under ``doc["slo"]``), a live
    snapshot dict, or a bare event list."""
    if isinstance(doc, list):
        return [e for e in doc if isinstance(e, Mapping) and "kind" in e]
    if not isinstance(doc, Mapping):
        return []
    for key in ("journal", "events"):
        v = doc.get(key)
        if isinstance(v, list):
            return extract_events(v)
        if isinstance(v, Mapping) and isinstance(v.get("events"), list):
            return extract_events(v["events"])
    slo = doc.get("slo")
    if isinstance(slo, Mapping):
        return extract_events(slo)
    return []


def render_timeline(events: Iterable[Mapping],
                    limit: Optional[int] = None) -> str:
    """Human-readable causally-ordered incident timeline."""
    ordered = sorted(events, key=sort_key)
    if limit:
        ordered = ordered[-limit:]
    if not ordered:
        return "journal timeline: no events"
    t0 = min(int(e.get("wall_ms", 0)) for e in ordered)
    out = [f"journal timeline ({len(ordered)} events, t0={t0} ms epoch)"]
    for e in ordered:
        hlc = e.get("hlc") or (0, 0)
        who = e.get("executor") or e.get("role", "")
        extras = []
        if e.get("tenant"):
            extras.append(f"tenant={e['tenant']}")
        if e.get("shuffle_id") is not None and "shuffle_id" in e:
            extras.append(f"shuffle={e['shuffle_id']}")
        for k, v in sorted((e.get("attrs") or {}).items()):
            extras.append(f"{k}={v}")
        out.append(
            f"  +{int(e.get('wall_ms', 0)) - t0:>7} ms "
            f"hlc=({int(hlc[0]) - t0},{hlc[1]:>2}) "
            f"{e.get('kind', '?'):<20} {who:<10} "
            + " ".join(extras)
        )
    return "\n".join(out)
