"""Unified observability layer: metrics registry + shuffle tracing.

See docs/OBSERVABILITY.md for metric names, label conventions, and the
Perfetto workflow. ``python -m sparkrdma_tpu.obs`` dumps the registry.
"""

from sparkrdma_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    metric_key,
)
from sparkrdma_tpu.obs.trace import (
    Span,
    Tracer,
    all_tracers,
    collect_spans,
    export_chrome_trace,
    get_tracer,
    mint_trace_id,
    now,
    to_chrome_trace,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "Tracer",
    "all_tracers",
    "collect_spans",
    "export_chrome_trace",
    "get_registry",
    "get_tracer",
    "metric_key",
    "mint_trace_id",
    "now",
    "to_chrome_trace",
]
