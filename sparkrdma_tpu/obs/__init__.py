"""Unified observability layer: metrics registry + shuffle tracing +
the cluster telemetry plane.

See docs/OBSERVABILITY.md for metric names, label conventions, the
Perfetto workflow, and the telemetry plane (heartbeats, time-series
rings, straggler detection, OpenMetrics export, flight recorder).
``python -m sparkrdma_tpu.obs`` dumps the registry.
"""

from sparkrdma_tpu.obs.capacity import CapacityPlane
from sparkrdma_tpu.obs.export import (
    OpenMetricsServer,
    extract_snapshot,
    render_openmetrics,
    write_openmetrics,
)
from sparkrdma_tpu.obs.journal import (
    HLC,
    EventJournal,
    JournalHub,
    active_journal,
    emit,
    events_to_chrome,
    extract_events,
    get_journal,
    render_timeline,
)
from sparkrdma_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    metric_key,
    parse_metric_key,
    snapshot_delta,
    strip_label,
)
from sparkrdma_tpu.obs.profiler import (
    ProfileHub,
    SamplingProfiler,
    acquire_profiler,
    get_profiler,
    release_profiler,
    render_flamegraph_html,
)
from sparkrdma_tpu.obs.diagnose import build_diagnosis, render_diagnosis
from sparkrdma_tpu.obs.slo import (
    Breach,
    Objective,
    SLOEngine,
    burn_rate,
    exceedance,
    judge,
    multi_window_burn,
)
from sparkrdma_tpu.obs.telemetry import Heartbeater, TelemetryHub
from sparkrdma_tpu.obs.timeseries import TimeSeriesRing, Window
from sparkrdma_tpu.obs.trace import (
    Span,
    SpanHandle,
    Tracer,
    all_tracers,
    collect_spans,
    collect_spans_with_epochs,
    export_chrome_trace,
    get_tracer,
    mint_trace_id,
    now,
    to_chrome_trace,
)

__all__ = [
    "Breach",
    "CapacityPlane",
    "Counter",
    "EventJournal",
    "Gauge",
    "HLC",
    "Heartbeater",
    "Histogram",
    "JournalHub",
    "MetricsRegistry",
    "Objective",
    "OpenMetricsServer",
    "SLOEngine",
    "ProfileHub",
    "SamplingProfiler",
    "Span",
    "SpanHandle",
    "TelemetryHub",
    "TimeSeriesRing",
    "Tracer",
    "Window",
    "acquire_profiler",
    "active_journal",
    "all_tracers",
    "build_diagnosis",
    "burn_rate",
    "collect_spans",
    "collect_spans_with_epochs",
    "emit",
    "events_to_chrome",
    "exceedance",
    "export_chrome_trace",
    "extract_events",
    "extract_snapshot",
    "get_journal",
    "get_profiler",
    "get_registry",
    "get_tracer",
    "judge",
    "metric_key",
    "mint_trace_id",
    "multi_window_burn",
    "now",
    "parse_metric_key",
    "release_profiler",
    "render_diagnosis",
    "render_flamegraph_html",
    "render_openmetrics",
    "render_timeline",
    "snapshot_delta",
    "strip_label",
    "to_chrome_trace",
    "write_openmetrics",
]
