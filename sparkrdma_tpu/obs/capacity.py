"""USE-method capacity accounting over the stack's governed resources.

Brendan Gregg's USE method asks three questions of every resource:
**U**tilization (how full), **S**aturation (how much work is waiting),
**E**rrors. The shuffle stack already governs nine resources with hard
caps and queues — this module folds the instruments they already
publish into one per-resource table, a ``capacity.*`` metric family,
and a hub-side :meth:`CapacityPlane.capacity_report` that names the
**binding resource** (highest utilization) and its headroom fraction.
That report is the declared input contract for the ROADMAP-2
autoscaler: scale when the binding resource's headroom shrinks, and
scale the *right* axis because the report names which resource binds.

Per-resource definitions (docs/OBSERVABILITY.md "Event journal &
capacity plane"):

==================  =============================  ====================
resource            utilization                    saturation / errors
==================  =============================  ====================
mempool             max tenant usage/quota         quota blocks / overruns
hbm                 in-use / hbm.maxBytes          quota blocks / overruns
pagecache           max tenant usage/quota         quota blocks / overruns
admission           inflight / maxConcurrentJobs   queue depth / timeouts
fairshare           (backlog-only, no capacity)    queued tasks / —
transport_send      (permit pool, no gauge)        send overflows / latched
iouring_sq          SQE depth / sendQueueDepth     depth HWM / fallbacks
collective_pipe     inflight waves / pipelineDepth wave HWM / degrades
merge_buffer        (budget-drop governed)         — / budget drops
==================  =============================  ====================

For the quota-brokered byte ledgers the point-in-time usage ratio
understates backpressure (usage is released between charges), so two
corrections pin utilization at 1.0: a thread blocked at the quota at
evaluation time (``QuotaBroker.waiting``), or the resource's block
counter having grown since the previous evaluation
(``blocked_in_interval`` in the row detail).

A resource with no meaningful utilization gauge reports ``None`` and
can never be named binding — it still surfaces saturation/errors so a
drop-governed resource (merge buffer) is visible when it sheds load.
Utilization inputs are point-in-time gauges; saturation/errors are
cumulative counters, which is what an argmax over one report wants and
what a delta between two reports turns into rates.

Stdlib-only, jax-free; tenancy/quota is imported lazily (it imports
``obs`` for its instruments, so a module-level import here would cycle
through the package init).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from sparkrdma_tpu.obs.metrics import parse_metric_key

__all__ = ["CapacityPlane", "RESOURCES"]

RESOURCES = (
    "mempool",
    "hbm",
    "pagecache",
    "admission",
    "fairshare",
    "transport_send",
    "iouring_sq",
    "collective_pipe",
    "merge_buffer",
)


def _counter_sum(snap, name: str, **labels) -> int:
    total = 0
    for key, v in snap.get("counters", {}).items():
        n, kv = parse_metric_key(key)
        if n != name:
            continue
        if any(kv.get(lk) != lv for lk, lv in labels.items()):
            continue
        total += v
    return total


def _gauge_agg(snap, name: str, field: str = "value",
               agg=sum) -> Optional[float]:
    vals = []
    for key, v in snap.get("gauges", {}).items():
        n, _ = parse_metric_key(key)
        if n == name:
            vals.append(v.get(field, 0) or 0)
    return agg(vals) if vals else None


def _hist_max(snap, name: str) -> Optional[float]:
    best = None
    for key, h in snap.get("histograms", {}).items():
        n, _ = parse_metric_key(key)
        if n != name:
            continue
        m = h.get("max")
        if m is not None and (best is None or m > best):
            best = m
    return best


def _broker_utilization(resource: str) -> Optional[float]:
    """Max tenant usage/quota for a quota-brokered resource; None when
    no broker is installed or no tenant has a finite quota. A thread
    blocked at the quota RIGHT NOW pins utilization at 1.0 — the
    held-bytes ledger reads low between charges, but active blocking is
    the definition of a full resource."""
    from sparkrdma_tpu.tenancy import quota as _quota

    b = _quota.broker(resource)
    if b is None:
        return None
    best = None
    for tenant, row in b.snapshot().items():
        q = row.get("quota", 0)
        if q <= 0:
            continue
        u = row.get("usage", 0) / q
        if best is None or u > best:
            best = u
    if b.waiting() > 0:
        best = 1.0 if best is None else max(best, 1.0)
    return best


class CapacityPlane:
    """Hub-side USE evaluation on the telemetry ingest cadence.

    Reads the process registry snapshot (which the hub's ring-fold has
    already merged across executors in-process; multi-process gauges
    arrive via their own role labels) + conf capacities + quota broker
    ledgers. ``maybe_evaluate`` is called from telemetry ingest beside
    ``slo.maybe_evaluate`` and is rate-limited by
    ``tpu.shuffle.obs.capacity.evalIntervalMs``."""

    def __init__(
        self,
        conf,
        registry=None,
        *,
        role: str = "driver",
        clock: Callable[[], float] = time.time,
    ):
        self.conf = conf
        self.role = role
        self.enabled = bool(conf.capacity_enabled)
        self._interval_ms = int(conf.capacity_eval_interval_ms)
        self._clock = clock
        if registry is None:
            from sparkrdma_tpu.obs.metrics import get_registry

            registry = get_registry()
        self.registry = registry
        self._lock = threading.Lock()
        self._last_eval_ms = 0
        self._last_rows: List[dict] = []
        # per-resource saturation counters at the previous evaluation:
        # a brokered quota whose block counter grew within the interval
        # was driven to its cap during it, however the point-in-time
        # ledger reads at evaluation instant
        self._prev_sat: Dict[str, int] = {}
        self._c_evals = registry.counter("capacity.evaluations", role=role)
        self._g_util = lambda r: registry.gauge(
            "capacity.utilization", resource=r
        )
        self._g_sat = lambda r: registry.gauge(
            "capacity.saturation", resource=r
        )
        self._g_err = lambda r: registry.gauge("capacity.errors", resource=r)
        self._g_headroom = registry.gauge(
            "capacity.binding_headroom", role=role
        )

    # -- probes --------------------------------------------------------
    def _rows(self, snap) -> List[dict]:
        conf = self.conf
        rows: List[dict] = []

        def row(resource, util, sat, err, **detail):
            rows.append({
                "resource": resource,
                "utilization": (None if util is None
                                else max(0.0, min(float(util), 1.0))),
                "saturation": int(sat or 0),
                "errors": int(err or 0),
                "detail": detail,
            })

        # quota-brokered byte ledgers
        row(
            "mempool",
            _broker_utilization("mempool"),
            _counter_sum(snap, "tenant.quota_blocks", resource="mempool"),
            _counter_sum(snap, "tenant.quota_overruns", resource="mempool"),
            in_use_bytes=_gauge_agg(snap, "mempool.in_use_bytes") or 0,
        )
        hbm_cap = conf.hbm_max_bytes
        hbm_in_use = _gauge_agg(snap, "hbm.in_use_bytes") or 0
        hbm_util = _broker_utilization("hbm")
        if hbm_cap > 0:
            arena = hbm_in_use / hbm_cap
            hbm_util = arena if hbm_util is None else max(hbm_util, arena)
        row(
            "hbm",
            hbm_util,
            _counter_sum(snap, "tenant.quota_blocks", resource="hbm"),
            _counter_sum(snap, "tenant.quota_overruns", resource="hbm"),
            in_use_bytes=hbm_in_use,
            capacity_bytes=hbm_cap,
        )
        row(
            "pagecache",
            _broker_utilization("pagecache"),
            _counter_sum(snap, "tenant.quota_blocks", resource="pagecache"),
            _counter_sum(snap, "tenant.quota_overruns",
                         resource="pagecache"),
        )

        # admission slots + fair-share backlog
        slots = conf.tenancy_max_concurrent_jobs
        inflight = _gauge_agg(snap, "admission.inflight") or 0
        row(
            "admission",
            (inflight / slots) if slots > 0 else None,
            _gauge_agg(snap, "admission.queue_depth") or 0,
            _counter_sum(snap, "admission.timeouts"),
            inflight=inflight,
            slots=slots,
        )
        row(
            "fairshare",
            None,
            _gauge_agg(snap, "tenant.queued") or 0,
            0,
        )

        # host transport: send permit pool + native submission queue
        row(
            "transport_send",
            None,
            _counter_sum(snap, "transport.send_overflow"),
            _counter_sum(snap, "transport.errors_latched"),
        )
        sq_cap = conf.send_queue_depth
        sq_depth = _gauge_agg(snap, "transport.sq.sqe_depth")
        row(
            "iouring_sq",
            (None if sq_depth is None or sq_cap <= 0
             else sq_depth / sq_cap),
            _gauge_agg(snap, "transport.sq.sqe_depth", field="hwm") or 0,
            _counter_sum(snap, "transport.sq.backend_fallbacks"),
            depth=sq_depth or 0,
            capacity=sq_cap,
        )

        # device plane: pipelined DMA waves + merge-endpoint budget
        pipe_cap = conf.collective_pipeline_depth
        wave_peak = _hist_max(snap, "collective.wave_inflight")
        row(
            "collective_pipe",
            (None if wave_peak is None or pipe_cap <= 0
             else wave_peak / pipe_cap),
            int(wave_peak or 0),
            _counter_sum(snap, "collective.degrades"),
            pipeline_depth=pipe_cap,
        )
        row(
            "merge_buffer",
            None,
            0,
            _counter_sum(snap, "push.budget_drops"),
            budget_bytes=conf.push_max_buffer_bytes,
        )
        return rows

    # -- evaluation ----------------------------------------------------
    def evaluate(self, now_ms: Optional[int] = None) -> List[dict]:
        """Recompute the USE table, publish ``capacity.*`` gauges, and
        return the rows (also cached for :meth:`capacity_report`)."""
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        snap = self.registry.snapshot()
        rows = self._rows(snap)
        with self._lock:
            prev_sat = dict(self._prev_sat)
        for r in rows:
            if (r["resource"] in ("mempool", "hbm", "pagecache")
                    and r["utilization"] is not None):
                last = prev_sat.get(r["resource"])
                if last is not None and r["saturation"] > last:
                    r["utilization"] = 1.0
                    r["detail"]["blocked_in_interval"] = 1
        for r in rows:
            if r["utilization"] is not None:
                self._g_util(r["resource"]).set(round(r["utilization"], 4))
            self._g_sat(r["resource"]).set(r["saturation"])
            self._g_err(r["resource"]).set(r["errors"])
        binding = self._binding(rows)
        if binding is not None:
            self._g_headroom.set(
                round(1.0 - binding["utilization"], 4)
            )
        with self._lock:
            self._last_eval_ms = now_ms
            self._last_rows = rows
            self._prev_sat = {
                r["resource"]: r["saturation"] for r in rows
            }
        self._c_evals.inc()
        return rows

    def maybe_evaluate(self, now_ms: Optional[int] = None) -> bool:
        if not self.enabled:
            return False
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        with self._lock:
            due = now_ms - self._last_eval_ms >= self._interval_ms
        if due:
            self.evaluate(now_ms)
        return due

    @staticmethod
    def _binding(rows: List[dict]) -> Optional[dict]:
        known = [r for r in rows if r["utilization"] is not None]
        if not known:
            return None
        return max(
            known,
            key=lambda r: (r["utilization"], r["saturation"], r["errors"]),
        )

    def capacity_report(self, *, refresh: bool = True) -> dict:
        """The autoscaler-facing report: every resource's USE row plus
        the binding resource (argmax utilization, ties broken by
        saturation then errors) and its headroom fraction."""
        if refresh or not self._last_rows:
            rows = self.evaluate()
        else:
            with self._lock:
                rows = self._last_rows
        binding = self._binding(rows)
        report = {
            "enabled": self.enabled,
            "evaluations": self._c_evals.value,
            "resources": {
                r["resource"]: {
                    "utilization": r["utilization"],
                    "saturation": r["saturation"],
                    "errors": r["errors"],
                    "detail": r["detail"],
                }
                for r in rows
            },
            "binding": None,
        }
        if binding is not None:
            report["binding"] = {
                "resource": binding["resource"],
                "utilization": binding["utilization"],
                "headroom": round(1.0 - binding["utilization"], 4),
                "saturation": binding["saturation"],
                "errors": binding["errors"],
            }
        return report

    def summary(self) -> dict:
        """Compact form for hub ``summary()`` / soak ledgers."""
        rep = self.capacity_report(refresh=True)
        out = {
            "enabled": [str(rep["enabled"])],
            "evaluations": rep["evaluations"],
        }
        if rep["binding"]:
            out["binding_resource"] = [rep["binding"]["resource"]]
            out["binding_headroom"] = rep["binding"]["headroom"]
        return out
