"""Per-job time attribution: critical-path segments -> category verdict.

Folds a :class:`~sparkrdma_tpu.obs.critpath.CriticalPath` into a
:class:`TimeBreakdown` — the "where did this job's wall time actually
go" answer, in a fixed category vocabulary (docs/OBSERVABILITY.md
"Critical path & attribution"):

- ``device-compute`` — device sort / merge / exchange kernels,
- ``dma-wave``       — collective DMA waves and the device fetch plane,
- ``host-read``      — one-sided READ service, fetch groups, native
                       submit→complete intervals,
- ``decode``         — frame parse / checksum / deserialize,
- ``rpc``            — control-plane publish/resolve/fetch-request and
                       push/seal messaging,
- ``queue-wait``     — fair-share DRR submit→dispatch parking,
- ``other``          — traced spans outside the vocabulary,
- ``idle-untraced``  — critical-path gaps (nothing traced was running).

Categories are assigned by longest-matching span-name prefix, so new
span families degrade to ``other`` rather than silently vanishing.

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.obs.critpath import CriticalPath

DEVICE_COMPUTE = "device-compute"
DMA_WAVE = "dma-wave"
HOST_READ = "host-read"
DECODE = "decode"
RPC = "rpc"
QUEUE_WAIT = "queue-wait"
OTHER = "other"
IDLE = "idle-untraced"

CATEGORIES: Tuple[str, ...] = (
    DEVICE_COMPUTE, DMA_WAVE, HOST_READ, DECODE, RPC, QUEUE_WAIT, OTHER, IDLE,
)

# span-name prefix -> category; longest prefix wins (so
# ``shuffle.collective.wave`` beats ``shuffle.collective``).
PREFIX_CATEGORIES: Dict[str, str] = {
    "engine.task": DEVICE_COMPUTE,  # task compute (sort/combine/user fns)
    "writer.pipeline.sort": DEVICE_COMPUTE,
    "reader.pipeline.merge": DEVICE_COMPUTE,
    "reader.pipeline.stage": DEVICE_COMPUTE,
    "writer.pipeline.stage": DEVICE_COMPUTE,
    "exchange.": DEVICE_COMPUTE,
    "shuffle.collective.wave": DMA_WAVE,
    "shuffle.collective": DMA_WAVE,
    "device_fetch.": DMA_WAVE,
    "shuffle.fetch": HOST_READ,  # fetch group (NOT fetch_request: see RPC)
    "transport.native_read": HOST_READ,
    "reader.pipeline.fetch": HOST_READ,
    "shuffle.read": HOST_READ,
    "reader.pipeline.decode": DECODE,
    "shuffle.fetch_request": RPC,
    "shuffle.publish": RPC,
    "shuffle.resolve": RPC,
    "shuffle.register": RPC,
    "writer.pipeline.publish": RPC,
    "shuffle.push": RPC,
    "shuffle.merge_seal": RPC,
    "tenant.queue_wait": QUEUE_WAIT,
}
_PREFIXES_BY_LEN = sorted(PREFIX_CATEGORIES, key=len, reverse=True)


def classify(name: str) -> str:
    """Category for one span name (longest matching prefix, else other)."""
    for prefix in _PREFIXES_BY_LEN:
        if name.startswith(prefix):
            return PREFIX_CATEGORIES[prefix]
    return OTHER


class TimeBreakdown:
    """One job's attribution verdict: wall, per-category ms, coverage.

    ``gap_frames`` aggregates the sampling profiler's dominant frames
    across every gap segment (``obs/profiler.py::annotate_gaps``) —
    empty when no profiler was live for the job."""

    __slots__ = ("wall_ms", "categories", "coverage", "critical_path",
                 "gap_frames")

    def __init__(self, wall_ms: float, categories: Dict[str, float],
                 coverage: float, critical_path: List[dict],
                 gap_frames: Optional[Dict[str, int]] = None):
        self.wall_ms = wall_ms
        self.categories = categories
        self.coverage = coverage
        self.critical_path = critical_path
        self.gap_frames = gap_frames or {}

    def to_dict(self) -> dict:
        out = {
            "wall_ms": round(self.wall_ms, 3),
            "coverage": round(self.coverage, 4),
            "categories_ms": {
                k: round(v, 3) for k, v in self.categories.items()
            },
            "critical_path": self.critical_path,
        }
        if self.gap_frames:
            out["gap_frames"] = dict(sorted(
                self.gap_frames.items(), key=lambda kv: -kv[1]))
        return out

    def render(self) -> str:
        """Fixed-width table for CLIs and logs."""
        lines = [f"wall {self.wall_ms:10.3f} ms   "
                 f"coverage {self.coverage * 100:5.1f}%"]
        wall = self.wall_ms or 1.0
        for cat in CATEGORIES:
            ms = self.categories.get(cat, 0.0)
            if ms <= 0.0:
                continue
            lines.append(f"  {cat:<16} {ms:10.3f} ms  {ms / wall * 100:5.1f}%")
        if self.gap_frames:
            top = sorted(self.gap_frames.items(), key=lambda kv: -kv[1])[:3]
            lines.append("  gap frames: " + ", ".join(
                f"{frame} ({n})" for frame, n in top))
        return "\n".join(lines)


# ----------------------------------------------------------------------
# process-local feedback seam: the last breakdown any producer built.
# ``critpath.job_breakdown`` publishes here so consumers that close a
# loop on attribution evidence — today the wave self-tuner
# (shuffle/autotune.py) — read the verdict without holding a reference
# to whichever engine/context produced it. Advisory by design: a stale
# or missing breakdown only makes the consumer more conservative.
# ----------------------------------------------------------------------
_last_breakdown: Optional[TimeBreakdown] = None

# transfer-plane frame markers in profiler gap aggregates: any of
# these dominating a gap segment says the untraced wall was the data
# mover, not user compute
TRANSFER_GAP_FRAMES: Tuple[str, ...] = (
    "device_put", "block_until_ready", "remote_copy", "stage_view",
    "put_array",
)


def publish_breakdown(bd: TimeBreakdown) -> None:
    """Record ``bd`` as the process's latest attribution verdict."""
    global _last_breakdown
    _last_breakdown = bd


def last_breakdown() -> Optional[TimeBreakdown]:
    """The most recent published verdict (None before the first job)."""
    return _last_breakdown


def dma_wave_signal(bd: TimeBreakdown) -> Tuple[float, bool]:
    """How loudly ``bd`` implicates the DMA-wave plane: the fraction
    of wall attributed to ``dma-wave``, and whether the profiler's gap
    frames point at the transfer path (``device_put`` and friends
    dominating idle-untraced time). The wave self-tuner acts only when
    one of the two says re-cutting waves can move the job."""
    wall = bd.wall_ms or 1.0
    fraction = bd.categories.get(DMA_WAVE, 0.0) / wall
    transfer = any(
        any(marker in frame for marker in TRANSFER_GAP_FRAMES)
        for frame in bd.gap_frames
    )
    return fraction, transfer


def attribute(path: CriticalPath, top_segments: int = 12) -> TimeBreakdown:
    """Fold a critical path into the category verdict."""
    cats: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
    gap_frames: Dict[str, int] = {}
    for seg in path.segments:
        cat = IDLE if seg.kind == "gap" else classify(seg.name)
        cats[cat] += seg.dur_s * 1e3
        for frame, n in (getattr(seg, "frames", None) or ()):
            gap_frames[frame] = gap_frames.get(frame, 0) + int(n)
    # traced-category coverage: everything except the idle bucket,
    # normalized to wall — the ≥90% acceptance gate reads this
    wall_ms = path.wall_s * 1e3
    traced_ms = sum(v for k, v in cats.items() if k != IDLE)
    coverage = (traced_ms / wall_ms) if wall_ms > 1e-3 else 1.0
    return TimeBreakdown(
        wall_ms,
        {k: v for k, v in cats.items() if v > 0.0},
        min(1.0, coverage),
        [s.to_dict() for s in path.top_segments(top_segments)],
        gap_frames,
    )
