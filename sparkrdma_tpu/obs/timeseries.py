"""Bounded per-executor time-series ring buffers for the telemetry hub.

One :class:`TimeSeriesRing` per executor on the driver: each heartbeat
payload (a labeled ``MetricsRegistry.delta()`` plus in-flight gauge
samples) folds into a wall-bucketed :class:`Window`. Buckets are
``wall_ms // interval_ms``; two payloads landing in the same bucket
merge (counter/histogram deltas sum, gauges keep the latest sample), so
the ring is a fixed-rate timeline regardless of heartbeat jitter. The
ring is capped (``obs.telemetry.ringSize``) — the hub's memory is
O(executors × ringSize × instruments) no matter how long the job runs.

Everything here is stdlib-only and jax-free (same rule as
``obs/metrics.py``).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Mapping, Optional


class Window:
    """One wall bucket of one executor's telemetry.

    ``counters``/``histograms`` hold *deltas* over the bucket;
    ``gauges`` hold the latest point-in-time sample. ``gap`` marks that
    at least one heartbeat was lost or late immediately before this
    window (sequence jump or wall-clock staleness) — the timeline shows
    the hole instead of silently smearing it."""

    __slots__ = ("bucket", "wall_ms", "seq", "counters", "gauges",
                 "histograms", "gap")

    def __init__(self, bucket: int, wall_ms: int, seq: int,
                 counters: Dict[str, int],
                 gauges: Dict[str, Dict[str, object]],
                 histograms: Dict[str, Dict[str, float]],
                 gap: bool = False):
        self.bucket = bucket
        self.wall_ms = wall_ms
        self.seq = seq
        self.counters = counters
        self.gauges = gauges
        self.histograms = histograms
        self.gap = gap

    def merge(self, other: "Window") -> None:
        """Fold a same-bucket window in: deltas sum, gauges refresh."""
        for k, v in other.counters.items():
            self.counters[k] = self.counters.get(k, 0) + v
        for k, h in other.histograms.items():
            mine = self.histograms.get(k)
            if mine is None:
                self.histograms[k] = dict(h)
            else:
                mine["count"] = mine.get("count", 0) + h.get("count", 0)
                mine["sum"] = mine.get("sum", 0.0) + h.get("sum", 0.0)
                theirs = h.get("buckets")
                if theirs:
                    mb = mine.setdefault("buckets", {})
                    for b, c in theirs.items():
                        mb[b] = mb.get(b, 0) + c
        self.gauges.update(other.gauges)
        self.wall_ms = max(self.wall_ms, other.wall_ms)
        self.seq = max(self.seq, other.seq)
        self.gap = self.gap or other.gap

    def to_dict(self) -> Dict[str, object]:
        return {
            "bucket": self.bucket,
            "wall_ms": self.wall_ms,
            "seq": self.seq,
            "gap": self.gap,
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {k: dict(v) for k, v in self.histograms.items()},
        }


class TimeSeriesRing:
    """Bounded, wall-bucketed window ring for one executor. Thread-safe."""

    def __init__(self, size: int, interval_ms: int):
        self.size = max(1, int(size))
        self.interval_ms = max(1, int(interval_ms))
        self._windows: "deque[Window]" = deque(maxlen=self.size)
        self._lock = threading.Lock()
        self.last_wall_ms: int = 0
        self.last_seq: int = 0

    def append(
        self,
        wall_ms: int,
        seq: int,
        counters: Optional[Mapping[str, int]] = None,
        gauges: Optional[Mapping[str, Dict[str, object]]] = None,
        histograms: Optional[Mapping[str, Dict[str, float]]] = None,
        gap: bool = False,
    ) -> Window:
        """Fold one heartbeat payload into its wall bucket."""
        bucket = int(wall_ms) // self.interval_ms
        win = Window(bucket, int(wall_ms), int(seq),
                     dict(counters or {}), dict(gauges or {}),
                     {k: dict(v) for k, v in (histograms or {}).items()},
                     gap=gap)
        with self._lock:
            if self._windows and self._windows[-1].bucket == bucket:
                self._windows[-1].merge(win)
                win = self._windows[-1]
            else:
                self._windows.append(win)
            self.last_wall_ms = max(self.last_wall_ms, int(wall_ms))
            self.last_seq = max(self.last_seq, int(seq))
        return win

    def windows(self, last: Optional[int] = None) -> List[Window]:
        with self._lock:
            wins = list(self._windows)
        if last is not None:
            wins = wins[-last:]
        return wins

    def __len__(self) -> int:
        with self._lock:
            return len(self._windows)

    def rollup(self, last: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        """Sum of counter/histogram deltas (and latest gauges) over the
        retained (or last N) windows — the hub's cross-window view."""
        counters: Dict[str, float] = {}
        histograms: Dict[str, Dict[str, float]] = {}
        gauges: Dict[str, Dict[str, object]] = {}
        for w in self.windows(last):
            for k, v in w.counters.items():
                counters[k] = counters.get(k, 0) + v
            for k, h in w.histograms.items():
                agg = histograms.setdefault(k, {"count": 0, "sum": 0.0})
                agg["count"] += h.get("count", 0)
                agg["sum"] += h.get("sum", 0.0)
                hb = h.get("buckets")
                if hb:
                    ab = agg.setdefault("buckets", {})
                    for b, c in hb.items():
                        ab[b] = ab.get(b, 0) + c
            gauges.update(w.gauges)
        return {"counters": counters, "histograms": histograms,
                "gauges": gauges}

    def to_list(self, last: Optional[int] = None) -> List[Dict[str, object]]:
        return [w.to_dict() for w in self.windows(last)]
