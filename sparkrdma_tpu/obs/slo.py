"""Declarative SLO engine: burn-rate objectives over telemetry rings.

The soak harness (benchmarks/soak.py) accumulated ad-hoc serving
verdicts — p99 bars, fairness bands, HWM flatness — while production
runs had dashboards but no judge: nothing watched the
:class:`~sparkrdma_tpu.obs.telemetry.TelemetryHub` rings and said
"this is now an incident". This module is that judgment layer:

- :class:`Objective` declares one service-level objective over
  existing registry/telemetry series — a fetch **error ratio**
  (``transport.read_errors`` / ``transport.reads``), a **latency**
  target (p99 task or admission-wait ms framed as a
  threshold-exceedance ratio over histogram bucket deltas), a
  **throughput floor** (MB/s per ring window), or executor
  **liveness** (the hub's missed-heartbeat accounting).
- :class:`SLOEngine` evaluates every objective against the hub's
  wall-bucketed windows with **multi-window burn rates** (the
  Google-SRE alerting shape): one objective produces both the
  fast-burn *page* (short horizon, high burn multiple) and the
  slow-burn *warn* (long horizon, low multiple), so a sudden outage
  and a slow leak alarm from the same declaration.
- every page/warn **transition** records a :class:`Breach`; the hub
  answers each with an automated root-cause
  :mod:`~sparkrdma_tpu.obs.diagnose` pass, and both ride
  ``metrics_snapshot()["slo"]``, flight records, soak/bench ledgers,
  and the ``python -m sparkrdma_tpu.obs --diagnose`` renderer.

Burn-rate semantics (unit-tested against hand-computed windows in
tests/test_slo.py):

- each ring window contributes ``(bad, total)`` event counts for the
  objective; windows from all executors folding into the same wall
  bucket sum (ratios are invariant to the in-process topology's
  duplication of process-global instruments across executor views);
- ``burn(span) = (Σ bad / Σ total) / budget`` over the last ``span``
  buckets — 0 when no events landed (an idle service burns nothing);
- **page** when ``burn(fast_windows)`` AND ``burn(fast_windows // 3)``
  both reach ``fast_burn``; **warn** analogously over ``slow_windows``
  with ``slow_burn``. The short confirmation window is what makes
  recovery drop the alert quickly instead of dragging the long
  window's average along;
- a latency objective "pX ≤ T ms" is the exceedance ratio "at most
  (100 - X)% of events above T", with T snapped UP to the nearest
  histogram bucket bound so a whole bucket is never split (optimistic:
  no false pages from bucket granularity);
- counter resets across heartbeat gaps are already absorbed upstream
  (:func:`~sparkrdma_tpu.obs.metrics.snapshot_delta` restarts the
  delta instead of going negative), so burn math only ever sees
  non-negative event counts.

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from sparkrdma_tpu.obs.journal import emit as journal_emit
from sparkrdma_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
    parse_metric_key,
)

logger = logging.getLogger(__name__)

KINDS = ("ratio", "latency", "throughput", "liveness")
SEVERITIES = ("page", "warn")

# Defaults for conf-less construction (bench.py's local hub, tests).
DEFAULT_ERROR_RATIO = 0.02
DEFAULT_FAST_WINDOWS = 8
DEFAULT_SLOW_WINDOWS = 32
DEFAULT_FAST_BURN = 8.0
DEFAULT_SLOW_BURN = 2.0
DEFAULT_EVAL_INTERVAL_MS = 2000


# ---------------------------------------------------------------------------
# pure burn-rate math (hand-computable; tests/test_slo.py)
# ---------------------------------------------------------------------------
def burn_rate(points: Sequence[Tuple[float, float]], budget: float) -> float:
    """``(Σ bad / Σ total) / budget`` over (bad, total) pairs; 0 when
    no events landed or the budget is degenerate."""
    bad = sum(p[0] for p in points)
    total = sum(p[1] for p in points)
    if total <= 0 or budget <= 0:
        return 0.0
    return (bad / total) / budget


def multi_window_burn(
    points: Sequence[Tuple[float, float]],
    budget: float,
    long_windows: int,
    burn_threshold: float,
) -> Tuple[float, float, bool]:
    """(long burn, short burn, fired) for one alerting horizon.

    The short window is ``max(1, long_windows // 3)`` — both must clear
    the threshold, so a stale high average cannot keep paging after the
    service recovers."""
    long_n = max(1, int(long_windows))
    short_n = max(1, long_n // 3)
    b_long = burn_rate(points[-long_n:], budget)
    b_short = burn_rate(points[-short_n:], budget)
    return b_long, b_short, (
        b_long >= burn_threshold and b_short >= burn_threshold
    )


def exceedance(buckets: Mapping[str, object],
               threshold_ms: float) -> Tuple[int, int]:
    """(bad, total) event counts from one histogram bucket-delta dict.

    ``bad`` counts only buckets whose whole range lies above the
    threshold (snapped up to the nearest bucket bound), plus the
    overflow bucket — bucket granularity can hide a real exceedance
    but never invent one."""
    bounds = sorted(
        float(k[3:]) for k in buckets if k.startswith("le_")
    )
    eff = next((b for b in bounds if b >= threshold_ms), None)
    bad = 0
    total = 0
    for k, c in buckets.items():
        n = int(c)
        total += n
        if k == "overflow":
            bad += n
        elif eff is not None and float(k[3:]) > eff:
            bad += n
    return bad, total


# ---------------------------------------------------------------------------
# objectives
# ---------------------------------------------------------------------------
@dataclass
class Objective:
    """One declarative SLO over existing metric series.

    ``bad``/``total`` (ratio) and ``series`` (latency/throughput) are
    metric-NAME prefixes; ``labels`` filters matched keys (a missing
    ``tenant`` label on a key means the default tenant). ``tenant`` is
    folded into ``labels`` for convenience and kept for reporting."""

    name: str
    kind: str
    description: str = ""
    bad: Tuple[str, ...] = ()
    total: Tuple[str, ...] = ()
    series: Tuple[str, ...] = ()
    labels: Dict[str, str] = field(default_factory=dict)
    tenant: str = ""
    threshold_ms: float = 0.0
    percentile: float = 99.0
    floor_mbps: float = 0.0
    budget: float = DEFAULT_ERROR_RATIO
    fast_windows: int = DEFAULT_FAST_WINDOWS
    slow_windows: int = DEFAULT_SLOW_WINDOWS
    fast_burn: float = DEFAULT_FAST_BURN
    slow_burn: float = DEFAULT_SLOW_BURN

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown objective kind {self.kind!r}; expected one of {KINDS}"
            )
        if self.tenant:
            self.labels = dict(self.labels, tenant=self.tenant)
        if self.kind == "latency":
            # "pX <= T" == "at most (100 - X)% of events above T"
            self.budget = max(1e-6, (100.0 - self.percentile) / 100.0)

    def matches(self, key: str, prefixes: Sequence[str]) -> bool:
        if not prefixes:
            return False
        name, key_labels = parse_metric_key(key)
        if not name.startswith(tuple(prefixes)):
            return False
        for k, want in self.labels.items():
            have = key_labels.get(k)
            if have is None and k == "tenant":
                from sparkrdma_tpu.tenancy import DEFAULT_TENANT

                have = DEFAULT_TENANT
            if have != want:
                return False
        return True

    def window_events(self, window, interval_ms: int) -> Tuple[float, float]:
        """(bad, total) event counts this objective sees in one ring
        window. Liveness is not window-driven and always yields (0, 0)."""
        if self.kind == "ratio":
            bad = float(sum(
                v for k, v in window.counters.items()
                if self.matches(k, self.bad)
            ))
            total = float(sum(
                v for k, v in window.counters.items()
                if self.matches(k, self.total)
            ))
            # a total-series that excludes failures must never yield a
            # ratio above 1 (burn math would overshoot its own scale)
            return bad, max(total, bad)
        if self.kind == "latency":
            bad = 0
            total = 0
            for k, h in window.histograms.items():
                if not self.matches(k, self.series):
                    continue
                buckets = h.get("buckets")
                if not buckets:
                    continue  # pre-bucket payload: not evaluable
                b, t = exceedance(buckets, self.threshold_ms)
                bad += b
                total += t
            return float(bad), float(total)
        if self.kind == "throughput":
            nbytes = sum(
                v for k, v in window.counters.items()
                if self.matches(k, self.series)
            )
            if nbytes <= 0:
                return 0.0, 0.0  # idle window: not a violation
            mbps = nbytes / (max(1, interval_ms) / 1000.0) / 1e6
            return (1.0 if mbps < self.floor_mbps else 0.0), 1.0
        return 0.0, 0.0

    def judge(self, observed, target=None, comparator: str = "le",
              note: str = "") -> dict:
        """End-state verdict for offline harnesses (benchmarks/soak.py):
        compare one observed scalar against this objective's target with
        the SAME identity that the ring-driven evaluation enforces
        online. ``target`` defaults to the objective's own bar."""
        if target is None:
            target = {
                "ratio": self.budget,
                "latency": self.threshold_ms,
                "throughput": self.floor_mbps,
                "liveness": 0,
            }[self.kind]
        return judge(self.name, observed, target, comparator=comparator,
                     note=note)


def judge(objective: str, observed, target, comparator: str = "le",
          note: str = "") -> dict:
    """One shared verdict primitive: ``observed`` vs ``target`` under
    ``comparator`` ("le" | "ge" | "eq"). ``observed`` None is a failed
    verdict with an explanatory note (a bar that could not be measured
    never passes silently)."""
    if comparator not in ("le", "ge", "eq"):
        raise ValueError(f"unknown comparator {comparator!r}")
    if observed is None:
        ok = False
        note = note or "observed value unavailable"
    elif comparator == "le":
        ok = observed <= target
    elif comparator == "ge":
        ok = observed >= target
    else:
        ok = observed == target
    out = {
        "objective": objective,
        "observed": observed,
        "target": target,
        "comparator": comparator,
        "ok": bool(ok),
    }
    if note:
        out["note"] = note
    return out


@dataclass
class Breach:
    """One page/warn transition of one objective."""

    objective: str
    kind: str
    severity: str
    wall_ms: int
    tenant: str = ""
    executor: str = ""
    burn_fast: float = 0.0
    burn_fast_short: float = 0.0
    burn_slow: float = 0.0
    burn_slow_short: float = 0.0
    windows: int = 0
    observed: Dict[str, object] = field(default_factory=dict)
    description: str = ""

    def to_dict(self) -> dict:
        out = {
            "objective": self.objective,
            "kind": self.kind,
            "severity": self.severity,
            "wall_ms": self.wall_ms,
            "burn_fast": round(self.burn_fast, 4),
            "burn_fast_short": round(self.burn_fast_short, 4),
            "burn_slow": round(self.burn_slow, 4),
            "burn_slow_short": round(self.burn_slow_short, 4),
            "windows": self.windows,
            "observed": dict(self.observed),
        }
        if self.tenant:
            out["tenant"] = self.tenant
        if self.executor:
            out["executor"] = self.executor
        if self.description:
            out["description"] = self.description
        return out


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class SLOEngine:
    """Evaluates a set of objectives against a TelemetryHub's rings.

    Passive: :meth:`maybe_evaluate` rides the hub's ingest path on a
    bounded cadence (``obs.slo.evalIntervalMs``), so the evaluator's
    cost stays inside the telemetry interval budget no matter how fast
    heartbeats arrive. Every page/warn *transition* (not every breaching
    evaluation) records a :class:`Breach` and fires ``on_breach`` —
    the hub's automated-diagnosis hook."""

    def __init__(
        self,
        hub=None,
        conf=None,
        *,
        registry: Optional[MetricsRegistry] = None,
        role: str = "driver",
        clock: Callable[[], float] = time.time,
        enabled: Optional[bool] = None,
        eval_interval_ms: Optional[int] = None,
        install_defaults: bool = True,
    ):
        self.hub = hub
        self.role = role
        self._registry = registry or get_registry()
        self._clock = clock
        self.enabled = bool(
            enabled
            if enabled is not None
            else (conf.slo_enabled if conf is not None else True)
        )
        self.eval_interval_ms = int(
            eval_interval_ms
            if eval_interval_ms is not None
            else (conf.slo_eval_interval_ms if conf is not None
                  else DEFAULT_EVAL_INTERVAL_MS)
        )
        self._lock = threading.Lock()
        self.objectives: Dict[str, Objective] = {}
        # (objective, executor) -> current severity; transitions only
        self._breaching: Dict[Tuple[str, str], str] = {}
        self.breaches: "deque[Breach]" = deque(maxlen=256)
        self.diagnoses: "deque[dict]" = deque(maxlen=32)
        self.breach_total = 0
        self._last_eval_ms = 0
        self.on_breach: Optional[Callable[[Breach], None]] = None

        reg = self._registry
        self._c_evals = reg.counter("slo.evaluations", role=role)
        self._g_objectives = reg.gauge("slo.objectives", role=role)
        self._g_breaching = reg.gauge("slo.breaching", role=role)

        if install_defaults:
            self.install_defaults(conf)

    # -- objective registry --------------------------------------------
    def add(self, objective: Objective) -> Objective:
        with self._lock:
            self.objectives[objective.name] = objective
            self._g_objectives.set(len(self.objectives))
        return objective

    def objective(self, name: str) -> Optional[Objective]:
        with self._lock:
            return self.objectives.get(name)

    def install_defaults(self, conf=None) -> None:
        """The standing objective set. Error-ratio and liveness default
        ON (they cannot fire without real faults); latency and
        throughput objectives install only when their conf target is
        nonzero, so a conf-less hub never pages a healthy run."""
        fast_w = conf.slo_fast_windows if conf else DEFAULT_FAST_WINDOWS
        slow_w = conf.slo_slow_windows if conf else DEFAULT_SLOW_WINDOWS
        fast_b = conf.slo_fast_burn if conf else DEFAULT_FAST_BURN
        slow_b = conf.slo_slow_burn if conf else DEFAULT_SLOW_BURN
        common = dict(fast_windows=fast_w, slow_windows=slow_w,
                      fast_burn=fast_b, slow_burn=slow_b)
        self.add(Objective(
            "fetch-error-ratio", "ratio",
            description="one-sided READ error ratio within budget",
            bad=("transport.read_errors",),
            total=("transport.reads",),
            budget=(conf.slo_error_ratio if conf else DEFAULT_ERROR_RATIO),
            **common,
        ))
        self.add(Objective(
            "executor-liveness", "liveness",
            description="every known executor heartbeats within "
                        "the missed-heartbeat horizon",
            **common,
        ))
        task_p99 = conf.slo_task_p99_ms if conf else 0
        if task_p99 > 0:
            self.add(Objective(
                "task-p99", "latency",
                description=f"p99 task latency <= {task_p99} ms",
                series=("engine.task_ms",),
                threshold_ms=float(task_p99),
                **common,
            ))
        for tenant, bar in sorted(self._tenant_targets(conf).items()):
            self.add(Objective(
                f"task-p99-{tenant}", "latency",
                description=f"p99 task latency <= {bar} ms for {tenant}",
                series=("engine.task_ms",),
                tenant=tenant,
                threshold_ms=float(bar),
                **common,
            ))
        queue_p99 = conf.slo_queue_wait_p99_ms if conf else 0
        if queue_p99 > 0:
            self.add(Objective(
                "queue-wait-p99", "latency",
                description=f"p99 admission queue wait <= {queue_p99} ms",
                series=("admission.wait_ms",),
                threshold_ms=float(queue_p99),
                **common,
            ))
        floor = conf.slo_throughput_floor_mbps if conf else 0.0
        if floor > 0:
            self.add(Objective(
                "throughput-floor", "throughput",
                description=f"active-window write throughput >= "
                            f"{floor} MB/s",
                series=("writer.bytes_written",),
                floor_mbps=float(floor),
                **common,
            ))

    @staticmethod
    def _tenant_targets(conf) -> Dict[str, int]:
        """Per-tenant p99 bars: every declared fair-share tenant plus
        any ``obs.slo.tenant.<t>.taskP99Ms`` override names a tenant;
        only nonzero bars install an objective."""
        if conf is None:
            return {}
        from sparkrdma_tpu.tenancy import declared_tenants

        tenants = set(declared_tenants(conf))
        from sparkrdma_tpu.utils.config import PREFIX

        head, tail = PREFIX + "obs.slo.tenant.", ".taskP99Ms"
        for key in conf.to_dict():
            if key.startswith(head) and key.endswith(tail):
                seg = key[len(head):-len(tail)]
                if seg and "." not in seg:
                    tenants.add(seg)
        out = {}
        for t in tenants:
            bar = conf.slo_tenant_task_p99_ms(t)
            if bar > 0:
                out[t] = bar
        return out

    # -- evaluation ----------------------------------------------------
    def burn_points(self, objective: Objective) -> List[Tuple[int, float, float]]:
        """(bucket, bad, total) per wall bucket across all executors,
        oldest first — the exact sequence :meth:`evaluate` burns over
        (exposed so tests can hand-compute the same windows)."""
        if self.hub is None:
            return []
        interval_ms = self.hub.interval_ms
        acc: Dict[int, List[float]] = {}
        for wins in self.hub.ring_windows().values():
            for w in wins:
                bad, total = objective.window_events(w, interval_ms)
                if bad or total:
                    cell = acc.setdefault(w.bucket, [0.0, 0.0])
                    cell[0] += bad
                    cell[1] += total
        return [(b, acc[b][0], acc[b][1]) for b in sorted(acc)]

    def maybe_evaluate(self, now_ms: Optional[int] = None) -> List[Breach]:
        """Cadence-bounded evaluation (the hub's ingest hook)."""
        if not self.enabled:
            return []
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        with self._lock:
            if now_ms - self._last_eval_ms < self.eval_interval_ms:
                return []
            self._last_eval_ms = now_ms
        return self.evaluate(now_ms)

    def evaluate(self, now_ms: Optional[int] = None) -> List[Breach]:
        """Evaluate every objective now; returns the NEW breaches
        (page/warn transitions) this pass produced."""
        if not self.enabled or self.hub is None:
            return []
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        self._c_evals.inc()
        new: List[Breach] = []
        with self._lock:
            objectives = list(self.objectives.values())
        for obj in objectives:
            if obj.kind == "liveness":
                new.extend(self._evaluate_liveness(obj, now_ms))
            else:
                new.extend(self._evaluate_windows(obj, now_ms))
        self._g_breaching.set(len(self._breaching))
        for breach in new:
            self._registry.counter(
                "slo.breaches", role=self.role,
                objective=breach.objective, severity=breach.severity,
            ).inc()
            logger.warning(
                "SLO breach [%s] %s: burn fast %.2f/%.2f slow %.2f/%.2f %s",
                breach.severity, breach.objective,
                breach.burn_fast, breach.burn_fast_short,
                breach.burn_slow, breach.burn_slow_short,
                f"executor={breach.executor}" if breach.executor else "",
            )
            if self.on_breach is not None:
                try:
                    self.on_breach(breach)
                except Exception:
                    logger.exception("on_breach hook failed")
        return new

    def _evaluate_windows(self, obj: Objective, now_ms: int) -> List[Breach]:
        pts = [(bad, total) for _, bad, total in self.burn_points(obj)]
        bf, bfs, page = multi_window_burn(
            pts, obj.budget, obj.fast_windows, obj.fast_burn)
        bs, bss, warn = multi_window_burn(
            pts, obj.budget, obj.slow_windows, obj.slow_burn)
        self._registry.gauge(
            "slo.burn_rate", role=self.role, objective=obj.name,
            window="fast").set(round(bf, 4))
        self._registry.gauge(
            "slo.burn_rate", role=self.role, objective=obj.name,
            window="slow").set(round(bs, 4))
        severity = "page" if page else ("warn" if warn else None)
        return self._transition(
            obj, severity, now_ms,
            burn=(bf, bfs, bs, bss), windows=len(pts),
            observed={
                "bad": sum(p[0] for p in pts),
                "total": sum(p[1] for p in pts),
                "budget": obj.budget,
                "threshold_ms": obj.threshold_ms,
            },
        )

    def _evaluate_liveness(self, obj: Objective, now_ms: int) -> List[Breach]:
        missed = list(self.hub.missed_executors())
        known = self.hub.executors()
        out: List[Breach] = []
        for eid in missed:
            out.extend(self._transition(
                obj, "page", now_ms, executor=eid,
                observed={"missed": len(missed), "known": len(known)},
                description=f"executor {eid} stopped heartbeating",
            ))
        # recovered executors clear their per-executor breach state
        with self._lock:
            for key in [k for k in self._breaching
                        if k[0] == obj.name and k[1] not in missed]:
                del self._breaching[key]
        return out

    def _transition(
        self,
        obj: Objective,
        severity: Optional[str],
        now_ms: int,
        *,
        executor: str = "",
        burn: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0),
        windows: int = 0,
        observed: Optional[dict] = None,
        description: str = "",
    ) -> List[Breach]:
        key = (obj.name, executor)
        with self._lock:
            prev = self._breaching.get(key)
            if severity is None:
                self._breaching.pop(key, None)
                if prev is not None:
                    journal_emit(
                        "slo.recover", role=self.role, executor=executor,
                        tenant=obj.tenant or "", wall_ms=now_ms,
                        objective=obj.name, was=prev,
                    )
                return []
            # re-record only on a fresh breach or a warn->page escalation
            if prev == severity or (prev == "page" and severity == "warn"):
                self._breaching[key] = (
                    severity if prev is None else prev
                )
                return []
            self._breaching[key] = severity
        journal_emit(
            f"slo.{severity}", role=self.role, executor=executor,
            tenant=obj.tenant or "", wall_ms=now_ms,
            objective=obj.name,
        )
        breach = Breach(
            objective=obj.name,
            kind=obj.kind,
            severity=severity,
            wall_ms=now_ms,
            tenant=obj.tenant,
            executor=executor,
            burn_fast=burn[0],
            burn_fast_short=burn[1],
            burn_slow=burn[2],
            burn_slow_short=burn[3],
            windows=windows,
            observed=observed or {},
            description=description or obj.description,
        )
        with self._lock:
            self.breaches.append(breach)
            self.breach_total += 1
        return [breach]

    # -- artifacts -----------------------------------------------------
    def note_diagnosis(self, diagnosis: dict) -> None:
        with self._lock:
            self.diagnoses.append(diagnosis)

    def summary(self) -> dict:
        """Ledger/snapshot view. Scalars at dict level are numeric and
        every string lives inside a list, so the trend flattener
        (obs/trend.py) charts the counts and skips the records."""
        with self._lock:
            breaches = [b.to_dict() for b in self.breaches]
            diagnoses = list(self.diagnoses)
            objectives = [
                {
                    "name": o.name,
                    "kind": o.kind,
                    "tenant": o.tenant,
                    "budget": o.budget,
                    "threshold_ms": o.threshold_ms,
                    "fast_windows": o.fast_windows,
                    "slow_windows": o.slow_windows,
                    "fast_burn": o.fast_burn,
                    "slow_burn": o.slow_burn,
                }
                for o in self.objectives.values()
            ]
            breaching = len(self._breaching)
            total = self.breach_total
        return {
            "enabled": self.enabled,
            "eval_interval_ms": self.eval_interval_ms,
            "objectives": len(objectives),
            "breaching": breaching,
            "breach_count": total,
            "diagnosis_count": len(diagnoses),
            "evaluations": self._c_evals.value,
            "objective_records": objectives,
            "breach_records": breaches,
            "diagnosis_records": diagnoses,
        }
