"""Automated root-cause diagnosis for SLO breaches.

When :mod:`~sparkrdma_tpu.obs.slo` records a breach, someone used to
open four artifacts by hand: the critical-path TimeBreakdown (which
category dominated the slow window?), the straggler report (is one
executor behind?), the circuit/quota state (is the system already
defending itself?), and the fault plan (did chaos testing do this on
purpose?). This module is that correlation walk, mechanised: one
breach in, one ``Diagnosis`` artifact out.

A Diagnosis is a plain JSON-able dict:

- ``evidence`` — the raw inputs, verbatim: active fault-plan state
  (spec/seed/injection counts), the last TimeBreakdown pushed by the
  engine (dominant category + profiler gap frames), the hub's
  straggler report, circuit-breaker states, missed-heartbeat set,
  per-tenant quota blocks, and (when a ledger dir is supplied) the
  latest trend deltas;
- ``causes`` — candidate root causes ranked by an **explicit rubric**
  (:data:`RUBRIC` — base scores by evidence class, plus
  :data:`CORROBORATION_BONUS` when two independent evidence sources
  name the same executor). Deterministic: equal scores tie-break by
  cause name, so the same evidence always yields the same ranking;
- ``top_cause`` — the ranked winner, duplicated at top level so
  downstream consumers (flight records, soak ledgers, CI assertions)
  don't have to index into the list.

The rubric, highest first:

====================  =====  ==========================================
cause                 score  evidence source
====================  =====  ==========================================
injected-fault          4.0  testing/faults.py plan actually fired
dead-executor           3.5  hub missed-heartbeat accounting (PR 5)
dead-metastore-peer     3.25 metastore.peer_kills / lease takeovers
straggler               3.0  robust-z straggler report (PR 5)
circuit-open            2.5  resilience SourceHealthRegistry states
quota-backpressure      2.0  tenant.quota_blocks counters (PR 13)
saturated-resource      1.75 capacity plane binding resource (PR 20)
dominant-category       1.5  TimeBreakdown critical path (PR 14)
trend-regression        1.0  ledger deltas vs committed trend (PR 15)
====================  =====  ==========================================

Two PR-20 evidence sources feed the walk without new top-level cause
machinery: the merged cluster **event journal** (time-windowed around
the breach) names executors for the corroboration bonus and backfills
ranked causes when a live metric source is silent (e.g. the breaker
already closed but ``circuit.open`` is in the journal), and the
**capacity plane**'s USE report contributes the ``saturated-resource``
row when the binding resource shows saturation, errors, or
near-exhausted headroom.

An injected fault outranks everything because it is the one cause we
*know* is real; infrastructure evidence (dead executor, straggler)
outranks symptom evidence (dominant category), which outranks
historical context (trend). Rendered by ``python -m sparkrdma_tpu.obs
--diagnose <file>``.

Stdlib-only, jax-free, and best-effort throughout: a diagnosis pass
must never add a failure mode to the breach path it explains.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from sparkrdma_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
    parse_metric_key,
)

logger = logging.getLogger(__name__)

# Base score per cause class — see the module docstring for the
# reasoning behind the ordering.
RUBRIC: Dict[str, float] = {
    "injected-fault": 4.0,
    "dead-executor": 3.5,
    # a dead metadata peer degrades EVERY job's control plane (routes
    # fail over, epochs fence in-flight publishes) but costs no shuffle
    # bytes — between the dead executor and the straggler
    "dead-metastore-peer": 3.25,
    "straggler": 3.0,
    "circuit-open": 2.5,
    "quota-backpressure": 2.0,
    # the USE-method binding resource: symptom-adjacent (the resource
    # is saturated *because* of load) but more actionable than the raw
    # dominant category — it names the knob to turn
    "saturated-resource": 1.75,
    "dominant-category": 1.5,
    "trend-regression": 1.0,
}

# Added when a cause's executor is independently named by the breach
# itself or by a second evidence source.
CORROBORATION_BONUS = 0.5

# Journal event kinds that can stand in for a live evidence source when
# the transient already resolved (breaker closed, tenant unblocked, peer
# re-adopted) by the time the diagnosis runs. Maps event kind -> RUBRIC
# cause class.
JOURNAL_CAUSE_KINDS: Dict[str, str] = {
    "driver.kill": "dead-metastore-peer",
    "meta.peer_kill": "dead-metastore-peer",
    "meta.takeover": "dead-metastore-peer",
    "circuit.open": "circuit-open",
    "straggler.flag": "straggler",
    "quota.block": "quota-backpressure",
}

# How far around the breach instant journal events count as evidence:
# everything in the half-minute leading up to it (causes precede
# symptoms) plus a short tail for events that race the breach emit.
JOURNAL_WINDOW_BEFORE_MS = 30_000
JOURNAL_WINDOW_AFTER_MS = 5_000


def _fault_evidence() -> dict:
    from sparkrdma_tpu.testing.faults import active

    plan = active()
    if plan is None:
        return {"active": 0, "rules": []}
    return {
        "active": 1,
        "seed": plan.seed,
        "total_injected": plan.total_injected,
        "spec": [plan.spec],
        "rules": [
            {
                "rule": [f"{r.op}:{r.kind}"],
                "stage": [r.stage or ""],
                "peer": [r.peer or ""],
                "delay_ms": r.delay_ms,
                "injected": plan.injected_count(r.op, r.kind),
            }
            for r in plan.rules
        ],
    }


def _dominant_category(breakdown: Optional[dict]) -> Optional[dict]:
    if not breakdown:
        return None
    cats = breakdown.get("categories_ms") or {}
    busy = {k: v for k, v in cats.items()
            if k not in ("idle-untraced",) and v > 0}
    if not busy:
        return None
    name = max(sorted(busy), key=lambda k: busy[k])
    wall = breakdown.get("wall_ms") or 0
    return {
        "category": name,
        "ms": round(busy[name], 3),
        "share": round(busy[name] / wall, 4) if wall else 0.0,
    }


def _quota_evidence(registry: MetricsRegistry) -> Dict[str, int]:
    snap = registry.snapshot(prefix="tenant.quota_blocks")
    out: Dict[str, int] = {}
    for key, v in snap.get("counters", {}).items():
        if v > 0:
            _, labels = parse_metric_key(key)
            tenant = labels.get("tenant", "")
            out[tenant] = out.get(tenant, 0) + int(v)
    return out


def _metastore_evidence(registry: MetricsRegistry) -> Dict[str, int]:
    """Dead metadata peers (sparkrdma_tpu/metastore): ``kill_peer``
    counts ``metastore.peer_kills`` and every route through the dead
    shard's range pays a ``metastore.lease_takeovers`` failover —
    control-plane degradation with zero shuffle bytes lost."""
    snap = registry.snapshot(prefix="metastore.")
    out: Dict[str, int] = {"peer_kills": 0, "lease_takeovers": 0}
    for key, v in snap.get("counters", {}).items():
        name, _ = parse_metric_key(key)
        if name == "metastore.peer_kills":
            out["peer_kills"] += int(v)
        elif name == "metastore.lease_takeovers":
            out["lease_takeovers"] += int(v)
    return out


def _journal_evidence(hub, breach_wall_ms) -> List[dict]:
    """Merged journal events time-windowed around the breach.

    Falls back to the journal tail when the window is empty (clock skew
    between emitters and the breach stamp must not erase evidence)."""
    journal = getattr(hub, "journal", None)
    if journal is None:
        return []
    merged = journal.merged(last=256)
    if not merged:
        return []
    if breach_wall_ms:
        lo = breach_wall_ms - JOURNAL_WINDOW_BEFORE_MS
        hi = breach_wall_ms + JOURNAL_WINDOW_AFTER_MS
        windowed = [e for e in merged
                    if lo <= e.get("wall_ms", 0) <= hi]
        if windowed:
            return windowed
    return merged[-64:]


def _capacity_evidence(hub) -> dict:
    plane = getattr(hub, "capacity", None)
    if plane is None:
        return {}
    return plane.capacity_report(refresh=True)


def _trend_evidence(trend_dir: Optional[str]) -> dict:
    if not trend_dir:
        return {}
    try:
        from sparkrdma_tpu.obs.trend import build_trend

        trend = build_trend(trend_dir)
    except Exception:
        logger.debug("trend evidence unavailable", exc_info=True)
        return {}
    rows = sorted(
        (
            (name, t["rel_delta_latest"])
            for name, t in trend.get("series", {}).items()
            if t.get("rel_delta_latest") is not None
        ),
        key=lambda r: r[1],
    )
    return {
        "regressions": [r.get("series", "") for r in
                        trend.get("regressions", [])],
        "worst_series": [
            {"name": [n], "delta": d} for n, d in rows[:5]
        ],
    }


def build_diagnosis(
    hub,
    breach,
    *,
    registry: Optional[MetricsRegistry] = None,
    trend_dir: Optional[str] = None,
    clock: Callable[[], float] = time.time,
) -> dict:
    """Assemble and rank the root-cause artifact for one breach.

    ``breach`` is a :class:`~sparkrdma_tpu.obs.slo.Breach` or its
    ``to_dict()`` form. Every evidence probe is independently
    best-effort; a probe that fails contributes nothing rather than
    failing the diagnosis."""
    reg = registry or get_registry()
    t0 = time.perf_counter()
    breach_d = breach if isinstance(breach, dict) else breach.to_dict()
    breach_exec = breach_d.get("executor", "")
    breach_tenant = breach_d.get("tenant", "")

    def probe(fn, default):
        try:
            return fn()
        except Exception:
            logger.debug("diagnosis evidence probe failed", exc_info=True)
            return default

    faults = probe(_fault_evidence, {"active": 0, "rules": []})
    breakdown = probe(
        lambda: getattr(hub, "last_breakdown", None), None) or {}
    stragglers = probe(
        lambda: hub.last_straggler_report(), {}) or {}
    health = probe(lambda: hub.source_health(), {}) or {}
    missed = probe(lambda: list(hub.missed_executors()), [])
    quota = probe(lambda: _quota_evidence(reg), {})
    metastore = probe(lambda: _metastore_evidence(reg), {})
    journal_events = probe(
        lambda: _journal_evidence(hub, breach_d.get("wall_ms")), [])
    capacity = probe(lambda: _capacity_evidence(hub), {}) or {}
    trend = probe(lambda: _trend_evidence(trend_dir), {})
    dominant = _dominant_category(breakdown)
    gap_frames = list(breakdown.get("gap_frames", []))[:5]

    straggler_ids = list(stragglers.get("stragglers", []))
    open_circuits = sorted(
        k for k, v in health.items() if "open" in str(v).lower()
    )

    # which executors does each independent evidence source name?
    named_by: Dict[str, set] = {}

    def name_executor(eid: str, source: str) -> None:
        if eid:
            named_by.setdefault(eid, set()).add(source)

    for eid in missed:
        name_executor(eid, "missed-heartbeat")
    for eid in straggler_ids:
        name_executor(eid, "straggler-report")
    for key in open_circuits:
        # breaker keys are "<executor>" or "<tenant>:<executor>"
        name_executor(key.rpartition(":")[2], "circuit")
    for ev in journal_events:
        name_executor(ev.get("executor", ""), "journal")
    if breach_exec:
        name_executor(breach_exec, "breach")

    def corroborated(eid: str, own_source: str) -> bool:
        others = named_by.get(eid, set()) - {own_source}
        return bool(others) or (bool(eid) and eid == breach_exec)

    causes: List[dict] = []

    def add_cause(kind: str, summary: str, *, executor: str = "",
                  category: str = "", source: str = "",
                  detail: Optional[dict] = None) -> None:
        score = RUBRIC[kind]
        corr = corroborated(executor, source) if executor else False
        if corr:
            score += CORROBORATION_BONUS
        causes.append({
            "cause": kind,
            "score": round(score, 2),
            "corroborated": 1 if corr else 0,
            "executor": executor,
            "category": category,
            "summary": [summary],
            "detail": detail or {},
        })

    for rule in faults.get("rules", []):
        if rule.get("injected", 0) <= 0:
            continue
        peer = (rule.get("peer") or [""])[0]
        stage = (rule.get("stage") or [""])[0]
        category = dominant["category"] if dominant else stage
        rname = (rule.get("rule") or ["?"])[0]
        add_cause(
            "injected-fault",
            f"fault plan rule {rname} fired "
            f"{rule.get('injected', 0)}x"
            + (f" against {peer}" if peer else ""),
            executor=peer, category=category, source="fault-plan",
            detail={"injected": rule.get("injected", 0),
                    "delay_ms": rule.get("delay_ms", 0),
                    "stage": [stage]},
        )
    for eid in missed:
        add_cause(
            "dead-executor",
            f"executor {eid} stopped heartbeating",
            executor=eid, source="missed-heartbeat",
        )
    if metastore.get("peer_kills", 0) > 0:
        add_cause(
            "dead-metastore-peer",
            f"{metastore['peer_kills']} metadata peer(s) lost their "
            f"shard lease; "
            f"{metastore.get('lease_takeovers', 0)} route failover(s)",
            source="metastore",
            detail=dict(metastore),
        )
    for eid in straggler_ids:
        flags = (stragglers.get("executors", {})
                 .get(eid, {}).get("flags", []))
        add_cause(
            "straggler",
            f"executor {eid} flagged by robust-z straggler detection",
            executor=eid, source="straggler-report",
            detail={"flags": flags[:3]},
        )
    for key in open_circuits:
        add_cause(
            "circuit-open",
            f"circuit breaker open for source {key}",
            executor=key.rpartition(":")[2], source="circuit",
            detail={"state": [str(health.get(key, ""))]},
        )
    for tenant, blocks in sorted(quota.items()):
        summary = (f"tenant {tenant} hit quota backpressure "
                   f"{blocks}x")
        cause_detail = {"tenant": [tenant], "blocks": blocks}
        if breach_tenant and tenant == breach_tenant:
            cause_detail["matches_breach_tenant"] = 1
        add_cause("quota-backpressure", summary, detail=cause_detail)
    binding = capacity.get("binding") or {}
    if binding and (
        binding.get("saturation", 0) > 0
        or binding.get("errors", 0) > 0
        or (binding.get("utilization") or 0.0) >= 0.9
    ):
        util = binding.get("utilization") or 0.0
        add_cause(
            "saturated-resource",
            f"binding resource {binding.get('resource', '?')} at "
            f"{util:.0%} utilization (headroom "
            f"{binding.get('headroom', 1.0):.0%}, saturation "
            f"{binding.get('saturation', 0)}, errors "
            f"{binding.get('errors', 0)})",
            source="capacity",
            detail={
                "resource": [binding.get("resource", "")],
                "utilization": round(util, 4),
                "headroom": round(binding.get("headroom", 1.0), 4),
                "saturation": binding.get("saturation", 0),
                "errors": binding.get("errors", 0),
            },
        )
    if dominant is not None:
        add_cause(
            "dominant-category",
            f"critical path dominated by {dominant['category']} "
            f"({dominant['ms']} ms, {dominant['share']:.0%} of wall)",
            category=dominant["category"], source="breakdown",
            detail=dict(dominant, category=dominant["category"]),
        )
    for name in trend.get("regressions", []):
        add_cause(
            "trend-regression",
            f"committed-trend regression on {name}",
            detail={"series": [name]},
        )

    # Journal evidence per cause class: when a live metric source
    # already produced the cause, the windowed events attach to it as
    # corroborating detail; when the transient resolved before the
    # diagnosis ran (breaker closed, peer re-adopted), the journal
    # events BECOME the ranked cause — the journal remembers what the
    # point-in-time probes no longer see.
    journal_grouped: Dict[tuple, List[dict]] = {}
    for ev in journal_events:
        kind = JOURNAL_CAUSE_KINDS.get(ev.get("kind", ""))
        if kind is None:
            continue
        key = (kind, ev.get("executor", ""))
        journal_grouped.setdefault(key, []).append(ev)
    for (kind, eid), evs in sorted(journal_grouped.items()):
        existing = next(
            (c for c in causes
             if c["cause"] == kind and c["executor"] == eid), None)
        if existing is not None:
            existing["detail"]["journal_events"] = evs[-3:]
            continue
        last = evs[-1]
        hlc = last.get("hlc") or [0, 0]
        add_cause(
            kind,
            f"journal: {len(evs)}x {last.get('kind', '?')}"
            + (f" on {eid}" if eid else "")
            + f" (last hlc=({hlc[0]},{hlc[1]}))",
            executor=eid, source="journal",
            detail={"events": evs[-3:], "count": len(evs)},
        )

    causes.sort(key=lambda c: (-c["score"], c["cause"], c["executor"]))
    build_ms = (time.perf_counter() - t0) * 1000
    role = getattr(hub, "role", "driver") if hub is not None else "driver"
    reg.counter("diagnosis.builds", role=role).inc()
    reg.histogram("diagnosis.build_ms", role=role).observe(build_ms)

    return {
        "kind": "sparkrdma_diagnosis",
        "version": 1,
        "generated_wall_ms": int(clock() * 1000),
        "build_ms": round(build_ms, 3),
        "role": role,
        "breach": breach_d,
        "evidence": {
            "faults": faults,
            "breakdown_dominant": dominant or {},
            "gap_frames": gap_frames,
            "stragglers": straggler_ids,
            "open_circuits": open_circuits,
            "missed_heartbeats": missed,
            "quota_blocks": quota,
            "metastore": metastore,
            "journal": journal_events[-16:],
            "capacity": capacity,
            "trend": trend,
        },
        "causes": causes,
        "top_cause": causes[0] if causes else {},
    }


def render(diag: dict) -> str:
    """Human-readable CLI view of one diagnosis artifact."""
    out: List[str] = []
    breach = diag.get("breach", {})
    out.append("SLO diagnosis")
    out.append(
        f"  breach     {breach.get('objective', '?')} "
        f"[{breach.get('severity', '?')}] kind={breach.get('kind', '?')}"
    )
    if breach.get("executor"):
        out.append(f"  executor   {breach['executor']}")
    if breach.get("tenant"):
        out.append(f"  tenant     {breach['tenant']}")
    if breach.get("kind") not in (None, "liveness"):
        out.append(
            "  burn       "
            f"fast {breach.get('burn_fast', 0):.2f}"
            f"/{breach.get('burn_fast_short', 0):.2f} "
            f"slow {breach.get('burn_slow', 0):.2f}"
            f"/{breach.get('burn_slow_short', 0):.2f} "
            f"over {breach.get('windows', 0)} windows"
        )
    top = diag.get("top_cause") or {}
    if top:
        summary = (top.get("summary") or ["?"])[0]
        out.append(
            f"  top cause  {top.get('cause', '?')} "
            f"(score {top.get('score', 0)}): {summary}"
        )
    causes = diag.get("causes", [])
    if causes:
        out.append(f"  ranked causes ({len(causes)}):")
        for c in causes:
            mark = "*" if c.get("corroborated") else " "
            extra = ""
            if c.get("executor"):
                extra += f" executor={c['executor']}"
            if c.get("category"):
                extra += f" category={c['category']}"
            out.append(
                f"   {mark} {c.get('score', 0):>4}  "
                f"{c.get('cause', '?')}{extra}"
            )
            summary = (c.get("summary") or [""])[0]
            if summary:
                out.append(f"         {summary}")
    else:
        out.append("  no candidate causes (breach without evidence)")
    ev = diag.get("evidence", {})
    gaps = ev.get("gap_frames", [])
    if gaps:
        out.append("  profiler gap frames:")
        for g in gaps[:3]:
            out.append(f"    {g}")
    binding = (ev.get("capacity") or {}).get("binding") or {}
    if binding:
        out.append(
            f"  capacity   binding={binding.get('resource', '?')} "
            f"headroom={binding.get('headroom', 1.0):.0%}"
        )
    jev = ev.get("journal", [])
    if jev:
        out.append(f"  journal    {len(jev)} event(s) in breach window")
    return "\n".join(out)


# package-namespace alias (sparkrdma_tpu.obs already exports several
# render_* functions; the bare name stays for module-local callers)
render_diagnosis = render
